"""Kernel cost observatory (ISSUE 16): the measurement contract the fused-
kernel work (ROADMAP item 2) is accepted against.

Two planes:

1. **CostLedger** — per-batch STRUCTURAL device-cost counters folded O(1)
   per micro-batch from every dispatch site (engine single-corpus, engine
   sharded, native ``_dispatch``, mesh shard-steps, and the host/brownout/
   degrade CPU evals).  Counts the things wall clock cannot swing:
   device-computation launches (the number item 2 must drive to 1 per
   batch), H2D bytes (fused staging buffer / per-operand upload sizes —
   snapshot upload traffic stays on the PR 8 ``delta/full_upload_bytes``
   counters so the two planes compose instead of double-counting), D2H
   bytes (the PR 3 bitpacked ``[pad, W]`` readback), pad waste (padded −
   real rows, plus eff-column slack), and the dedup/cache-avoided rows
   that never shipped.  The ledger is PROCESS-WIDE like /metrics: every
   engine and frontend in the process folds into the same lanes
   ("engine", "host", "mesh", "native").

2. **CostModel** — per-component static analysis at reconcile: at each
   snapshot swap, ``lower().compile().cost_analysis()`` of the serving
   kernel entry points at a representative (pad, eff) shape → modeled
   FLOPs / bytes-accessed per padded row, recorded per generation.  A
   reconcile whose modeled per-row cost regresses ≥2× vs the previous
   generation raises a ``cost-regression`` flight-recorder anomaly —
   ADVISORY, never rejects the swap (modeled cost compares generations,
   not wall clock; see docs/performance.md "Kernel cost model").
   Analyses are memoized process-wide by (entry, shape, params
   fingerprint): an unchanged-shape reconcile pays a dict hit, not an
   XLA compile.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..utils import metrics as metrics_mod

log = logging.getLogger("authorino-tpu.kernel-cost")

LANES = ("engine", "host", "mesh", "native")

# modeled per-row cost must grow by this factor generation-over-generation
# to count as a regression (2x: a pad-bucket step or an added operand lane
# never doubles per-row FLOPs by itself — a kernel-structure change does)
REGRESSION_FACTOR = 2.0

_FIELDS = (
    "batches", "launches", "zero_launch_batches", "rows", "device_rows",
    "h2d_bytes", "d2h_bytes", "pad_rows", "pad_waste_rows",
    "eff_slack_cols", "dedup_avoided_rows", "cache_avoided_rows",
)


class _LaneCost:
    __slots__ = _FIELDS

    def __init__(self) -> None:
        for f in _FIELDS:
            setattr(self, f, 0)

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {f: int(getattr(self, f)) for f in _FIELDS}
        if self.batches:
            d["launches_per_batch"] = round(self.launches / self.batches, 4)
        if self.device_rows:
            d["h2d_bytes_per_device_row"] = round(
                self.h2d_bytes / self.device_rows, 2)
        if self.pad_rows:
            d["d2h_bytes_per_pad_row"] = round(
                self.d2h_bytes / self.pad_rows, 2)
            d["pad_occupancy"] = round(self.device_rows / self.pad_rows, 4)
        return d


class CostLedger:
    """Process-wide structural device-cost counters, one fold per batch."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._lanes: Dict[str, _LaneCost] = {}

    def observe(self, lane: str, *, rows: int, device_rows: int = 0,
                launches: int = 0, h2d_bytes: int = 0, d2h_bytes: int = 0,
                pad_rows: int = 0, eff_slack_cols: int = 0,
                dedup_avoided_rows: int = 0,
                cache_avoided_rows: int = 0) -> None:
        """Fold one batch: ``rows`` real requests in the cut, of which
        ``device_rows`` actually shipped (``pad_rows`` after padding) in
        ``launches`` device calls.  Host/degrade evals and fully cache/
        dedup-resolved cuts fold with launches=0 and zero byte counts.
        The mesh lane folds its batch here with launches=0 and counts the
        actual shard-step launches at the dispatch site instead
        (``observe_launch``) — failover re-dispatches then show up as
        launches_per_batch > 1 rather than vanishing."""
        pad_waste = max(0, pad_rows - device_rows)
        with self._lock:
            lc = self._lanes.get(lane)
            if lc is None:
                lc = self._lanes[lane] = _LaneCost()
            lc.batches += 1
            lc.launches += launches
            if launches == 0 and device_rows == 0:
                lc.zero_launch_batches += 1
            lc.rows += rows
            lc.device_rows += device_rows
            lc.h2d_bytes += h2d_bytes
            lc.d2h_bytes += d2h_bytes
            lc.pad_rows += pad_rows
            lc.pad_waste_rows += pad_waste
            lc.eff_slack_cols += eff_slack_cols
            lc.dedup_avoided_rows += dedup_avoided_rows
            lc.cache_avoided_rows += cache_avoided_rows
        metrics_mod.observe_kernel_cost(
            lane, launches, h2d_bytes, d2h_bytes, pad_waste)

    def observe_launch(self, lane: str, launches: int = 1,
                       h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
        """Count device launches + bytes at the dispatch site WITHOUT
        folding a batch (the mesh shard-step hook: the batch itself folds
        once at the cut via ``observe``)."""
        with self._lock:
            lc = self._lanes.get(lane)
            if lc is None:
                lc = self._lanes[lane] = _LaneCost()
            lc.launches += launches
            lc.h2d_bytes += h2d_bytes
            lc.d2h_bytes += d2h_bytes
        metrics_mod.observe_kernel_cost(lane, launches, h2d_bytes,
                                        d2h_bytes, 0)

    def snapshot(self, lane: str) -> Dict[str, Any]:
        """One lane's raw counters (zeros if the lane never folded) —
        tests delta two snapshots around a dispatch to pin exact counts."""
        with self._lock:
            lc = self._lanes.get(lane)
            return lc.to_json() if lc is not None else _LaneCost().to_json()

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            return {lane: lc.to_json()
                    for lane, lc in sorted(self._lanes.items())}

    def reset_for_tests(self) -> None:
        with self._lock:
            self._lanes.clear()


LEDGER = CostLedger()


# ---------------------------------------------------------------------------
# Static cost analysis at reconcile.
# ---------------------------------------------------------------------------

# (entry, pad, eff, params fingerprint) -> (flops, bytes_accessed).
# Process-wide on purpose: jax's AOT lowering cache makes a repeat
# lower().compile() ~1ms, but the memo keeps even that (and the throwaway
# operand build) off the reconcile path for unchanged shapes.
_ANALYSIS_MEMO: Dict[tuple, Tuple[float, float]] = {}


def params_fingerprint(params: Any) -> tuple:
    """Hashable (shape, dtype) tree fingerprint of a params pytree — the
    memo key axis that changes exactly when the compiled corpus's operand
    shapes change (recompiles that keep shapes hit the memo)."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    return tuple(
        (tuple(getattr(a, "shape", ())), str(getattr(a, "dtype", type(a))))
        for a in leaves)


def _cost_numbers(compiled) -> Tuple[float, float]:
    """(flops, bytes accessed) out of a Compiled.cost_analysis() result,
    tolerant of the backend returning a dict OR a list of per-module
    dicts, with missing keys reading 0 (CPU backends fill both today)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not hasattr(ca, "get"):
        return 0.0, 0.0
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0))


def modeled_entry_cost(entry: str, fn, args: tuple, pad: int,
                       fingerprint: tuple,
                       eff: int = 0) -> Optional[Dict[str, Any]]:
    """XLA-modeled cost of one jit entry point at one (pad, eff) shape:
    {flops, bytes_accessed, flops_per_row, bytes_per_row, pad, eff}.
    Memoized by (entry, pad, eff, fingerprint); returns None when the
    backend cannot lower/analyze (advisory plane — never raises)."""
    key = (entry, pad, eff, fingerprint)
    hit = _ANALYSIS_MEMO.get(key)
    if hit is None:
        try:
            flops, bytes_acc = _cost_numbers(fn.lower(*args).compile())
        except Exception as e:  # pragma: no cover - backend-dependent
            log.debug("cost_analysis unavailable for %s: %r", entry, e)
            return None
        _ANALYSIS_MEMO[key] = hit = (flops, bytes_acc)
    flops, bytes_acc = hit
    return {
        "entry": entry, "pad": pad, "eff": eff,
        "flops": flops, "bytes_accessed": bytes_acc,
        "flops_per_row": round(flops / pad, 2) if pad else 0.0,
        "bytes_per_row": round(bytes_acc / pad, 2) if pad else 0.0,
    }


def _bitpacked_zero_args(policy, params, pad: int, eff: int) -> tuple:
    """Throwaway zero operands for eval_bitpacked_jit at one (pad, eff)
    bucket — the _warm_one recipe, shapes only (PR 14 operand tail rides
    on the params' structural Nones)."""
    import jax.numpy as jnp
    import numpy as np

    from ..compiler.intern import PAD
    from ..compiler.pack import wire_dtype

    dt = wire_dtype(policy)
    A, M, K = policy.n_attrs, policy.n_member_attrs, policy.members_k
    C, NB = policy.n_cpu_leaves, max(policy.n_byte_attrs, 1)
    return (
        params,
        jnp.asarray(np.zeros((pad, A), dtype=dt)),
        jnp.asarray(np.full((pad, M, K), PAD, dtype=dt)),
        jnp.asarray(np.zeros((pad, C), dtype=bool)),
        jnp.asarray(np.zeros((pad,), dtype=np.int32)),
        jnp.asarray(np.zeros((pad, NB, eff), dtype=np.uint8)) if eff else None,
        jnp.asarray(np.zeros((pad, NB), dtype=bool)) if eff else None,
    )


def entry_points(policy=None, sharded=None) -> List[Dict[str, Any]]:
    """Enumerate the jit entry points a serving snapshot can dispatch
    through, with the operand lanes each one stages — the warm-grid audit
    surface (ISSUE 16 satellite: PR 1's grid predates the bitpacked/fused
    readback and the PR 14 relations operands; this list is what tests
    pin so the surface cannot drift again)."""
    base = ["attrs_val", "members_c", "cpu_dense", "config_id"]

    def _operands(pol) -> List[str]:
        ops = list(base)
        if pol is not None:
            if getattr(pol, "n_byte_attrs", 0):
                ops += ["attr_bytes", "byte_ovf"]  # device regex (DFA) lane
            if getattr(pol, "n_num_attrs", 0):
                ops += ["attrs_num", "num_valid"]  # PR 14 numeric lane
            if getattr(pol, "rel_bits", None) is not None:
                ops += ["rel_rows"]                # PR 14 relation lane
            if getattr(pol, "ovf_assist", False):
                ops += ["member_ovf"]              # PR 14 overflow assist
        return ops

    out: List[Dict[str, Any]] = []
    if sharded is not None:
        p0 = sharded.shards[0]
        out.append({
            "entry": "sharded_step",
            "kind": "collective (one launch per shard-step, psum-merged)",
            "operands": _operands(p0),
            "n_shards": int(sharded.n_shards),
        })
    elif policy is not None:
        ops = _operands(policy)
        out.append({
            "entry": "eval_bitpacked",
            "kind": "single-corpus bitpacked readback [pad, W] uint8",
            "operands": ops,
        })
        out.append({
            "entry": "eval_fused",
            "kind": "single fused H2D staging buffer (same compute as "
                    "eval_bitpacked; per-operand fallback when the "
                    "backend bitcast probe fails)",
            "operands": ops,
        })
        out.append({
            "entry": "fused_kernel",
            "kind": "one-launch mega-kernel (ISSUE 17): Pallas on TPU, "
                    "interpret-mode Pallas on CPU, single-jit lax "
                    "fallback; every lane + circuit + in-kernel bitpack "
                    "in one executable, armed by --kernel-lane fused",
            "operands": ops,
        })
    # --kernel-lane auto provenance (ISSUE 18 satellite): the last auto
    # resolution (lane armed + the device platforms consulted) rides the
    # dispatchable entries as a FIELD — the entry list itself is a pinned
    # audit surface and must not grow phantom entry points
    try:
        from ..ops.pattern_eval import last_auto_decision

        dec = last_auto_decision()
    except Exception:  # pragma: no cover - import cycle hygiene
        dec = None
    if dec is not None:
        for e in out:
            if e["entry"] in ("fused_kernel", "sharded_step"):
                e["kernel_lane_auto"] = dec
    return out


class CostModel:
    """Per-component (engine / native frontend) modeled-cost lineage:
    one record per snapshot generation, compared against the previous
    one at reconcile time."""

    HISTORY = 8

    def __init__(self, component: str) -> None:
        self.component = component
        self._lock = threading.Lock()
        self._history: List[Dict[str, Any]] = []

    # -- recording ------------------------------------------------------
    def analyze(self, generation: int, *, policy=None, params=None,
                sharded=None, pad: int = 16, recorder=None) -> Dict[str, Any]:
        """Model the serving snapshot's kernel cost and diff it against
        the previous generation.  Advisory end to end: any failure
        degrades to an empty record, never blocks the swap."""
        with self._lock:
            if self._history and \
                    self._history[-1]["generation"] == int(generation):
                # canary promote re-installs the same generation: one
                # record per generation, not one per install
                return self._history[-1]
        entries: Dict[str, Dict[str, Any]] = {}
        try:
            entries = self._model_entries(policy=policy, params=params,
                                          sharded=sharded, pad=pad)
        except Exception:  # pragma: no cover - advisory plane
            log.exception("kernel cost analysis failed (advisory)")
        rec: Dict[str, Any] = {"generation": int(generation),
                               "entries": entries, "regressions": []}
        with self._lock:
            prev = self._history[-1] if self._history else None
            if prev is not None:
                rec["regressions"] = self._diff(prev, rec)
            self._history.append(rec)
            del self._history[:-self.HISTORY]
        for name, e in entries.items():
            metrics_mod.kernel_modeled_flops_per_row.labels(name).set(
                e["flops_per_row"])
        if rec["regressions"] and recorder is not None:
            try:
                recorder.record(
                    "cost-regression", lane=self.component,
                    detail={"generation": int(generation),
                            "regressions": rec["regressions"]},
                    anomaly=True)
            except Exception:  # pragma: no cover
                log.exception("cost-regression record failed")
        return rec

    def _model_entries(self, *, policy, params, sharded,
                       pad: int) -> Dict[str, Dict[str, Any]]:
        if sharded is not None:
            # the mesh step's shard_map lowering is mesh-bound state; model
            # the per-shard compute via the stacked single-device kernel
            # shapes instead (same per-row compute, collective excluded)
            return {}
        if policy is None or params is None:
            return {}
        from ..compiler.compile import DFA_VALUE_BYTES
        from ..ops.pattern_eval import eval_bitpacked_jit

        has_dfa = params.get("dfa_tables") is not None
        eff = DFA_VALUE_BYTES if has_dfa else 0
        fp = params_fingerprint(params)
        args = _bitpacked_zero_args(policy, params, pad, eff)
        cost = modeled_entry_cost("eval_bitpacked", eval_bitpacked_jit,
                                  args, pad, fp, eff=eff)
        return {"eval_bitpacked": cost} if cost is not None else {}

    @staticmethod
    def _diff(prev: Dict[str, Any], cur: Dict[str, Any]) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for name, e in cur["entries"].items():
            pe = prev["entries"].get(name)
            if pe is None:
                continue
            for axis in ("flops_per_row", "bytes_per_row"):
                base, now = pe.get(axis, 0.0), e.get(axis, 0.0)
                if base > 0 and now >= REGRESSION_FACTOR * base:
                    out.append({
                        "entry": name, "axis": axis,
                        "previous": base, "current": now,
                        "ratio": round(now / base, 2),
                        "previous_generation": prev["generation"],
                    })
        return out

    # -- surfaces -------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            cur = self._history[-1] if self._history else None
            regressed = [r for rec in self._history
                         for r in rec["regressions"]]
            return {
                "component": self.component,
                "generations_analyzed": len(self._history),
                "current": cur,
                "regressions_seen": len(regressed),
                "last_regression": regressed[-1] if regressed else None,
            }
