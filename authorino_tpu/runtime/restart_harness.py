"""Kill harness: SIGKILL a serving process, prove the warm restart (ISSUE 20).

The crash-consistency claim is end-to-end: a process killed with SIGKILL at
an arbitrary instant — mid-reconcile, mid-capture-rotation, mid-publish —
must restart from its ``--state-dir`` alone (NO control plane) and serve the
exact allow/deny table the killed process was serving, with every on-disk
artifact either old-valid or new-valid (readers reject corruption typed,
never crash, never serve a partial state).  This module is both a runnable
harness and a library the tests drive as a subprocess:

  serve    build a deterministic engine + StatePlane, precompute the
           allow/deny table for a FIXED cycle of config variants (keyed by
           the snapshot's fingerprint digest, so the restarted process can
           find the row matching WHATEVER generation survived on disk),
           touch the ready file, then loop {reconcile → publish, capture
           rotation, hot-set export} forever until killed.  ``--stress``
           biases the loop so the kill lands mid-reconcile or mid-rotation
           with high probability.
  restart  fresh engine + StatePlane.warm_start() against the same state
           dir, re-submit the probe docs, compare verdicts bit-exact to the
           precomputed table row, and validate EVERY artifact on disk
           (snapshot blobs, MANIFEST, HOTSET, capture segments, corpus
           containers: loadable or typed rejection).  Emits a JSON report;
           exit 0 iff recovered + verdicts match + zero unhandled failures.

Usage (tests/test_warm_restart.py wires this up; also runnable by hand):

  python -m authorino_tpu.runtime.restart_harness serve \
      --state-dir /tmp/sd --table /tmp/sd/TABLE.json --ready /tmp/sd/READY \
      --stress reconcile
  kill -9 <pid>      # at any instant after READY appears
  python -m authorino_tpu.runtime.restart_harness restart \
      --state-dir /tmp/sd --table /tmp/sd/TABLE.json --report /tmp/rep.json
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import hashlib
import json
import os
import sys
import time
from typing import Any, Dict, List

N_PROBES = 24
VARIANT_SEED = 73


def _corpus(n_configs: int, variant: int):
    """Deterministic corpus; ``variant`` folds into one rule constant per
    config so each variant compiles to a distinct fingerprint set (and a
    distinct allow/deny table) while keeping identical tensor shapes."""
    from ..compiler import ConfigRules
    from ..expressions import All, Any_, Operator, Pattern

    cfgs = []
    for i in range(n_configs):
        rule = All(
            Pattern("request.method", Operator.EQ, ["GET", "POST"][i % 2]),
            Any_(
                Pattern("auth.identity.org", Operator.EQ,
                        f"org-{i}-v{variant % 3}"),
                Pattern("auth.identity.roles", Operator.INCL, f"role-{i}"),
                Pattern("request.url_path", Operator.MATCHES,
                        rf"^/svc-{i % 3}/"),
            ),
        )
        cfgs.append(ConfigRules(name=f"cfg-{i}", evaluators=[(None, rule)]))
    return cfgs


def _probe_docs(n_configs: int):
    """(doc, config) probes covering allow AND deny rows for every variant:
    org matches variant 0 only ⇒ different variants answer differently."""
    probes = []
    for i in range(N_PROBES):
        c = i % n_configs
        probes.append((
            {"request": {"method": ["GET", "POST"][c % 2],
                         "url_path": f"/svc-{c % 3}/x" if i % 3 else "/other"},
             "auth": {"identity": {"org": f"org-{c}-v0",
                                   "roles": [f"role-{c}"] if i % 2 else []}}},
            f"cfg-{c}",
        ))
    return probes


def table_key(engine) -> str:
    """Content key of the SERVING snapshot: digest over its sorted
    per-config fingerprints.  Generation-independent, so the restarted
    process can look up whichever variant survived the kill on disk."""
    fps = getattr(engine._snapshot, "fingerprints", None) or {}
    blob = json.dumps(sorted(fps.items())).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _verdicts(engine, probes) -> List[List[List[int]]]:
    import numpy as np

    async def all_probes():
        return await asyncio.gather(*[engine.submit(doc, name)
                                      for doc, name in probes])

    out = []
    for rule_res, skipped in _run(all_probes()):
        out.append([np.asarray(rule_res).astype(int).tolist(),
                    np.asarray(skipped).astype(int).tolist()])
    return out


def _build_engine(n_configs: int, variant: int):
    from . import EngineEntry, PolicyEngine

    engine = PolicyEngine(max_batch=max(8, n_configs), members_k=4,
                          mesh=None, strict_verify=True,
                          verdict_cache_size=4096, lane_select=False)
    engine.apply_snapshot(
        [EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
         for c in _corpus(n_configs, variant)])
    return engine


# ---------------------------------------------------------------------------
# serve: precompute the truth table, then loop until SIGKILLed
# ---------------------------------------------------------------------------


def cmd_serve(args) -> int:
    from ..replay.capture import write_segment
    from ..corpus.store import write_corpus
    from ..utils.atomicio import atomic_write_json
    from .state_plane import StatePlane

    probes = _probe_docs(args.configs)
    engine = _build_engine(args.configs, 0)
    plane = StatePlane(engine, args.state_dir, hotset_k=512,
                       hotset_s=3600.0)  # cadence driven by the loop below
    plane.start()  # attach publisher: every apply_snapshot persists

    # precompute the table: every variant the loop will ever serve, keyed
    # by fingerprint digest.  The incremental compiler makes variants 1..k
    # cheap (same shapes, one constant changed per config).
    table: Dict[str, Any] = {}
    entries = None
    for v in range(args.variants):
        if v:
            from . import EngineEntry

            entries = [EngineEntry(id=c.name, hosts=[c.name], runtime=None,
                                   rules=c)
                       for c in _corpus(args.configs, v)]
            engine.apply_snapshot(entries)
        table[table_key(engine)] = {
            "variant": v,
            "verdicts": _verdicts(engine, probes),
        }
    atomic_write_json(args.table, {"configs": args.configs,
                                   "variants": args.variants,
                                   "table": table},
                      artifact="harness-table", indent=1)
    # everything the restart needs is now durable: snapshot of the LAST
    # precomputed variant is published (attached publisher), table is on
    # disk.  Flush so READY truthfully means "killable from here on".
    plane.publisher.flush(timeout_s=10.0)
    plane.export_hotset_once()
    with open(args.ready, "w") as f:  # lint-ok: non-atomic-write -- sentinel
        f.write(str(os.getpid()))
    print(f"READY pid={os.getpid()}", flush=True)

    variants = [_corpus(args.configs, v) for v in range(args.variants)]
    cap_dir = os.path.join(args.state_dir, "captures")
    corp_dir = os.path.join(args.state_dir, "corpus")
    os.makedirs(cap_dir, exist_ok=True)
    os.makedirs(corp_dir, exist_ok=True)
    from . import EngineEntry

    i = 0
    while True:
        i += 1
        v = i % args.variants
        reps = 4 if args.stress == "reconcile" else 1
        for _ in range(reps):
            engine.apply_snapshot(
                [EngineEntry(id=c.name, hosts=[c.name], runtime=None,
                             rules=c) for c in variants[v]])
            plane.publisher.flush(timeout_s=5.0)
        _verdicts(engine, probes)  # keep the verdict cache warm
        reps = 8 if args.stress == "capture" else 1
        for r in range(reps):
            rows = [{"authconfig": f"cfg-{j}", "doc": {"i": i, "r": r},
                     "rule_index": j, "lane": "device",
                     "verdict": bool(j % 2)} for j in range(16)]
            write_segment(os.path.join(cap_dir, f"seg-{i % 4}.atpucap"),
                          rows, meta={"iter": i})
            write_corpus(os.path.join(corp_dir, f"c-{i % 4}.atpucorp"),
                         rows, meta={"iter": i})
        plane.export_hotset_once()
    return 0  # unreachable: the harness dies by signal


# ---------------------------------------------------------------------------
# restart: warm start from disk alone, verify bit-exact + artifact validity
# ---------------------------------------------------------------------------


def _validate_artifacts(state_dir: str) -> Dict[str, Any]:
    """Every on-disk artifact must be loadable or rejected TYPED.  Any
    other exception is an unhandled crash-consistency failure."""
    from ..replay.capture import CaptureFormatError, read_segment
    from ..corpus.store import CorpusFormatError, read_corpus_file
    from ..snapshots.distribution import (SnapshotLoadError,
                                          load_hotset, load_snapshot_blob)

    out: Dict[str, Any] = {"valid": 0, "rejected_typed": 0, "tmp_debris": 0,
                           "unhandled": []}

    def check(path, loader, typed):
        try:
            loader(path)
            out["valid"] += 1
        except typed:
            out["rejected_typed"] += 1
        except Exception as e:  # crash-consistency violation
            out["unhandled"].append(f"{path}: {type(e).__name__}: {e}")

    def load_blob(path):
        with open(path, "rb") as f:
            load_snapshot_blob(f.read())

    for p in sorted(glob.glob(os.path.join(state_dir, "*.atpusnap"))):
        check(p, load_blob, SnapshotLoadError)
    for p in sorted(glob.glob(os.path.join(state_dir, "captures", "*"))):
        if p.endswith(".tmp"):
            out["tmp_debris"] += 1
            continue
        check(p, read_segment, CaptureFormatError)
    for p in sorted(glob.glob(os.path.join(state_dir, "corpus", "*"))):
        if p.endswith(".tmp"):
            out["tmp_debris"] += 1
            continue
        check(p, read_corpus_file, CorpusFormatError)
    # manifest + hotset: their readers are total (typed error / None)
    try:
        with open(os.path.join(state_dir, "MANIFEST.json")) as f:
            json.load(f)
        out["manifest"] = "valid"
    except FileNotFoundError:
        out["manifest"] = "missing"
    except ValueError:
        out["manifest"] = "rejected_typed"
    try:
        out["hotset"] = ("valid" if load_hotset(state_dir) is not None
                         else "none")
    except Exception as e:
        out["unhandled"].append(f"HOTSET.json: {type(e).__name__}: {e}")
    out["tmp_debris"] += len(glob.glob(os.path.join(state_dir, "*.tmp")))
    return out


def cmd_restart(args) -> int:
    from . import PolicyEngine
    from ..utils.atomicio import atomic_write_json
    from .state_plane import StatePlane

    with open(args.table) as f:
        spec = json.load(f)
    probes = _probe_docs(int(spec["configs"]))

    t0 = time.monotonic()
    engine = PolicyEngine(max_batch=max(8, int(spec["configs"])),
                          members_k=4, mesh=None, strict_verify=True,
                          verdict_cache_size=4096, lane_select=False)
    plane = StatePlane(engine, args.state_dir)
    summary = plane.warm_start()  # NO control plane anywhere in this mode
    recovered = summary.get("snapshot") in ("ok", "stale")

    report: Dict[str, Any] = {
        "recovered": recovered,
        "warm_start": summary,
        "warm_start_wall_s": round(time.monotonic() - t0, 4),
    }
    verdicts_match = False
    if recovered:
        key = table_key(engine)
        row = spec["table"].get(key)
        report["table_key"] = key
        report["table_hit"] = row is not None
        if row is not None:
            report["variant"] = row["variant"]
            got = _verdicts(engine, probes)
            verdicts_match = got == row["verdicts"]
            if not verdicts_match:
                report["mismatch"] = [i for i, (g, w) in
                                      enumerate(zip(got, row["verdicts"]))
                                      if g != w]
    report["verdicts_match"] = verdicts_match
    report["artifacts"] = _validate_artifacts(args.state_dir)
    ok = (recovered and verdicts_match
          and not report["artifacts"]["unhandled"])
    report["ok"] = ok
    atomic_write_json(args.report, report, artifact="harness-report",
                      indent=1)
    print(json.dumps(report), flush=True)
    return 0 if ok else 1


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m authorino_tpu.runtime.restart_harness",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("serve", help="serve + churn until SIGKILLed")
    s.add_argument("--state-dir", required=True)
    s.add_argument("--table", required=True)
    s.add_argument("--ready", required=True)
    s.add_argument("--configs", type=int, default=8)
    s.add_argument("--variants", type=int, default=3)
    s.add_argument("--stress", choices=["reconcile", "capture"],
                   default="reconcile")
    r = sub.add_parser("restart", help="warm start from disk + verify")
    r.add_argument("--state-dir", required=True)
    r.add_argument("--table", required=True)
    r.add_argument("--report", required=True)
    args = ap.parse_args(argv)
    if args.cmd == "serve":
        return cmd_serve(args)
    return cmd_restart(args)


if __name__ == "__main__":
    sys.exit(main())
