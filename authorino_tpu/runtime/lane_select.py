"""Cost-model lane selection + speculative dual-dispatch (ISSUE 12).

PR 7 built an exact host twin of the kernel (the engine's expression
oracle; the native frontend's CPU-backend kernel) and wired it into both
lanes — but only as overload *brownout*.  Under light load the fast exact
lane sat idle while every interactive request paid the device H2D/D2H
round trip: p50 ≈ one device RTT, the floor every bench round since r01
shows.  This module promotes the host twin to a first-class serving lane:

``LaneCostModel`` — EWMAs of everything the decision needs, fed from both
lanes' completion paths (allocation-free, GIL-atomic reads on the hot
path):

  - host-lane service time per ROW (observe_host: each host decision
    batch folds duration/rows);
  - device round trip per batch (observe_device — the same EWMA family
    the deadline shedder uses);
  - queue depth and batch occupancy at completion (the congestion terms:
    a deep queue means a device dispatch waits behind in-flight work);
  - per-lane SLO burn fractions (observe_slo — PR 9's tracker feeds the
    same per-batch bad counts here), so selection biases toward the lane
    that is NOT burning budget.

``LaneSelector`` — the per-batch-cut decision.  The law::

    host_cost(n)   = host_row_s × n                     (× burn bias)
    device_cost(n) = device_rtt × (1 + inflight/window) (× burn bias,
                     × mesh penalty when devices are down)

    pick HOST when host_cost < device_cost AND n ≤ host_max_rows AND the
    host lane has concurrency headroom; DEVICE otherwise.

Under light load n is small, host_cost is microseconds-to-milliseconds
and the host lane wins; as load grows the cut grows, host_cost crosses
the RTT and the device wins with full pads — throughput is preserved by
construction.  Requests whose propagated deadline lands inside the device
cost but outside the host cost are rescued onto the host lane even when
the cut itself rides the device (the latency-critical head).

``Speculation`` — the first-wins token for dual-dispatch while a lane
breaker is HALF-OPEN: the probe batch is dispatched to BOTH lanes, the
first completion resolves the futures, the loser's work is ignored
(verdicts are bit-identical by PR 6's certification, so the race is safe
— and the device half still reports its outcome to the breaker, which is
the whole point of the probe).  ``claim`` is a one-shot compare-and-set:
exactly one lane ever resolves, SLO burns once, provenance folds once.

Brownout (overload spill, PR 7) and lane selection (latency choice) share
the host twin but have distinct triggers and distinct counters: brownout
engages only when the device window is saturated; lane selection engages
whenever the host lane is simply FASTER.  See docs/performance.md "Lane
selection" and docs/robustness.md "Overload & brownout".
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..utils import metrics as metrics_mod

__all__ = ["LaneCostModel", "LaneSelector", "Speculation",
           "HOST", "DEVICE"]

HOST, DEVICE = "host", "device"

# decision reasons (the `reason` label of auth_server_lane_decisions_total)
R_COST = "cost-model"          # host_cost(n) beat device_cost(n)
R_DEADLINE = "deadline"        # latency-critical head rescued host-side
R_SPECULATIVE = "speculative"  # dual-dispatch twin (breaker half-open)
R_BATCH = "batch"              # device: the cut is batch-shaped work
R_HOST_BUSY = "host-busy"      # device: host lane at its concurrency cap
R_DISABLED = "disabled"        # device: selection off / lane unavailable
R_BURN = "slo-burn"            # the burn bias flipped the raw cost verdict
R_EXPLORE = "explore"          # device: periodic RTT-freshness probe

# cold-start host estimate: optimistic but conservative against a real
# device RTT (~100ms link on the reference deployment, ~1ms local): the
# first host decision measures the truth and the EWMA takes over.
_HOST_ROW_COLD_S = 100e-6
# EWMA smoothing (matches the engine's device EWMA: 0.8 old / 0.2 new)
_ALPHA = 0.2
# per-lane burn windows decay on this horizon (seconds)
_BURN_DECAY_S = 30.0


class LaneCostModel:
    """Shared cost state for one serving lane pair (host twin + device).

    Thread-safe: feeds arrive from encode workers, completer threads and
    host-lane worker threads; decision-time reads are GIL-atomic floats.
    """

    def __init__(self, lane: str):
        self.lane = lane
        self.host_row_s = 0.0       # EWMA seconds per host-decided row
        self.device_rtt_s = 0.0     # EWMA device batch round trip
        self.depth_ewma = 0.0       # queue depth at batch completion
        self.occupancy_ewma = 0.0   # in-flight window occupancy fraction
        self.host_batches = 0
        self.device_batches = 0
        # mesh cost feed (ISSUE 12 / sharded_eval.cost_feed): >1.0 when
        # part of the mesh is down — the surviving devices carry the load,
        # so the effective device cost rises
        self.mesh_penalty = 1.0
        self._lock = threading.Lock()
        # per-lane decayed SLO burn counters: (total, bad) with exponential
        # decay — the bias signal, not an alerting surface (PR 9's
        # SloTracker stays the alerting surface)
        self._burn: Dict[str, list] = {HOST: [0.0, 0.0, 0.0],
                                       DEVICE: [0.0, 0.0, 0.0]}
        self._g_host = metrics_mod.lane_cost_ewma.labels(lane, HOST)
        self._g_device = metrics_mod.lane_cost_ewma.labels(lane, DEVICE)

    # -- feeds -------------------------------------------------------------

    def observe_host(self, dur_s: float, rows: int) -> None:
        """One host-lane batch decided: fold per-row service time."""
        if rows <= 0 or not (dur_s >= 0.0):
            return
        per_row = dur_s / rows
        self.host_row_s = (per_row if not self.host_row_s
                           else (1 - _ALPHA) * self.host_row_s
                           + _ALPHA * per_row)
        self.host_batches += 1
        self._g_host.set(self.host_row_s)

    def observe_device(self, rtt_s: float, rows: int, depth: int = 0,
                       inflight: int = 0, window: int = 1) -> None:
        """One device batch completed: fold its round trip plus the
        congestion terms (queue depth, window occupancy) at completion."""
        if not (rtt_s >= 0.0):
            return
        self.device_rtt_s = (rtt_s if not self.device_rtt_s
                             else (1 - _ALPHA) * self.device_rtt_s
                             + _ALPHA * rtt_s)
        self.depth_ewma = ((1 - _ALPHA) * self.depth_ewma
                           + _ALPHA * float(depth))
        occ = float(inflight) / float(max(1, window))
        self.occupancy_ewma = ((1 - _ALPHA) * self.occupancy_ewma
                               + _ALPHA * occ)
        self.device_batches += 1
        self._g_device.set(self.device_rtt_s)

    def observe_slo(self, which: str, n: int, n_bad: int,
                    now: Optional[float] = None) -> None:
        """Per-lane burn feed: ``n`` requests decided on ``which`` lane,
        ``n_bad`` of them over the SLO target (or errored).  Decayed so a
        recovered lane sheds its bad history within ~_BURN_DECAY_S."""
        if n <= 0:
            return
        now = time.monotonic() if now is None else now
        rec = self._burn.get(which)
        if rec is None:
            return
        with self._lock:
            total, bad, t_last = rec
            if t_last:
                decay = 0.5 ** ((now - t_last) / _BURN_DECAY_S)
                total *= decay
                bad *= decay
            rec[0] = total + n
            rec[1] = bad + n_bad
            rec[2] = now

    def burn_frac(self, which: str) -> float:
        rec = self._burn.get(which)
        if rec is None:
            return 0.0
        total, bad, _ = rec
        return (bad / total) if total >= 1.0 else 0.0

    # -- cost estimates ----------------------------------------------------

    def host_cost(self, n: int) -> float:
        """Expected seconds to answer ``n`` rows on the host twin."""
        per_row = self.host_row_s or _HOST_ROW_COLD_S
        return per_row * max(1, n)

    def device_cost(self, inflight: int = 0, window: int = 1) -> float:
        """Expected seconds for a device answer dispatched NOW: one round
        trip, inflated by window occupancy (a launch behind a full window
        waits out earlier completions) and the mesh penalty."""
        rtt = self.device_rtt_s
        if not rtt:
            return float("inf") if self.host_row_s else 0.0
        occ = float(inflight) / float(max(1, window))
        return rtt * (1.0 + occ) * self.mesh_penalty

    def burn_bias(self) -> float:
        """Multiplier > 1 applied to the DEVICE cost when the device lane
        is burning SLO budget faster than the host lane (and symmetrically
        < 1 when the host lane is the one burning).  Bounded to [0.5, 2]:
        the bias nudges a close call, it never overrides a 10x cost gap."""
        d = self.burn_frac(DEVICE) - self.burn_frac(HOST)
        return min(2.0, max(0.5, 1.0 + d))

    def min_service_s(self) -> float:
        """The fastest lane's expected service time for a small batch —
        the lane-aware admission floor (a deadline only the host lane can
        meet is NOT doomed once the host lane is first-class)."""
        host = self.host_cost(1)
        dev = self.device_rtt_s or host
        return min(host, dev)

    def to_json(self) -> Dict[str, Any]:
        return {
            "host_row_ewma_s": round(self.host_row_s, 9),
            "device_rtt_ewma_s": round(self.device_rtt_s, 6),
            "queue_depth_ewma": round(self.depth_ewma, 2),
            "occupancy_ewma": round(self.occupancy_ewma, 4),
            "mesh_penalty": round(self.mesh_penalty, 3),
            "host_batches": self.host_batches,
            "device_batches": self.device_batches,
            "burn_frac": {k: round(self.burn_frac(k), 4)
                          for k in (HOST, DEVICE)},
            "burn_bias": round(self.burn_bias(), 3),
        }


class Speculation:
    """First-wins token for one dual-dispatched batch.  ``claim(which)``
    is a one-shot compare-and-set: the first lane to claim resolves the
    futures and runs the request-level telemetry (SLO, admission service
    count, provenance fold); every later claimer gets False and must
    treat its verdicts as confirmation only.  The device half's breaker
    bookkeeping is NOT gated on winning — the probe's whole purpose is a
    breaker verdict, whoever answered the clients first."""

    __slots__ = ("lane", "t0", "_winner", "_lock")

    def __init__(self, lane: str):
        self.lane = lane
        self.t0 = time.monotonic()
        self._winner: Optional[str] = None
        self._lock = threading.Lock()

    def claim(self, which: str) -> bool:
        with self._lock:
            if self._winner is None:
                self._winner = which
                return True
            return False

    def acquire(self, which: str) -> bool:
        """Idempotent ownership check: True when ``which`` is (or just
        became) the winner.  A lane that already owns the batch — e.g. the
        device half re-entering through the retry/degrade path after its
        own finalize failed — keeps ownership instead of reading its own
        earlier claim as a loss."""
        with self._lock:
            if self._winner is None:
                self._winner = which
            return self._winner == which

    @property
    def winner(self) -> Optional[str]:
        return self._winner


class LaneSelector:
    """Per-batch-cut lane decision for one serving lane.

    ``decide`` runs under the caller's queue lock (engine) or on the
    dispatcher thread (native): no locks, no allocation — EWMA reads and
    a handful of float ops."""

    def __init__(self, lane: str, enabled: bool = True,
                 host_max_rows: int = 64, speculative: bool = True,
                 host_concurrency: int = 2, explore_every: int = 64,
                 cost: Optional[LaneCostModel] = None):
        self.lane = lane
        self.enabled = bool(enabled)
        self.host_max_rows = max(1, int(host_max_rows))
        self.speculative = bool(speculative)
        # RTT-freshness exploration: every Nth host-winning decision rides
        # the device anyway, so the device RTT EWMA cannot go stale during
        # a long host-only light-load regime (a device that got faster —
        # or slower — is re-measured within N cuts).  Cost: one RTT on
        # 1/N of light-load batches — p50 untouched, bounded p99 tail.
        # 0 disables exploration.
        self.explore_every = max(0, int(explore_every))
        self._host_streak = 0
        # concurrent host-lane batches are bounded: the host twin rescues
        # latency, it must not become an unbounded CPU amplifier (same
        # contract as the brownout bound)
        self.host_limit = max(1, int(host_concurrency))
        self.host_inflight = 0     # guarded by the caller's queue lock
        self.cost = cost if cost is not None else LaneCostModel(lane)
        self.decisions: Dict[str, int] = {}
        self.rows: Dict[str, int] = {HOST: 0, DEVICE: 0}
        self.speculative_outcomes: Dict[str, int] = {}
        self._children: Dict[Tuple[str, str], Any] = {}
        self._spec_children: Dict[str, Any] = {}

    # -- decision ----------------------------------------------------------

    def decide(self, n: int, inflight: int, window: int,
               host_inflight: Optional[int] = None) -> Tuple[str, str]:
        """(lane, reason) for a cut of ``n`` rows with ``inflight`` device
        batches riding a ``window``-deep in-flight window."""
        if not self.enabled:
            return DEVICE, R_DISABLED
        if n > self.host_max_rows:
            return DEVICE, R_BATCH
        hi = self.host_inflight if host_inflight is None else host_inflight
        if hi >= self.host_limit:
            return DEVICE, R_HOST_BUSY
        host = self.cost.host_cost(n)
        dev = self.cost.device_cost(inflight, window)
        bias = self.cost.burn_bias()
        if host < dev * bias:
            self._host_streak += 1
            if self.explore_every and \
                    self._host_streak % self.explore_every == 0:
                return DEVICE, R_EXPLORE
            return HOST, (R_COST if host < dev else R_BURN)
        self._host_streak = 0
        if host < dev:
            return DEVICE, R_BURN  # raw cost said host; burn bias said no
        return DEVICE, R_COST

    # -- accounting --------------------------------------------------------

    def admission_floor(self) -> float:
        """Lane-aware doomed-deadline floor for AdmissionController: the
        fastest lane's expected service time — but only while the host
        lane actually HAS headroom to take the work.  With the host
        concurrency cap saturated, the floor collapses to +inf so the
        min() in _doomed falls back to the device RTT: admission keeps
        providing backpressure instead of admitting tight-deadline work
        the host lane cannot rescue (it would just burn encode and shed
        at dispatch)."""
        if not self.enabled or self.host_inflight >= self.host_limit:
            return float("inf")
        return self.cost.min_service_s()

    def count(self, which: str, reason: str, n: int = 1) -> None:
        key = (which, reason)
        ch = self._children.get(key)
        if ch is None:
            ch = self._children[key] = metrics_mod.lane_decisions.labels(
                f"{self.lane}-{which}", reason)
        ch.inc(n)
        k = f"{which}:{reason}"
        self.decisions[k] = self.decisions.get(k, 0) + n

    def count_rows(self, which: str, n: int) -> None:
        """Requests actually SERVED per lane (the decision counter above is
        per batch-cut decision) — the bimodal bench block's split."""
        self.rows[which] = self.rows.get(which, 0) + n

    def count_speculative(self, outcome: str) -> None:
        ch = self._spec_children.get(outcome)
        if ch is None:
            ch = self._spec_children[outcome] = (
                metrics_mod.speculative_dispatch.labels(outcome))
        ch.inc()
        self.speculative_outcomes[outcome] = (
            self.speculative_outcomes.get(outcome, 0) + 1)

    def to_json(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "host_max_rows": self.host_max_rows,
            "speculative": self.speculative,
            "host_inflight": self.host_inflight,
            "host_concurrency_limit": self.host_limit,
            "decisions": dict(self.decisions),
            "rows": dict(self.rows),
            "speculative_outcomes": dict(self.speculative_outcomes),
            "cost": self.cost.to_json(),
        }
