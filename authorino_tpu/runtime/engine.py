"""PolicyEngine: the serving-time owner of the host index, the compiled rule
corpus (double-buffered, atomically swapped on reconcile) and the
micro-batching queue that dispatches (requests × rules) kernels to the
device.

This is the TPU-era replacement for the reference's per-request goroutine
evaluation (SURVEY.md §5 "communication backend"): the gRPC/HTTP frontend
stays on host CPU; Check() contexts are encoded and batched here; one jitted
kernel evaluates the batch against the whole corpus.  Reconcile-time
compilation is the analog of the reference's OPA precompile
(ref: pkg/evaluators/authorization/opa.go:141); the swap is the analog of
index Set (ref: controllers/auth_config_controller.go:605-636)."""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..authjson.wellknown import CheckRequestModel
from ..compiler.compile import CompiledPolicy, ConfigRules, compile_corpus
from ..compiler.encode import encode_batch
from ..evaluators.base import RuntimeAuthConfig
from ..index import HostIndex
from ..pipeline.pipeline import AuthPipeline, AuthResult
from ..utils import metrics as metrics_mod
from ..utils import tracing as tracing_mod
from ..utils.rpc import NOT_FOUND

__all__ = ["PolicyEngine", "EngineEntry"]


@dataclass
class EngineEntry:
    """One AuthConfig as the control plane hands it to the engine."""

    id: str                       # e.g. "namespace/name"
    hosts: List[str]
    runtime: RuntimeAuthConfig
    rules: Optional[ConfigRules] = None  # compilable pattern surface (may be None)


class _Snapshot:
    """Immutable compiled corpus + device params (double-buffered).

    With a multi-device mesh, the corpus compiles as a ShardedPolicyModel
    (rules axis tensor-parallel over 'mp', batch over 'dp') — the TPU-era
    successor of the reference's label-selector instance sharding
    (ref: controllers/label_selector.go:14-45)."""

    def __init__(self, entries: Sequence[EngineEntry], members_k: int = 16, mesh=None):
        from ..ops.pattern_eval import to_device

        self.by_id: Dict[str, EngineEntry] = {e.id: e for e in entries}
        rules = [e.rules for e in entries if e.rules is not None]
        self.policy: Optional[CompiledPolicy] = None
        self.params = None
        self.sharded = None
        if rules:
            if mesh is not None:
                from ..parallel import ShardedPolicyModel

                self.sharded = ShardedPolicyModel(rules, mesh, members_k=members_k)
            else:
                self.policy = compile_corpus(rules, members_k=members_k)
                self.params = to_device(self.policy)


@dataclass
class _Pending:
    doc: Any
    config_name: str
    future: asyncio.Future
    span: Any = None              # RequestSpan (DeviceBatch span links)
    t_enq: float = 0.0            # monotonic enqueue time (queue-wait hist)


class PolicyEngine:
    def __init__(
        self,
        max_batch: int = 256,
        max_delay_s: float = 0.0005,
        timeout_s: Optional[float] = None,
        members_k: int = 16,
        mesh: Any = "auto",
        max_fallback_per_batch: Optional[int] = None,
    ):
        """``mesh="auto"`` shards the rule corpus over all visible devices
        when more than one is present (dp × mp ShardedPolicyModel);
        ``mesh=None`` forces the single-corpus path; an explicit
        ``jax.sharding.Mesh`` pins the layout.

        ``max_fallback_per_batch`` bounds the per-batch host-oracle work for
        membership-overflow requests (an overload valve: beyond the cap,
        fallback requests are DENIED fail-closed and counted in
        auth_server_host_fallback_shed_total).  None = unbounded — safe by
        default, since the compiled-closure oracle costs ~2µs/request,
        cheaper than the reference's normal per-request path."""
        self.index: HostIndex[EngineEntry] = HostIndex()
        self.generation = 0  # bumped per apply_snapshot (gauge + /debug/vars)
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.timeout_s = timeout_s
        self.members_k = members_k
        self.max_fallback_per_batch = max_fallback_per_batch
        self._mesh = mesh
        self._snapshot: Optional[_Snapshot] = None
        self._swap_lock = threading.Lock()
        # micro-batch queues are PER event loop: the gRPC/HTTP servers and
        # the native frontend's slow lane may share one engine from
        # different loops, and asyncio futures/timers are loop-owned
        self._pending: Dict[Any, List[_Pending]] = {}
        self._flush_handles: Dict[Any, asyncio.TimerHandle] = {}
        self._swap_listeners: List[Any] = []

    # swap listeners: the native frontend rebuilds its C++ snapshot after
    # every corpus swap (runtime/native_frontend.py refresh)
    def add_swap_listener(self, cb) -> None:
        self._swap_listeners.append(cb)

    def remove_swap_listener(self, cb) -> None:
        if cb in self._swap_listeners:
            self._swap_listeners.remove(cb)

    def notify_swap_listeners(self) -> None:
        """Fire swap listeners without a corpus swap — used by the secret
        reconciler after in-place API-key/mTLS rotation, so the native
        frontend rebuilds its credential→plan variants
        (ref controllers/secret_controller.go:40-130 mutates evaluators in
        place; the fast lane's compiled view must follow)."""
        for cb in list(self._swap_listeners):
            cb()

    # ---- control plane ---------------------------------------------------

    def _resolve_mesh(self):
        if self._mesh == "auto":
            import jax

            from ..parallel import build_mesh

            self._mesh = build_mesh() if len(jax.devices()) > 1 else None
        return self._mesh

    def apply_snapshot(self, entries: Sequence[EngineEntry], override: bool = True) -> None:
        """Compile the new corpus off the serving path, then atomically swap
        snapshot + index (double buffering: in-flight batches keep the old
        params alive until their futures resolve)."""
        snap = _Snapshot(entries, members_k=self.members_k, mesh=self._resolve_mesh())
        new_index: HostIndex[EngineEntry] = HostIndex()
        for e in entries:
            for host in e.hosts:
                new_index.set(e.id, host, e, override=override)
        with self._swap_lock:
            self._snapshot = snap
            self.index = new_index
            self.generation += 1
            metrics_mod.snapshot_generation.labels("engine").set(self.generation)
        self.notify_swap_listeners()

    def snapshot_policy(self) -> Optional[CompiledPolicy]:
        snap = self._snapshot
        return snap.policy if snap else None

    def debug_vars(self) -> Dict[str, Any]:
        """JSON-safe live state for the /debug/vars endpoint: config
        generation, micro-batch queue depths per event loop, and the
        compiled snapshot's shape.  Read-only, GIL-atomic reads."""
        queues = {hex(id(loop)): len(q)
                  for loop, q in list(self._pending.items())}
        snap = self._snapshot
        out: Dict[str, Any] = {
            "generation": self.generation,
            "max_batch": self.max_batch,
            "max_delay_s": self.max_delay_s,
            "members_k": self.members_k,
            "queue_depth": sum(queues.values()),
            "queues": queues,
            "snapshot": None,
        }
        if snap is not None:
            policy = snap.policy
            out["snapshot"] = {
                "configs": len(snap.by_id),
                "sharded": snap.sharded is not None,
                "compiled_configs": (len(policy.config_ids)
                                     if policy is not None else 0),
                "n_attrs": int(getattr(policy, "n_attrs", 0)) if policy else 0,
                "n_leaves": int(getattr(policy, "n_leaves", 0)) if policy else 0,
            }
        return out

    # ---- request path ----------------------------------------------------

    def lookup(self, host: str) -> Optional[EngineEntry]:
        """Host lookup with :port-stripping retry
        (ref: pkg/service/auth.go:270-289)."""
        entry = self.index.get(host)
        if entry is None and ":" in host:
            entry = self.index.get(host.rsplit(":", 1)[0])
        return entry

    async def check(self, request: CheckRequestModel, span=None) -> AuthResult:
        """Full request-time flow (ref: pkg/service/auth.go:239-310)."""
        entry = self.lookup(request.host())
        if entry is None:
            return AuthResult(code=NOT_FOUND, message="Service not found")
        pipeline = AuthPipeline(request, entry.runtime, timeout=self.timeout_s, span=span)
        return await pipeline.evaluate()

    # ---- micro-batching verdicts ----------------------------------------

    def provider_for(self, config_name: str):
        """BatchedVerdictProvider bound to one compiled config — handed to
        PatternMatching evaluators at translate time."""

        async def provider(pipeline, evaluator_slot: int) -> Tuple[bool, bool]:
            rule, skipped = await self.submit(
                pipeline.authorization_json(), config_name, span=pipeline.span)
            e = evaluator_slot
            return bool(rule[e]), bool(skipped[e])

        return provider

    async def submit(self, doc: Any, config_name: str,
                     span: Any = None) -> Tuple[np.ndarray, np.ndarray]:
        """Queue one request for the next micro-batch; resolves to that
        request's per-evaluator (rule_results [E], skipped [E]).  ``span``
        (the request's RequestSpan, optional) lets the batch's DeviceBatch
        span link back to this request's trace."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        q = self._pending.get(loop)
        if q is None:
            q = self._pending[loop] = []
        q.append(_Pending(doc, config_name, fut, span=span,
                          t_enq=time.monotonic()))
        if len(q) >= self.max_batch:
            self._schedule_flush(loop)
        elif loop not in self._flush_handles:
            self._flush_handles[loop] = loop.call_later(
                self.max_delay_s, self._schedule_flush, loop)
        return await fut

    def _schedule_flush(self, loop) -> None:
        # always runs on `loop` (its call_later, or a submit running on it),
        # so the flush task + future completions stay loop-local
        handle = self._flush_handles.pop(loop, None)
        if handle is not None:
            handle.cancel()
        batch = self._pending.get(loop)
        if not batch:
            return
        self._pending[loop] = []
        asyncio.ensure_future(self._flush(batch))

    async def _flush(self, batch: List[_Pending]) -> None:
        snap = self._snapshot
        if snap is None or (snap.policy is None and snap.sharded is None):
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(RuntimeError("no compiled policy snapshot"))
            return
        try:
            own_rule, own_skipped, binfo = await asyncio.get_running_loop().run_in_executor(
                _dispatch_pool(), self._run_batch, snap, batch)
        except Exception as e:
            for p in batch:
                if not p.future.done():
                    p.future.set_exception(e)
            return
        if tracing_mod.tracing_active():
            # one DeviceBatch span per kernel launch, span-linked to every
            # constituent request's trace (export only: a link list build
            # per batch, nothing per request)
            links = [(p.span.trace_id, p.span.span_id) for p in batch
                     if p.span is not None and getattr(p.span, "sampled", True)]
            if links:
                tracing_mod.export_device_batch_span(
                    binfo["batch_size"], binfo["pad"], binfo["eff"], links,
                    binfo["start_ns"], binfo["duration_s"])
        for i, p in enumerate(batch):
            if not p.future.done():
                p.future.set_result((own_rule[i], own_skipped[i]))

    def _run_batch(self, snap: _Snapshot, batch: List[_Pending]):
        """Returns (own_rule [B,E], own_skipped [B,E], batch-info dict) —
        the info dict feeds the DeviceBatch span and carries no tensors."""
        n = len(batch)
        pad = _bucket(n)
        t0 = time.monotonic()
        # batch[0] is the first enqueued: its wait bounds every member's
        wait_s = (t0 - batch[0].t_enq) if batch[0].t_enq else None
        binfo = {"batch_size": n, "pad": pad, "eff": 0,
                 "start_ns": time.time_ns(), "duration_s": 0.0}
        if snap.sharded is not None:
            out = snap.sharded.run_full(
                [p.doc for p in batch],
                [p.config_name for p in batch],
                batch_pad=pad,
                max_fallback=self.max_fallback_per_batch,
            )
            # encode+dispatch+readback wall (run_full observes its own
            # per-batch fallback count into auth_server_batch_host_fallback)
            binfo["duration_s"] = time.monotonic() - t0
            metrics_mod.observe_batch("engine", n, pad, wait_s,
                                      binfo["duration_s"])
            return out[0], out[1], binfo
        from ..compiler.pack import pack_batch
        from ..ops.pattern_eval import eval_packed_jit
        import jax.numpy as jnp

        policy = snap.policy
        rows = [policy.config_ids[p.config_name] for p in batch]
        enc = encode_batch(policy, [p.doc for p in batch], rows, batch_pad=pad)
        db = pack_batch(policy, enc)
        has_dfa = snap.params["dfa_tables"] is not None
        binfo["eff"] = int(db.attr_bytes.shape[-1]) if has_dfa else 0
        # span window = the device call itself (start_ns re-stamped here):
        # encode/pack are host work that precedes the launch
        binfo["start_ns"] = time.time_ns()
        t_dev = time.monotonic()
        packed = np.asarray(eval_packed_jit(
            snap.params,
            jnp.asarray(db.attrs_val),
            jnp.asarray(db.members_c),
            jnp.asarray(db.cpu_dense),
            jnp.asarray(db.config_id),
            jnp.asarray(db.attr_bytes) if has_dfa else None,
            jnp.asarray(db.byte_ovf) if has_dfa else None,
        ))
        binfo["duration_s"] = time.monotonic() - t_dev
        E = policy.eval_rule.shape[1]
        own_rule = packed[:, 1:1 + E].copy()
        own_skipped = packed[:, 1 + E:1 + 2 * E].copy()
        n_fallback = int(np.count_nonzero(db.host_fallback[:n]))
        if n_fallback:
            # compact payload was lossy for these rows (membership overflow):
            # exact re-decision on host via the expression oracle, bounded
            # by the fallback cap (beyond it: deny fail-closed + counter)
            from ..models.policy_model import apply_host_fallback, host_results

            apply_host_fallback(
                lambda r: host_results(policy, batch[r].doc, rows[r])[1:],
                np.nonzero(db.host_fallback[: len(batch)])[0],
                own_rule, own_skipped, self.max_fallback_per_batch,
            )
        metrics_mod.observe_batch("engine", n, pad, wait_s,
                                  binfo["duration_s"], n_fallback)
        return own_rule, own_skipped, binfo


# dispatch pool, shared process-wide: asyncio.to_thread rides the loop's
# default executor (≈5 workers on a 1-CPU host), which caps the number of
# micro-batches in flight — on a device behind a long link that cap IS the
# slow-path throughput ceiling (in-flight batches × batch ≈ throughput ×
# RTT).  One shared pool: engines are created freely (tests, reconciles)
# and per-engine pools with no shutdown path would leak threads.
_DISPATCH_POOL = None
_DISPATCH_POOL_LOCK = threading.Lock()


def _dispatch_pool():
    global _DISPATCH_POOL
    if _DISPATCH_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        with _DISPATCH_POOL_LOCK:
            if _DISPATCH_POOL is None:
                _DISPATCH_POOL = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="atpu-engine-dispatch")
    return _DISPATCH_POOL


from ..utils import bucket_pow2 as _bucket  # noqa: E402 — shared bucketing policy
