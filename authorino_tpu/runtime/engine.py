"""PolicyEngine: the serving-time owner of the host index, the compiled rule
corpus (double-buffered, atomically swapped on reconcile) and the pipelined
micro-batch dispatcher that overlaps encode / H2D / kernel / readback across
in-flight batches.

This is the TPU-era replacement for the reference's per-request goroutine
evaluation (SURVEY.md §5 "communication backend"): the gRPC/HTTP frontend
stays on host CPU; Check() contexts are encoded and batched here; one jitted
kernel evaluates the batch against the whole corpus.  Reconcile-time
compilation is the analog of the reference's OPA precompile
(ref: pkg/evaluators/authorization/opa.go:141); the swap is the analog of
index Set (ref: controllers/auth_config_controller.go:605-636).

Dispatch is an explicit three-stage software pipeline (one global dispatcher
for all event loops; futures resolve loop-affinely):

  1. encode stage — dispatch workers (shared CPU pool) run encode_batch /
     pack_batch and build ONE fused H2D staging buffer per batch
     (ops/pattern_eval.py fuse_batch) instead of 5-7 small transfers;
  2. dispatch stream — the kernel launches WITHOUT blocking (JAX async
     dispatch + copy_to_host_async); in-flight batches are tracked as a
     bounded counter window (max_inflight_batches), not captive pool
     threads, so throughput ≈ window × batch / RTT by construction;
  3. completion stage — a shared completer thread detects each batch's
     readback arrival (jax.Array.is_ready polling) and hands it to the
     worker pool to finalize + resolve futures: completion is
     FIFO-independent, and neither a slow readback nor a fallback-heavy
     finalize convoys another batch.

Flushing is adaptive: a free window slot + a non-empty queue dispatches
immediately (light-load latency ≈ one device RTT, never a max_delay_s
stack); with the window full, requests queue and each completion cuts the
next batch — batch size grows with load instead of with a timer.

Fault tolerance (ISSUE 5, docs/robustness.md): a failed in-flight batch is
retried ONCE on a fresh dispatch, then every request is re-decided exactly
through the host expression oracle (models/policy_model.host_results — the
kernel's differential-test reference); consecutive batch failures trip a
circuit breaker (runtime/breaker.py) that routes whole batches host-side
with half-open probing; requests that cannot make their propagated Check()
deadline are shed BEFORE encode (typed DEADLINE_EXCEEDED); a completer
watchdog times out batches wedged in is_ready (--device-timeout) and feeds
them the same retry/degrade path; and SIGTERM drains the queue + in-flight
window before exit.  No request ever observes a raw exception: failures
that cannot degrade resolve as typed CheckAbort(UNAVAILABLE)."""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..authjson.wellknown import CheckRequestModel
from ..compiler.compile import CompiledPolicy, ConfigRules, compile_corpus
from ..compiler.encode import encode_batch
from ..evaluators.base import RuntimeAuthConfig
from ..index import HostIndex
from ..pipeline.pipeline import AuthPipeline, AuthResult
from ..utils import metrics as metrics_mod
from ..utils import tracing as tracing_mod
from ..utils.rpc import DEADLINE_EXCEEDED, NOT_FOUND, UNAVAILABLE, CheckAbort
from ..utils.verdict_cache import VerdictCache
from . import faults
from . import provenance as prov_mod
from . import change_safety as safety_mod
from ..replay.capture import CAPTURE
from .admission import AdaptiveWindow, AdmissionController
from .breaker import CircuitBreaker
from .flight_recorder import RECORDER
from . import kernel_cost as kernel_cost_mod
from .kernel_cost import LEDGER, CostModel
from .lane_select import (
    DEVICE as L_DEVICE,
    HOST as L_HOST,
    R_COST,
    R_DEADLINE,
    R_SPECULATIVE,
    LaneSelector,
    Speculation,
)
from ..tenancy.quota import R_TENANT_CONTAINED as TEN_R_CONTAINED

__all__ = ["PolicyEngine", "EngineEntry", "SnapshotRejected"]

log = logging.getLogger("authorino_tpu.engine")


class SnapshotRejected(RuntimeError):
    """A compiled snapshot failed --strict-verify tensor lint at swap time.
    The previously-serving snapshot stays live (the reconciler records
    CachingError and retries on the next resync)."""

    def __init__(self, findings):
        self.findings = findings
        super().__init__(
            f"snapshot rejected by tensor lint ({len(findings)} finding(s)): "
            + "; ".join(str(f) for f in findings[:3]))


@dataclass
class EngineEntry:
    """One AuthConfig as the control plane hands it to the engine."""

    id: str                       # e.g. "namespace/name"
    hosts: List[str]
    runtime: RuntimeAuthConfig
    rules: Optional[ConfigRules] = None  # compilable pattern surface (may be None)
    # AuthConfig metadata.annotations (ISSUE 15): the tenant QoS plane
    # resolves per-tenant weights/quotas from these at every reconcile
    # (authorino.tpu/qos-class, qos-weight, qos-quota-rps); None = the
    # default QoS class
    annotations: Optional[Dict[str, str]] = None


class _Snapshot:
    """Immutable compiled corpus + device params (double-buffered).

    With a multi-device mesh, the corpus compiles as a ShardedPolicyModel
    (rules axis tensor-parallel over 'mp', batch over 'dp') — the TPU-era
    successor of the reference's label-selector instance sharding
    (ref: controllers/label_selector.go:14-45)."""

    # class-level default: the replica/clone paths construct via
    # ``__new__`` (from_serialized, clone) and never run ``__init__``,
    # yet ``_upload`` still reads the lane — a loaded snapshot resolves
    # it from the environment like any lane-unaware caller.
    kernel_lane: Optional[str] = None

    def __init__(self, entries: Sequence[EngineEntry], members_k: int = 16,
                 mesh=None, strict_verify: bool = False,
                 compile_cache=None, prev: "Optional[_Snapshot]" = None,
                 breaker_threshold: int = 3, breaker_reset_s: float = 5.0,
                 ovf_assist: Optional[bool] = None,
                 kernel_lane: Optional[str] = None):
        self.by_id: Dict[str, EngineEntry] = {e.id: e for e in entries}
        self.kernel_lane = kernel_lane
        rules = [e.rules for e in entries if e.rules is not None]
        self.policy: Optional[CompiledPolicy] = None
        self.params = None
        self.sharded = None
        # engine generation this snapshot serves under, set inside
        # apply_snapshot's swap lock.  In-flight batches pin their
        # snapshot, so they insert AND serve under the cache tokens (or,
        # on the mesh path, the generation) they were encoded against: a
        # swap can never let a stale verdict leak into the new snapshot's
        # lookups.
        self.generation = 0
        # set by a passing _verify(): downstream strict-verify consumers
        # (the native frontend's refresh) skip re-linting an already-vetted
        # snapshot — the lint rebuilds both lanes' host operand pytrees,
        # too heavy to repeat per swap listener
        self.lint_ok = False
        # translation-validation stats from _verify (None when strict
        # verify is off): validated / cache_hits / failed / sampled —
        # the /debug/vars evidence that the fingerprint cache is
        # actually incremental across reconciles
        self.translation: Optional[Dict[str, int]] = None
        # incremental control plane (ISSUE 8, authorino_tpu/snapshots/):
        # per-config source fingerprints, the (epoch, fingerprint) verdict-
        # cache tokens per eval row, what the incremental compile actually
        # did, the upload plan, per-phase timings, and the host operand
        # view the NEXT reconcile diffs against
        self.fingerprints: Dict[str, str] = {}
        self.cache_tokens = None         # per-row tokens (single corpus only)
        self.compile_report = None
        self.upload: Optional[Dict[str, Any]] = None
        self.phase_s: Dict[str, float] = {}
        self.host_view = None
        self.published_origin: Optional[str] = None  # set by from_published
        # change-safety provenance (ISSUE 10): set on rollback clones and
        # quarantine re-applies so the publisher manifest can carry the
        # rollback/quarantine record to replicas
        self.change_safety: Optional[Dict[str, Any]] = None
        # rule heat map (ISSUE 9): built at install time by
        # _install_snapshot (kernel rows → authconfig/rule-source labels)
        self.heat = None
        # mesh verdict-cache tokens (ISSUE 11): [shard][row] → (encoding
        # epoch, rules fingerprint), the PR 8 keying the mesh lane now
        # shares with the single corpus (generation keying retired)
        self.mesh_tokens = None
        if rules:
            if mesh is not None:
                self._compile_mesh(rules, members_k, mesh, strict_verify,
                                   prev, breaker_threshold, breaker_reset_s,
                                   ovf_assist=ovf_assist,
                                   kernel_lane=kernel_lane)
            else:
                self._compile_single(rules, members_k, strict_verify,
                                     compile_cache, prev,
                                     ovf_assist=ovf_assist)

    def _compile_mesh(self, rules, members_k: int, mesh,
                      strict_verify: bool,
                      prev: "Optional[_Snapshot]",
                      breaker_threshold: int = 3,
                      breaker_reset_s: float = 5.0,
                      ovf_assist: Optional[bool] = None,
                      kernel_lane: Optional[str] = None) -> None:
        """Mesh compile → verify → delta upload, each phase timed (the
        control-plane parity half of ISSUE 11):

        - the previous mesh snapshot's INTERNER is adopted (insert-only, so
          ids are stable), which keeps each untouched shard's encoding
          epoch — and with it the verdict-cache tokens — identical across
          the swap;
        - with --strict-verify the packed shards are linted HOST-side,
          BEFORE the device upload (the PR 4 ordering caveat, fixed:
          a corrupt corpus never stages a byte);
        - the upload is a per-shard DELTA against the previous stacked host
          view: a one-config mutation ships rows only to its owning
          shard(s)."""
        from ..parallel import ShardedPolicyModel
        from ..snapshots.fingerprint import rules_fingerprint

        t0 = time.monotonic()
        prev_ok = (prev is not None and prev.sharded is not None
                   and prev.sharded.mesh is mesh)
        self.sharded = ShardedPolicyModel(
            rules, mesh, members_k=members_k,
            interner=(prev.sharded.interner if prev_ok else None),
            defer_upload=True, breaker_threshold=breaker_threshold,
            breaker_reset_s=breaker_reset_s, ovf_assist=ovf_assist,
            kernel_lane=kernel_lane)
        self.phase_s["compile"] = time.monotonic() - t0
        memo: Dict[int, str] = {}
        self.fingerprints = {c.name: rules_fingerprint(c, memo)
                             for c in rules}
        if strict_verify:
            t0 = time.monotonic()
            self._verify()
            self.phase_s["validate"] = time.monotonic() - t0
        self.mesh_tokens = self.sharded.cache_tokens(self.fingerprints)
        t0 = time.monotonic()
        self.upload = self.sharded.upload(
            prev.sharded if prev_ok else None)
        self.phase_s["upload"] = time.monotonic() - t0

    def _compile_single(self, rules, members_k: int, strict_verify: bool,
                        compile_cache, prev: "Optional[_Snapshot]",
                        ovf_assist: Optional[bool] = None) -> None:
        """Single-corpus compile → verify → diff → upload, each phase
        timed.  With a compile cache and an unchanged corpus the previous
        snapshot's CompiledPolicy AND device params are reused outright:
        zero configs compiled, zero bytes uploaded, verification skipped
        (the artifacts are byte-identical to ones already vetted)."""
        from ..snapshots.fingerprint import cache_tokens, rules_fingerprint

        t0 = time.monotonic()
        prev_ok = (prev is not None and prev.policy is not None
                   and prev.sharded is None)
        if compile_cache is not None:
            policy, report = compile_cache.compile(
                rules, members_k=members_k,
                prev_fps=(prev.fingerprints if prev_ok else None),
                prev_policy=(prev.policy if prev_ok else None),
                ovf_assist=ovf_assist)
            self.compile_report = report
            self.fingerprints = dict(report.fingerprints)
        else:
            policy = compile_corpus(rules, members_k=members_k,
                                    ovf_assist=ovf_assist)
            memo: Dict[int, str] = {}
            self.fingerprints = {c.name: rules_fingerprint(c, memo)
                                 for c in rules}
        self.policy = policy
        self.phase_s["compile"] = time.monotonic() - t0
        reused = (self.compile_report is not None
                  and self.compile_report.reused_policy)
        if reused and (prev.lint_ok or not strict_verify):
            # fingerprint-identical corpus: previous params serve as-is
            self.lint_ok = prev.lint_ok
            # the strict-verify evidence for /debug/vars: every config's
            # certificate is (trivially) served from cache — nothing was
            # re-validated, the strongest form of PR 6's zero-revalidation
            # property (the certify pass didn't even need to run)
            self.translation = (
                {"validated": 0, "cache_hits": len(prev.policy.config_ids),
                 "failed": 0, "sampled": 0, "dfa_witnesses": 0}
                if strict_verify and prev.lint_ok else prev.translation)
            self.params = prev.params
            self.host_view = prev.host_view
            self.cache_tokens = prev.cache_tokens
            self.upload = {"mode": "reuse", "upload_bytes": 0,
                           "full_bytes": 0, "arrays_reused": None,
                           "arrays_touched": []}
            return
        if strict_verify:
            # lint BEFORE the device upload: a corrupt corpus is rejected
            # host-side, never staged on the device (and never crashes
            # mid-operand-build with a raw IndexError)
            t0 = time.monotonic()
            self._verify()
            self.phase_s["validate"] = time.monotonic() - t0
        self.cache_tokens = cache_tokens(policy, self.fingerprints)
        self._upload(prev if prev_ok else None)

    def _upload(self, prev: "Optional[_Snapshot]") -> None:
        """Diff + upload phases: plan a delta against the previous host
        operand view, ship only changed rows when a structure-preserving
        delta exists, fall back to a full re-stage otherwise."""
        from ..ops.pattern_eval import to_device
        from ..snapshots.delta import apply_delta, full_upload
        from ..snapshots.diff import plan_delta

        t0 = time.monotonic()
        host_view = to_device(self.policy, host=True, lane=self.kernel_lane)
        self.host_view = host_view
        plan = None
        if (prev is not None and prev.params is not None
                and prev.host_view is not None):
            plan = plan_delta(prev.host_view, host_view)
        self.phase_s["diff"] = time.monotonic() - t0
        t0 = time.monotonic()
        if plan is not None:
            self.params, uploaded = apply_delta(prev.params, host_view, plan)
            self.upload = dict(plan.to_json(), upload_bytes=uploaded)
        else:
            self.params, uploaded = full_upload(host_view)
            self.upload = {"mode": "full", "upload_bytes": uploaded,
                           "full_bytes": uploaded, "arrays_reused": 0,
                           "arrays_touched": []}
        self.phase_s["upload"] = time.monotonic() - t0

    @classmethod
    def from_published(cls, loaded, members_k: int = 16,
                       strict_verify: bool = False,
                       prev: "Optional[_Snapshot]" = None) -> "_Snapshot":
        """Serving-replica constructor: wrap a leader-serialized corpus
        (snapshots/distribution.py LoadedSnapshot) WITHOUT compiling
        anything.  The admission gate: an uncertified snapshot is rejected
        outright; with ``strict_verify`` the replica additionally re-runs
        the full local verification (tensor lint + translation
        certification — cheap on repeats thanks to the fingerprint-keyed
        certificate cache).  Entries carry hosts only (runtime=None): a
        replica serves the compiled verdict lane, not the identity/
        metadata pipeline (docs/control_plane.md)."""
        from ..snapshots.fingerprint import cache_tokens

        if not loaded.certified:
            raise SnapshotRejected([
                "snapshot is not certified: the leader never marked it "
                "strict-verified (lint + translation certification)"])
        entries = [EngineEntry(id=cid, hosts=hosts, runtime=None, rules=None)
                   for cid, hosts in loaded.entries]
        snap = cls.__new__(cls)
        snap.by_id = {e.id: e for e in entries}
        snap.policy = loaded.policy
        snap.sharded = None
        snap.params = None
        snap.generation = 0
        snap.lint_ok = False
        snap.translation = (loaded.meta or {}).get("translation")
        snap.fingerprints = loaded.fingerprints
        snap.cache_tokens = None
        snap.mesh_tokens = None
        snap.compile_report = None
        snap.upload = None
        snap.phase_s = {}
        snap.host_view = None
        snap.change_safety = (loaded.meta or {}).get("change_safety")
        snap.heat = None
        # provenance: this snapshot was LOADED, not compiled here — the
        # publisher skips it (a replica must never republish what it
        # consumed, or a node whose source and publish dir meet — even
        # through an HTTP relay — would republish/re-apply forever)
        snap.published_origin = loaded.digest or "<loaded>"
        if strict_verify:
            t0 = time.monotonic()
            snap._verify()
            snap.phase_s["validate"] = time.monotonic() - t0
        else:
            snap.lint_ok = True  # vouched for by the leader's certificate
        prev_ok = (prev is not None and prev.policy is not None
                   and prev.sharded is None)
        if prev_ok:
            # interner continuity: every deserialize builds a FRESH
            # StringInterner (new identity serial), which would change the
            # encoding epoch and kill every cached verdict on each applied
            # generation — the exact churn cliff this subsystem removes.
            # The leader's interner is insert-only, so when the loaded
            # table prefix-extends the previous snapshot's, the ids ARE
            # the previous interner's ids: extend it in place and adopt it
            # (same object ⇒ same serial ⇒ untouched configs' entries
            # survive on replicas too).
            _adopt_interner(prev.policy.interner, loaded.policy)
        snap.cache_tokens = cache_tokens(loaded.policy, snap.fingerprints)
        snap._upload(prev if prev_ok else None)
        return snap

    def clone(self) -> "_Snapshot":
        """Shallow re-serve copy (rollback is a pointer swap, ISSUE 10):
        shares the compiled policy, device params, heat map and cache
        tokens — only the generation and change-safety record are fresh,
        so in-flight batches pinned to the ORIGINAL object keep resolving
        and inserting verdicts under their own generation/tokens."""
        c = _Snapshot.__new__(_Snapshot)
        c.__dict__.update(self.__dict__)
        c.change_safety = None
        return c

    def _verify(self) -> None:
        from ..analysis.tensor_lint import lint_snapshot

        findings = lint_snapshot(self)
        if findings:
            raise SnapshotRejected(findings)
        # translation validation (ISSUE 6): beyond structural sanity, the
        # compiled circuits/DFA tables must DECIDE identically to the host
        # expression oracle.  Per-config fingerprints + the process-wide
        # certificate cache make this incremental: an unchanged config is
        # a cache hit, never a re-validation (ROADMAP item 1).
        from ..analysis.translation_validate import (
            certify_snapshot,
            snapshot_policies,
        )

        stats = {"validated": 0, "cache_hits": 0, "failed": 0,
                 "sampled": 0, "dfa_witnesses": 0}
        failures = []
        for pol in snapshot_policies(self):
            _, fails, st = certify_snapshot(pol)
            failures += fails
            for k in stats:
                stats[k] += st.get(k, 0)
        self.translation = stats
        if failures:
            raise SnapshotRejected(failures)
        self.lint_ok = True


def _adopt_interner(prev_interner, new_policy) -> None:
    """Replica-side interner continuity (see from_published): when the
    freshly-deserialized policy's id table prefix-extends the previous
    snapshot's, graft the new entries onto the previous interner and point
    the policy at it.  Ids are positional in the insertion-ordered table,
    so a true prefix match proves every shared id means the same string;
    any mismatch (leader restarted with a fresh interner) keeps the new
    interner — a structural epoch change, exactly as safe as before."""
    old_t = prev_interner._table
    new_t = new_policy.interner._table
    if len(new_t) < len(old_t):
        return
    it = iter(new_t.items())
    for want in old_t.items():
        if next(it) != want:
            return
    for s, i in new_t.items():
        if s not in old_t:
            old_t[s] = i
    new_policy.interner = prev_interner


@dataclass
class _Pending:
    doc: Any
    config_name: str
    future: asyncio.Future
    loop: Any                     # owning event loop (loop-affine resolution)
    span: Any = None              # RequestSpan (DeviceBatch span links)
    t_enq: float = 0.0            # monotonic enqueue time (queue-wait hist)
    deadline: Optional[float] = None  # monotonic Check() deadline (shedding)
    # canary cohort flag (ISSUE 10): stamped at submit while a canary is in
    # progress — batch cuts partition by it so every launched batch rides
    # exactly ONE snapshot generation (no torn batches)
    canary: bool = False


class _Inflight:
    """One launched micro-batch riding the device window: the on-device
    result handle plus everything the completion stage needs to finalize
    and resolve it.  ``handle`` only needs is_ready() (non-blocking) and
    np.asarray-ability — tests substitute stubs for both."""

    __slots__ = ("engine", "batch", "handle", "finalize", "binfo", "waits",
                 "t_launch", "snap", "attempt", "route", "spec")

    def __init__(self, engine, batch, handle, finalize, binfo, waits,
                 snap=None, attempt=0):
        self.engine = engine
        self.batch = batch
        self.handle = handle
        self.finalize = finalize
        self.binfo = binfo
        self.waits = waits
        self.t_launch = time.monotonic()
        self.snap = snap          # pinned snapshot (retry/degrade path)
        self.attempt = attempt    # 0 = first dispatch, 1 = the one retry
        self.route = None         # mesh lane: occupied device windows
        self.spec = None          # speculative dual-dispatch token (ISSUE 12)

    def ready(self) -> bool:
        is_ready = getattr(self.handle, "is_ready", None)
        if is_ready is None:
            return True  # no readiness probe: finalize blocks (degraded)
        try:
            return bool(is_ready())
        except Exception:
            return True  # let finalize surface the real error

    def expired(self) -> bool:
        """Watchdog probe: True once this batch has been wedged in the
        in-flight window past the engine's --device-timeout."""
        t = self.engine.device_timeout_s
        return bool(t) and (time.monotonic() - self.t_launch) > t


class PolicyEngine:
    def __init__(
        self,
        max_batch: int = 256,
        max_delay_s: Optional[float] = None,
        timeout_s: Optional[float] = None,
        members_k: int = 16,
        mesh: Any = "auto",
        max_fallback_per_batch: Optional[int] = None,
        max_inflight_batches: int = 48,
        dispatch_workers: int = 4,
        verdict_cache_size: int = 32768,
        batch_dedup: bool = True,
        strict_verify: bool = False,
        analyze_policies: bool = True,
        device_timeout_s: Optional[float] = None,
        breaker_threshold: int = 5,
        breaker_reset_s: float = 5.0,
        admission_target_s: float = 0.05,
        admission_queue_cap: int = 0,
        admission_min_cap: Optional[int] = None,
        adaptive_window: bool = True,
        brownout: bool = True,
        brownout_max_batch: int = 32,
        lane_select: bool = True,
        lane_host_max_rows: int = 64,
        speculative_dispatch: bool = True,
        slo_ms: float = 0.0,
        canary_fraction: float = 0.0,
        canary_window_s: float = 30.0,
        canary_thresholds=None,
        snapshot_history: int = 4,
        replay_pregate: bool = False,
        replay_pregate_budget_s: float = 2.0,
        corpus_pregate: str = "",
        corpus_pregate_budget_s: float = 2.0,
        ovf_assist: Optional[bool] = None,
        kernel_lane: Optional[str] = None,
        metadata_prefetch: bool = True,
        metadata_prefetch_max_age_s: float = 300.0,
        metadata_prefetch_refresh_s: float = 60.0,
        tenant_qos: bool = True,
        tenant_default_weight: float = 1.0,
        tenant_weights: Optional[Dict[str, float]] = None,
        tenant_quota_rps: float = 0.0,
        tenant_contain_threshold: float = 3.0,
        tenant_contain_allowance_rps: float = 100.0,
        tenant_top_k: int = 16,
    ):
        """``mesh="auto"`` shards the rule corpus over all visible devices
        when more than one is present (dp × mp ShardedPolicyModel);
        ``mesh=None`` forces the single-corpus path; an explicit
        ``jax.sharding.Mesh`` pins the layout.

        ``max_fallback_per_batch`` bounds the per-batch host-oracle work for
        membership-overflow requests (an overload valve: beyond the cap,
        fallback requests are DENIED fail-closed and counted in
        auth_server_host_fallback_shed_total).  None = unbounded — safe by
        default, since the compiled-closure oracle costs ~2µs/request,
        cheaper than the reference's normal per-request path.

        ``max_delay_s`` is RETIRED (deprecated no-op since PR 2, replaced
        by the adaptive controller below): flushing is completion-driven
        (open window → immediate, full window → completion-driven) and the
        window/batch-cut are tuned by ``AdaptiveWindow``.  Passing a value
        emits a DeprecationWarning and only echoes on /debug/vars; the
        CLI's ``--batch-window-us`` still feeds the native frontend's C++
        gather window, which is a real knob there.

        ``max_inflight_batches`` is the dispatch-window depth: launched
        batches awaiting readback.  Size it so window × max_batch ≥
        device RTT × target RPS (the default 48 covers 100k RPS at 120ms
        RTT with 256-request batches); it bounds device-side memory, not
        host threads.  ``dispatch_workers`` sizes the shared encode-stage
        CPU pool (first engine in the process wins).

        ``batch_dedup`` collapses duplicate encoded rows within each
        micro-batch before dispatch (the device evaluates unique rows
        only; verdicts fan back out on completion — bit-identical by
        construction, the kernel is a pure per-row function).
        ``verdict_cache_size`` bounds the snapshot-scoped verdict LRU
        keyed by (generation, encoded-row digest); 0 disables it.  Both
        are exactness-preserving: see docs/performance.md.

        ``strict_verify`` runs the tensor-IR lint (analysis/tensor_lint.py)
        on every compiled snapshot BEFORE the generation bump: a snapshot
        with any structural finding is rejected (SnapshotRejected raised,
        auth_server_snapshot_rejected_total bumped) and the previous one
        keeps serving.  ``analyze_policies`` runs the Cedar-style semantic
        pass (analysis/policy_analysis.py) once per reconcile — advisory
        warnings on /debug/vars + metrics, never a gate.  Both are
        reconcile-path costs only; see docs/static_analysis.md.

        ``device_timeout_s`` arms the completer watchdog: an in-flight
        batch whose readback never arrives is abandoned after this long,
        counted as a circuit-breaker failure, and fed the retry/degrade
        path (None/0 = off).  ``breaker_threshold`` consecutive batch
        failures trip the device circuit breaker OPEN (whole batches
        decided host-side); after ``breaker_reset_s`` one half-open probe
        batch tests recovery.  See docs/robustness.md.

        Overload resilience (ISSUE 7, docs/robustness.md "Overload &
        brownout"): ``admission_target_s``/``admission_queue_cap``/
        ``admission_min_cap`` parameterize the CoDel-style admission gate —
        a submit that would push the queue past the wait-targeted cap is
        rejected typed RESOURCE_EXHAUSTED at admission (and one whose
        deadline lands inside the predicted wait + device RTT is shed
        DEADLINE_EXCEEDED there, before it ever queues).
        ``adaptive_window`` enables the Little's-law controller that tunes
        the live in-flight window and batch-cut inside
        [1, max_inflight_batches] / [1, max_batch] from observed arrival
        rate, queue wait and device RTT — ``max_inflight_batches`` is the
        CAP, no longer the operating point.  ``brownout`` lets saturated
        windows spill small head-of-queue batches to the exact host oracle
        (``brownout_max_batch`` rows at a time): overload degrades
        throughput, never correctness.

        Lane selection (ISSUE 12, docs/performance.md "Lane selection"):
        ``lane_select`` promotes the exact host oracle from brownout
        fallback to a FIRST-CLASS serving lane — at every batch cut a
        cost model (EWMAs of host per-row service time, device RTT, queue
        depth, window occupancy, per-lane SLO burn) decides whether the
        cut is answered host-side (light-load p50 in single-digit ms
        instead of one device RTT) or rides the device (full pads under
        load — throughput preserved by construction); the
        latency-critical head of a device cut (by propagated deadline) is
        rescued host-side instead of shed.  ``lane_host_max_rows`` caps
        what the host lane may take per cut; ``speculative_dispatch``
        dual-dispatches the breaker's half-open probe batch to BOTH lanes
        and resolves first-wins (verdicts are bit-identical by PR 6's
        certification, so the race is safe — and the device half still
        decides the breaker).

        Change safety (ISSUE 10, docs/robustness.md "Change safety"):
        with ``canary_fraction`` > 0, a reconcile that actually changes
        the compiled corpus does NOT swap at 100% — a deterministic
        hash-fraction of requests routes to the new generation for
        ``canary_window_s`` while the rest keeps serving the previous one.
        Guards (``canary_thresholds``: runtime/change_safety.py
        GuardThresholds) compare the cohorts' deny/error/SLO rates; a
        breach auto-rolls-back (pointer swap — the previous snapshot and
        its device buffers are retained) and quarantines the poison
        configs, a clean window promotes.  ``snapshot_history`` bounds how
        many previous (snapshot, index) generations are retained for
        manual rollback.

        Replay preflight (ISSUE 13, docs/replay.md): with
        ``replay_pregate``, a corpus-changing reconcile is first REPLAYED
        against the in-process capture ring (replay/capture.py CAPTURE —
        arm it with --capture) through the exact host oracle on both the
        serving and the candidate snapshot; a verdict diff breaching the
        canary guard thresholds rejects the swap as typed
        SnapshotRejected BEFORE any live request reaches the candidate
        (zero live exposure, vs the canary's ~seconds of detection
        latency), with the attributed diff frozen into a
        replay-pregate-breach flight bundle.  A clean preflight annotates
        the canary phase and HALVES its deny-delta guard thresholds (the
        change already proved behavior-preserving on yesterday's
        traffic).  ``replay_pregate_budget_s`` bounds the reconcile-path
        replay cost; records past the budget are reported as truncated,
        never silently skipped."""
        self.index: HostIndex[EngineEntry] = HostIndex()
        self.generation = 0  # bumped per apply_snapshot (gauge + /debug/vars)
        self.max_batch = max_batch
        if max_delay_s is not None:
            import warnings

            warnings.warn(
                "PolicyEngine(max_delay_s=...) is deprecated and ignored: "
                "the engine lane dispatches adaptively (AdaptiveWindow); "
                "--batch-window-us still tunes the native C++ gather window",
                DeprecationWarning, stacklevel=2)
        self.max_delay_s = max_delay_s
        self.timeout_s = timeout_s
        self.members_k = members_k
        self.max_fallback_per_batch = max_fallback_per_batch
        self.max_inflight_batches = max(1, int(max_inflight_batches))
        self.dispatch_workers = max(1, int(dispatch_workers))
        self.batch_dedup = bool(batch_dedup)
        self.strict_verify = bool(strict_verify)
        self.analyze_policies = bool(analyze_policies)
        # ISSUE 14: membership-overflow in-kernel assist (None = env
        # default AUTHORINO_TPU_OVF_ASSIST; compiler/compile.py) and the
        # metadata prefetch cache (request-independent external documents
        # pinned at reconcile cadence; relations/prefetch.py)
        self.ovf_assist = ovf_assist
        # ISSUE 17: kernel lane override (None = env default
        # AUTHORINO_TPU_KERNEL_LANE; "fused" arms the one-launch
        # mega-kernel, ops/fused_kernel.py)
        self.kernel_lane = kernel_lane
        self.metadata_prefetcher = None
        if metadata_prefetch:
            from ..relations.prefetch import MetadataPrefetcher

            self.metadata_prefetcher = MetadataPrefetcher(
                max_age_s=metadata_prefetch_max_age_s,
                refresh_s=metadata_prefetch_refresh_s)
        # incremental control plane (ISSUE 8): the persistent per-config
        # compile cache (fingerprint → artifact + the cross-reconcile
        # interner/DFA memos) and the latest reconcile's phase/delta
        # evidence for /debug/vars
        from ..snapshots.compile_cache import CompileCache

        self.compile_cache = CompileCache()
        self._control_plane: Optional[Dict[str, Any]] = None
        # latest reconcile's policy-analysis report (JSON-safe; /debug/vars)
        self._analysis: Optional[Dict[str, Any]] = None
        # latest reconcile's lowerability report (ISSUE 6: fast/slow lane
        # classification per config, with reason codes; /debug/vars)
        self._lowerability: Optional[Dict[str, Any]] = None
        self._verdict_cache = (VerdictCache(verdict_cache_size)
                               if verdict_cache_size else None)
        self._mesh = mesh
        self._snapshot: Optional[_Snapshot] = None
        self._swap_lock = threading.Lock()
        # ONE global dispatcher queue for every event loop (the gRPC/HTTP
        # servers and the native frontend's slow lane may share one engine
        # from different loops): futures remember their owning loop and
        # resolve via call_soon_threadsafe, so no per-loop queue/timer state
        # exists to leak when tests/reconciles create loops freely
        self._queue: deque = deque()
        self._queue_lock = threading.Lock()
        self._inflight = 0
        self.inflight_peak = 0    # high-watermark (bench occupancy evidence)
        self._swap_listeners: List[Any] = []
        self._g_inflight = metrics_mod.inflight_batches.labels("engine")
        self._g_depth = metrics_mod.dispatch_queue_depth.labels("engine")
        # fault tolerance (ISSUE 5): device circuit breaker, completer
        # watchdog, deadline shedding headroom, graceful-drain admission
        self.device_timeout_s = (float(device_timeout_s)
                                 if device_timeout_s else None)
        self.breaker = CircuitBreaker("engine", threshold=breaker_threshold,
                                      reset_s=breaker_reset_s)
        self._draining = False
        # cumulative typed serving errors (fleet fold, ISSUE 18): requests
        # failed UNAVAILABLE after every degrade lane was exhausted.
        # Deadline sheds stay out — they are the protection working.
        self.error_total = 0
        # EWMA of the device stage (launch→readback) — the shedding
        # headroom: a request whose deadline lands inside one expected
        # device round trip cannot be answered in time
        self._device_ewma = 0.0
        # overload resilience (ISSUE 7): CoDel-style admission on the
        # submit queue + the Little's-law window/batch-cut controller +
        # host-lane brownout when the device pipeline saturates
        if admission_min_cap is None:
            # floor = one full pipeline's worth of standing work: the gate
            # must never reject a burst the window itself could absorb
            admission_min_cap = max(64, self.max_inflight_batches * max_batch)
        self.admission = AdmissionController(
            "engine", target_s=admission_target_s,
            queue_cap=admission_queue_cap, min_cap=admission_min_cap)
        self.controller = AdaptiveWindow(
            "engine", cap=self.max_inflight_batches, batch_cap=max_batch,
            enabled=adaptive_window)
        self.brownout = bool(brownout)
        self.brownout_max_batch = max(1, int(brownout_max_batch))
        # concurrent brownout batches are bounded: the host lane absorbs
        # overload, it must not become an unbounded CPU amplifier
        self._brownout_limit = max(1, self.dispatch_workers // 2)
        self._brownout_inflight = 0
        self._brownout_total = 0
        # lane selection (ISSUE 12, docs/performance.md "Lane selection"):
        # the host oracle as a FIRST-CLASS serving lane — a per-batch-cut
        # cost model decides host vs device (brownout stays the separate
        # overload spill), the latency-critical head of a device cut is
        # rescued host-side by propagated deadline, and a half-open
        # breaker probe dual-dispatches the same rows to both lanes,
        # resolving first-wins (verdicts are bit-identical by PR 6's
        # certification, so the race is safe)
        self.lanes = LaneSelector(
            "engine", enabled=lane_select,
            host_max_rows=lane_host_max_rows,
            speculative=speculative_dispatch,
            host_concurrency=max(1, self.dispatch_workers // 2))
        if lane_select:
            # predicted-wait is lane-aware at admission: a deadline only
            # the microsecond host lane can meet is no longer doomed —
            # but only while the host lane has concurrency headroom to
            # actually take it (the floor collapses to the device RTT
            # when the cap is saturated: backpressure stays honest)
            self.admission.lane_floor = self.lanes.admission_floor
        # decision observability (ISSUE 9, docs/observability.md): the SLO
        # burn-rate tracker (--slo-ms; 0 = off) and the flight-recorder
        # debug-vars provider.  The rule heat map lives on each snapshot
        # (attribution must match the corpus that evaluated the batch).
        self.slo = None
        if slo_ms:
            from ..utils.slo import SloTracker

            self.slo = SloTracker("engine", slo_ms)
        # change safety (ISSUE 10): the canary state machine, the
        # quarantine record (poison config id → fingerprints + the prior
        # entry each resync substitutes back in), the bounded generation
        # history for manual rollback, and the last-rollback evidence
        self.canary_fraction = min(max(float(canary_fraction), 0.0), 1.0)
        self.canary_window_s = float(canary_window_s)
        self.canary_thresholds = canary_thresholds
        self._canary: Optional[safety_mod.CanaryPhase] = None
        self._quarantine: Optional[Dict[str, Any]] = None
        self._quarantine_prior: Dict[str, EngineEntry] = {}
        self._history: deque = deque(maxlen=max(1, int(snapshot_history)))
        self._last_rollback: Optional[Dict[str, Any]] = None
        self._g_canary = metrics_mod.canary_state.labels("engine")
        self._g_quarantine = metrics_mod.quarantined_configs.labels("engine")
        # kernel cost observatory (ISSUE 16): per-generation modeled-cost
        # lineage — lower().compile().cost_analysis() at each reconcile,
        # >=2x per-row regression raises the cost-regression anomaly
        self._cost_model = CostModel("engine")
        # traffic replay preflight (ISSUE 13): gate state + last verdict
        self.replay_pregate = bool(replay_pregate)
        self.replay_pregate_budget_s = float(replay_pregate_budget_s)
        self._last_pregate: Optional[Dict[str, Any]] = None
        self._g_replay_flips = metrics_mod.replay_diff_flips.labels("engine")
        # corpus preflight (ISSUE 19, docs/policy_ci.md): the long-retention
        # decision corpus replayed frequency-weighted before the canary —
        # synthetic witness rows (built lazily against the serving baseline,
        # cached per generation) extend the judgment to rules live traffic
        # never exercised
        self.corpus_pregate = str(corpus_pregate or "")
        self.corpus_pregate_budget_s = float(corpus_pregate_budget_s)
        self._corpus_rows: Optional[list] = None   # loaded captured rows
        self._corpus_load_error: Optional[str] = None
        self._corpus_synth: Tuple[int, list, Dict[str, Any]] = (-1, [], {})
        self._last_corpus_pregate: Optional[Dict[str, Any]] = None
        # tenant QoS plane (ISSUE 15, docs/tenancy.md): weighted-fair batch
        # cuts over per-tenant virtual queues inside the submit queue,
        # per-tenant quotas + CoDel wait tracking + tenant-aware doomed
        # shedding at admission, the tenant axis of the provenance/SLO
        # folds, and noisy-neighbor containment (a tenant-scoped
        # brownout/shed — the global OVERLOADED latch never fires for one
        # hot tenant).  The tenant is the AuthConfig identity every
        # encoded row already carries as config_id.
        from ..tenancy import TenantPlane

        self.tenancy = TenantPlane(
            "engine", enabled=bool(tenant_qos),
            default_weight=tenant_default_weight,
            weight_overrides=tenant_weights,
            default_quota_rps=tenant_quota_rps,
            admission_target_s=self.admission.target_s,
            contain_threshold=tenant_contain_threshold,
            contain_allowance_rps=tenant_contain_allowance_rps,
            top_k=tenant_top_k,
            wait_ewma=lambda: self.admission.wait_ewma,
            wait_target_s=lambda: self.admission.target_s,
            # second pressure signal: rising GLOBAL admission rejections
            # (the wait-targeted cap clamps the queue AT the target, so
            # the wait gauge alone can read healthy while cold tenants
            # are being turned away)
            reject_count=lambda: (
                self.admission.rejected.get("overload", 0)
                + self.admission.rejected.get("queue-full", 0)
                # rising queue-share rejections = a tenant persistently
                # over-occupying the shared queue: pressure even while
                # the bound keeps the global wait healthy
                + self.admission.rejected.get("tenant-queue-share", 0)))
        RECORDER.register_provider("engine", self, "debug_vars")

    # swap listeners: the native frontend rebuilds its C++ snapshot after
    # every corpus swap (runtime/native_frontend.py refresh)
    def add_swap_listener(self, cb) -> None:
        self._swap_listeners.append(cb)

    def remove_swap_listener(self, cb) -> None:
        if cb in self._swap_listeners:
            self._swap_listeners.remove(cb)

    def notify_swap_listeners(self) -> None:
        """Fire swap listeners without a corpus swap — used by the secret
        reconciler after in-place API-key/mTLS rotation, so the native
        frontend rebuilds its credential→plan variants
        (ref controllers/secret_controller.go:40-130 mutates evaluators in
        place; the fast lane's compiled view must follow)."""
        for cb in list(self._swap_listeners):
            cb()

    # ---- control plane ---------------------------------------------------

    def _resolve_mesh(self):
        if self._mesh == "auto":
            import jax

            from ..parallel import build_mesh

            self._mesh = build_mesh() if len(jax.devices()) > 1 else None
        return self._mesh

    def apply_snapshot(self, entries: Sequence[EngineEntry], override: bool = True) -> None:
        """Compile the new corpus off the serving path, then atomically swap
        snapshot + index (double buffering: in-flight batches keep the old
        params alive until their futures resolve).

        With ``strict_verify`` the compiled snapshot is tensor-linted HERE,
        before the generation bump: a corrupt snapshot raises
        SnapshotRejected and the old snapshot/index keep serving (the
        reconciler maps the raise to CachingError + retry).

        Incremental (ISSUE 8): compilation runs through the engine's
        persistent per-config compile cache and the device upload is a
        DELTA against the previous snapshot — an unchanged corpus compiles
        zero configs and ships zero bytes; verdict-cache entries of
        untouched configs survive the swap (per-config cache tokens).

        Change safety (ISSUE 10): still-poisoned quarantined configs are
        substituted with their prior artifacts before compile, and a
        corpus-changing swap enters the canary phase instead of serving
        100% immediately (``canary_fraction`` > 0)."""
        self._apply_entries(entries, override=override, allow_canary=True)

    def _apply_entries(self, entries: Sequence[EngineEntry],
                       override: bool = True,
                       allow_canary: bool = True) -> None:
        phase = self._canary
        if phase is not None:
            # a newer reconcile supersedes an undecided canary: fall back
            # to the baseline first — the new corpus gets its own canary
            # (never stack two candidate generations)
            self._canary_rollback(phase, reason="superseded",
                                  quarantine=False)
        entries = self._substitute_quarantined(entries)
        try:
            snap = _Snapshot(entries, members_k=self.members_k,
                             mesh=self._resolve_mesh(),
                             strict_verify=self.strict_verify,
                             compile_cache=self.compile_cache,
                             prev=self._snapshot,
                             breaker_threshold=self.breaker.threshold,
                             breaker_reset_s=self.breaker.reset_s,
                             ovf_assist=self.ovf_assist,
                             kernel_lane=self.kernel_lane)
        except SnapshotRejected as e:
            metrics_mod.snapshot_rejected.labels("engine").inc()
            RECORDER.record("snapshot-rejected", lane="engine", detail={
                "generation": self.generation,
                "findings": [str(f) for f in e.findings[:5]]})
            log.error(
                "snapshot REJECTED by tensor lint (previous generation %d "
                "keeps serving): %s", self.generation,
                "; ".join(str(f) for f in e.findings[:5]))
            raise
        q = self._quarantine
        if q is not None:
            # stamp the ACTIVE quarantine onto the outgoing snapshot BEFORE
            # install fires the swap listeners: the publisher serializes
            # this record into the blob meta + manifest, so replicas
            # converge on the quarantined state — assigning it after the
            # listeners ran would race the publish thread's read
            snap.change_safety = {"quarantine": {
                "configs": sorted(q["configs"]),
                "from_generation": q["from_generation"]}}
        # replay preflight (ISSUE 13): judge a corpus-changing swap on
        # REPLAYED captured traffic before any live request can see it —
        # a breaching diff raises SnapshotRejected here (the old snapshot
        # keeps serving, zero live exposure); quarantine/rollback
        # re-applies (allow_canary=False) skip it, they must always land
        preflight = None
        if self.replay_pregate and allow_canary and not self._draining \
                and self._comparable_change(snap):
            preflight = self._run_replay_pregate(snap)
        # corpus preflight (ISSUE 19): the same judgment over the
        # long-retention corpus + synthetic witnesses — catches a breaching
        # edit to a rule the capture ring never exercised, zero exposure
        if self.corpus_pregate and allow_canary and not self._draining \
                and self._comparable_change(snap):
            self._run_corpus_pregate(snap)
        if allow_canary and self._should_canary(snap):
            self._enter_canary(snap, entries, override=override,
                               preflight=preflight)
        else:
            self._install_snapshot(snap, entries, override=override)
        if self.analyze_policies:
            self._run_policy_analysis(entries, snap)
            self._run_lowerability(entries, snap)

    def apply_published(self, loaded) -> None:
        """Serving-replica swap path: install a leader-serialized vetted
        snapshot (snapshots/distribution.py LoadedSnapshot) without
        compiling anything.  The admission gate lives in
        _Snapshot.from_published — an uncertified or locally-failing
        snapshot raises SnapshotRejected and the previous snapshot keeps
        serving, exactly like a strict-verify reconcile rejection."""
        try:
            snap = _Snapshot.from_published(
                loaded, members_k=self.members_k,
                strict_verify=self.strict_verify, prev=self._snapshot)
        except SnapshotRejected as e:
            metrics_mod.snapshot_rejected.labels("engine").inc()
            RECORDER.record("snapshot-rejected", lane="engine", detail={
                "generation": self.generation, "published": True,
                "findings": [str(f) for f in e.findings[:5]]})
            log.error(
                "published snapshot REJECTED at admission (previous "
                "generation %d keeps serving): %s", self.generation,
                "; ".join(str(f) for f in e.findings[:5]))
            raise
        entries = list(snap.by_id.values())
        self._install_snapshot(snap, entries, override=True)

    def _install_snapshot(self, snap: "_Snapshot",
                          entries: Sequence[EngineEntry],
                          override: bool = True) -> None:
        """Shared swap tail: index build, atomic swap, telemetry, swap
        listeners."""
        new_index: HostIndex[EngineEntry] = HostIndex()
        for e in entries:
            for host in e.hosts:
                new_index.set(e.id, host, e, override=override)
        # decision provenance (ISSUE 9): the rule heat map binds kernel rows
        # to (authconfig, rule source) for THIS snapshot — attribution and
        # the dead-rule report always read the corpus that evaluated
        self._build_heat(snap)
        # tenant QoS (ISSUE 15): weights/quotas resolve from the entries'
        # AuthConfig annotations at every reconcile
        try:
            self.tenancy.bind_entries(entries)
        except Exception:
            log.exception("tenant weight rebuild failed (swap unaffected)")
        with self._swap_lock:
            self.generation += 1
            # the mesh lane's verdict cache keys on snap.generation (the
            # single-corpus lane keys on per-config cache tokens instead):
            # in-flight batches of the OLD snapshot keep inserting/serving
            # under the tokens/generation they were encoded against, so
            # the swap structurally invalidates without TTLs
            snap.generation = self.generation
            prev_snap, prev_index = self._snapshot, self.index
            self._snapshot = snap
            self.index = new_index
            metrics_mod.snapshot_generation.labels("engine").set(self.generation)
            # bounded generation history (ISSUE 10): rollback is a pointer
            # swap to a retained (snapshot, index) pair — the old device
            # buffers are double-buffer safe and the compile cache keeps
            # re-applies nearly free
            if prev_snap is not None and (prev_snap.policy is not None
                                          or prev_snap.sharded is not None):
                self._history.append((prev_snap, prev_index))
        RECORDER.record("snapshot-swap", lane="engine", detail={
            "generation": snap.generation, "configs": len(snap.by_id)})
        self._record_control_plane(snap)
        # listeners (the native frontend rebuilding its C++ snapshot) fire
        # BEFORE the advisory analysis: a revoking reconcile must propagate
        # at swap speed, not wait out a bounded-evaluation pass
        self.notify_swap_listeners()
        # metadata prefetch (ISSUE 14): register this snapshot's request-
        # independent metadata evaluators and (asynchronously) re-pin
        # their documents — after the listeners, off the swap-speed path;
        # a registration failure never fails a reconcile
        if self.metadata_prefetcher is not None:
            try:
                self.metadata_prefetcher.reconcile(entries)
            except Exception:
                log.exception("metadata prefetch registration failed "
                              "(reconcile unaffected)")
        # relation-table footprint gauges (ISSUE 14)
        try:
            from ..analysis.translation_validate import snapshot_policies

            rows = nbytes = 0
            for pol in snapshot_policies(snap):
                if getattr(pol, "rel_bits", None) is not None:
                    rows += int(pol.rel_bits.shape[0])
                    nbytes += int(pol.rel_bits.nbytes)
            metrics_mod.relation_table_rows.set(rows)
            metrics_mod.relation_table_bytes.set(nbytes)
        except Exception:
            log.exception("relation-table telemetry failed (swap unaffected)")

    def _record_control_plane(self, snap: "_Snapshot") -> None:
        """Reconcile telemetry (ISSUE 8 satellite): phase histograms,
        compile-cache hit/miss counters, delta-upload byte counters, and
        the /debug/vars control_plane block.  Advisory — never fails a
        swap."""
        try:
            for phase, dt in snap.phase_s.items():
                metrics_mod.reconcile_phase.labels(phase).observe(dt)
            rep = snap.compile_report
            if rep is not None:
                if rep.cached:
                    metrics_mod.compile_cache_events.labels("hit").inc(
                        rep.cached)
                if rep.compiled:
                    metrics_mod.compile_cache_events.labels("miss").inc(
                        rep.compiled)
            if snap.upload is not None:
                metrics_mod.delta_upload_bytes.labels("engine").inc(
                    int(snap.upload.get("upload_bytes", 0)))
                metrics_mod.full_upload_bytes.labels("engine").inc(
                    int(snap.upload.get("full_bytes", 0)))
            RECORDER.record("reconcile", lane="engine", detail={
                "generation": snap.generation,
                "phases_ms": {k: round(v * 1e3, 3)
                              for k, v in snap.phase_s.items()}})
            self._control_plane = {
                "generation": snap.generation,
                "phases_ms": {k: round(v * 1e3, 3)
                              for k, v in snap.phase_s.items()},
                "compile": rep.to_json() if rep is not None else None,
                "upload": snap.upload,
                "compile_cache": (self.compile_cache.stats()
                                  if self.compile_cache is not None else None),
                "per_config_cache_keying": snap.cache_tokens is not None,
            }
        except Exception:
            log.exception("control-plane telemetry failed (swap unaffected)")
        # kernel cost observatory (ISSUE 16): modeled per-row FLOPs/bytes
        # of the new generation's kernel entry points, diffed against the
        # previous generation.  Advisory end to end — a >=2x per-row
        # regression raises the cost-regression flight-recorder anomaly
        # and stamps the canary phase, but NEVER rejects the swap.
        try:
            cost_rec = self._cost_model.analyze(
                snap.generation, policy=snap.policy, params=snap.params,
                sharded=snap.sharded, recorder=RECORDER)
            if isinstance(self._control_plane, dict):
                self._control_plane["kernel_cost"] = cost_rec
            phase = self._canary
            if phase is not None and phase.snap is snap:
                phase.kernel_cost = cost_rec
        except Exception:
            log.exception("kernel cost analysis failed (swap unaffected)")
        # fused mega-kernel pre-warm (ISSUE 17): compile the one-launch
        # entry at a small warm-grid pad at swap so the first
        # post-reconcile batch pays no XLA/Pallas compile.  Advisory: a
        # warm failure never affects the swap (dispatch compiles lazily).
        try:
            if snap.policy is not None and snap.params is not None:
                from ..ops import fused_kernel as fused_mod

                fused_mod.prewarm_fused(snap.policy, snap.params, pad=16)
        except Exception:
            log.exception("fused-kernel prewarm failed (swap unaffected)")

    def _build_heat(self, snap: "_Snapshot") -> None:
        if snap.heat is not None:
            return
        try:
            snap.heat = prov_mod.HeatMap.for_snapshot(snap.policy,
                                                      snap.sharded)
        except Exception:
            log.exception("rule heat map build failed (swap unaffected)")
            snap.heat = None

    # ---- change safety (ISSUE 10): canary, rollback, quarantine ----------

    def _should_canary(self, snap: "_Snapshot") -> bool:
        """A swap canaries when it can (both generations on the SAME lane —
        single-corpus↔single-corpus or mesh↔mesh; cohort routing has no
        meaning across a lane change) and should (the compiled corpus
        actually changed; an identical-fingerprint resync swaps straight
        through, it has nothing to prove).  Mesh↔mesh canaries (ISSUE 11)
        work exactly like single-corpus ones: cohorts are stamped at
        submit, batch cuts partition by cohort, and the guards read the
        shard-stacked attribution columns."""
        if not (self.canary_fraction > 0.0 and self.canary_window_s > 0.0):
            return False
        if self._draining:
            return False
        return self._comparable_change(snap)

    def _comparable_change(self, snap: "_Snapshot") -> bool:
        """True when the incoming snapshot actually CHANGES the compiled
        corpus and both generations are comparable (same lane) — the
        precondition shared by the canary split and the replay pregate:
        an identical-fingerprint resync has nothing to prove, a lane
        change has nothing to compare against."""
        prev = self._snapshot
        if prev is None or (prev.policy is None and prev.sharded is None):
            return False
        if snap.policy is None and snap.sharded is None:
            return False
        if (prev.sharded is None) != (snap.sharded is None):
            return False  # lane change: swap through, nothing to compare
        return snap.fingerprints != prev.fingerprints

    def _run_replay_pregate(self, snap: "_Snapshot") -> Dict[str, Any]:
        """Replay the candidate snapshot against the live capture ring and
        judge the verdict diff (ISSUE 13, docs/replay.md "Preflight
        gate").  Returns the preflight summary on pass/skip; raises typed
        SnapshotRejected on breach — the caller's old snapshot keeps
        serving and the candidate never sees a live request.

        Runs on the reconcile path but bounded: the replay stops at
        ``replay_pregate_budget_s`` and reports what it could not cover
        (a truncated preflight is partial evidence, not full coverage)."""
        from ..replay import pregate as pregate_mod
        from ..snapshots.diff import snapshot_diff

        t0 = time.monotonic()
        baseline = self._snapshot
        thresholds = self.canary_thresholds or safety_mod.GuardThresholds()
        records = CAPTURE.ring_records()
        if len(records) < thresholds.min_requests:
            self._last_pregate = {
                "result": "skipped",
                "reason": (f"capture ring holds {len(records)} record(s) < "
                           f"min_requests {thresholds.min_requests} — not "
                           f"enough replay evidence to judge"
                           + ("" if CAPTURE.enabled else
                              " (capture is OFF: arm --capture)")),
                "replayed": 0,
            }
            metrics_mod.replay_pregate.labels("skipped").inc()
            RECORDER.record("replay-pregate", lane="engine",
                            detail=self._last_pregate)
            log.warning("replay pregate SKIPPED: %s",
                        self._last_pregate["reason"])
            return self._last_pregate
        changed = set(snapshot_diff(baseline.fingerprints or {},
                                    snap.fingerprints or {})["recompile"])
        try:
            pf = pregate_mod.preflight(
                baseline, snap, records, thresholds, changed=changed,
                time_budget_s=self.replay_pregate_budget_s)
        except Exception:
            # a pregate bug must never block the control plane: the swap
            # proceeds under its normal canary protection, loudly
            log.exception("replay pregate errored (swap proceeds under "
                          "canary protection only)")
            self._last_pregate = {"result": "skipped",
                                  "reason": "pregate error (see logs)",
                                  "replayed": 0}
            metrics_mod.replay_pregate.labels("skipped").inc()
            return self._last_pregate
        report, breach = pf["report"], pf["breach"]
        self._g_replay_flips.set(report["flips"]["total"])
        elapsed_ms = round((time.monotonic() - t0) * 1e3, 3)
        if breach is None and report["replayed"] < thresholds.min_requests:
            # the ring LOOKED big enough, but the replay itself could not
            # re-decide min_requests records (every config missing on one
            # side, or the time budget truncated almost everything) — that
            # is ABSENT evidence, not clean evidence: record skipped, so
            # the canary keeps its normal (untightened) guards
            self._last_pregate = {
                "result": "skipped",
                "reason": (f"only {report['replayed']} of "
                           f"{len(records)} record(s) re-decided "
                           f"(missing configs / time budget) < "
                           f"min_requests {thresholds.min_requests}"),
                "replayed": report["replayed"],
                "skipped_detail": report["skipped"],
                "elapsed_ms": elapsed_ms,
            }
            metrics_mod.replay_pregate.labels("skipped").inc()
            RECORDER.record("replay-pregate", lane="engine",
                            detail=self._last_pregate)
            log.warning("replay pregate SKIPPED: %s",
                        self._last_pregate["reason"])
            return self._last_pregate
        if breach is not None:
            metrics_mod.replay_pregate.labels("breach").inc()
            metrics_mod.snapshot_rejected.labels("engine").inc()
            self._last_pregate = {
                "result": "breach",
                "replayed": report["replayed"],
                "flips_total": report["flips"]["total"],
                "flips": report["flips"],
                "guards": breach["guards"],
                "suspects": breach["suspects"],
                "elapsed_ms": elapsed_ms,
            }
            # the anomaly kind auto-dumps a flight bundle with the top-N
            # attributed verdict-diff rows frozen as incident evidence
            RECORDER.record(pregate_mod.PREGATE_ANOMALY, lane="engine",
                            detail={
                                "baseline_generation": baseline.generation,
                                "breach": breach,
                                "replayed": report["replayed"],
                                "elapsed_ms": elapsed_ms,
                            })
            top = breach["top_flips"][:3]
            findings = [
                f"replay pregate breach: {', '.join(breach['guards'])} over "
                f"{report['replayed']} replayed request(s) "
                f"({report['flips']['newly_denied']} newly denied, "
                f"{report['flips']['newly_allowed']} newly allowed)"
            ] + [
                f"{g['authconfig']} rule[{g['rule_index']}] {g['rule']} "
                f"{g['direction']} {g['count']} replayed request(s)"
                for g in top
            ]
            log.error("replay pregate REJECTED the candidate snapshot "
                      "(generation %d keeps serving, zero live exposure): "
                      "%s", baseline.generation, "; ".join(findings))
            exc = SnapshotRejected(findings)
            exc.replay_diff = breach  # the full attributed evidence
            raise exc
        self._last_pregate = {
            "result": "pass",
            "replayed": report["replayed"],
            "flips_total": report["flips"]["total"],
            "flips": report["flips"],
            "truncated": report["skipped"]["truncated"],
            "elapsed_ms": elapsed_ms,
        }
        metrics_mod.replay_pregate.labels("pass").inc()
        RECORDER.record("replay-pregate", lane="engine",
                        detail=self._last_pregate)
        log.info("replay pregate PASS: %d record(s) replayed, %d flip(s), "
                 "%.0fms", report["replayed"], report["flips"]["total"],
                 elapsed_ms)
        return self._last_pregate

    def _corpus_pregate_rows(self, baseline: "_Snapshot") -> Optional[list]:
        """Captured corpus rows (loaded once from --corpus-pregate) plus
        synthetic witness rows built against the BASELINE policy (cached
        per baseline generation — synthesis is a reconcile-path cost only
        on the first swap of each generation).  None when the corpus
        source is unreadable (the pregate skips, loudly)."""
        from ..corpus import read_corpus
        from ..corpus.synthesize import augment_corpus

        if self._corpus_rows is None and self._corpus_load_error is None:
            try:
                self._corpus_rows = read_corpus(self.corpus_pregate)
            except Exception as e:
                self._corpus_load_error = str(e)
                log.error("corpus pregate: corpus unreadable at %s: %s",
                          self.corpus_pregate, e)
        if self._corpus_rows is None:
            return None
        gen, synth, _rep = self._corpus_synth
        if gen != baseline.generation:
            synth, rep = [], {}
            if baseline.policy is not None:
                try:
                    aug = augment_corpus(baseline.policy, self._corpus_rows)
                    synth, rep = aug["rows"], {
                        "reasons": aug["synthesis"]["reasons"],
                        "uncoverable": aug["synthesis"]["uncoverable"][:20],
                        "coverage_before":
                            aug["coverage_before"]["fraction"],
                        "coverage_after": aug["coverage_after"]["fraction"],
                    }
                except Exception:
                    # synthesis is additive evidence: a synthesis bug must
                    # not disarm the captured-row judgment
                    log.exception("corpus pregate: synthesis errored "
                                  "(captured rows only this generation)")
            self._corpus_synth = (baseline.generation, synth, rep)
            try:
                metrics_mod.corpus_rows.labels("captured").set(
                    len(self._corpus_rows))
                metrics_mod.corpus_rows.labels("synthetic").set(len(synth))
            except Exception:
                pass
        return self._corpus_rows + self._corpus_synth[1]

    def _run_corpus_pregate(self, snap: "_Snapshot") -> Dict[str, Any]:
        """Judge the candidate snapshot on the frequency-weighted decision
        corpus (ISSUE 19, docs/policy_ci.md "Corpus pregate") — same
        state machine as the replay pregate, but the evidence is the
        long-retention corpus plus synthetic truth-table witnesses, so a
        breaching edit to a ZERO-TRAFFIC rule is rejected here with zero
        live exposure.  Raises typed SnapshotRejected on breach."""
        from ..corpus import pregate as corpus_pregate_mod
        from ..snapshots.diff import snapshot_diff

        t0 = time.monotonic()
        baseline = self._snapshot
        thresholds = self.canary_thresholds or safety_mod.GuardThresholds()
        rows = self._corpus_pregate_rows(baseline)
        if not rows:
            self._last_corpus_pregate = {
                "result": "skipped",
                "reason": (f"corpus unreadable: {self._corpus_load_error}"
                           if self._corpus_load_error else
                           f"corpus at {self.corpus_pregate} holds no rows"),
                "replayed": 0,
            }
            metrics_mod.corpus_pregate.labels("skipped").inc()
            RECORDER.record("corpus-pregate", lane="engine",
                            detail=self._last_corpus_pregate)
            log.warning("corpus pregate SKIPPED: %s",
                        self._last_corpus_pregate["reason"])
            return self._last_corpus_pregate
        changed = set(snapshot_diff(baseline.fingerprints or {},
                                    snap.fingerprints or {})["recompile"])
        try:
            pf = corpus_pregate_mod.corpus_preflight(
                baseline, snap, rows, thresholds, changed=changed,
                time_budget_s=self.corpus_pregate_budget_s)
        except Exception:
            log.exception("corpus pregate errored (swap proceeds under "
                          "canary protection only)")
            self._last_corpus_pregate = {"result": "skipped",
                                         "reason": "pregate error (see "
                                                   "logs)",
                                         "replayed": 0}
            metrics_mod.corpus_pregate.labels("skipped").inc()
            return self._last_corpus_pregate
        report, breach = pf["report"], pf["breach"]
        elapsed_ms = round((time.monotonic() - t0) * 1e3, 3)
        if breach is None and report["replayed"] < thresholds.min_requests:
            # below the weighted evidence floor: absent evidence, recorded
            # as skipped — never a false 'pass'
            self._last_corpus_pregate = {
                "result": "skipped",
                "reason": (f"weighted corpus evidence {report['replayed']} "
                           f"< min_requests {thresholds.min_requests}"),
                "replayed": report["replayed"],
                "skipped_detail": report["skipped"],
                "elapsed_ms": elapsed_ms,
            }
            metrics_mod.corpus_pregate.labels("skipped").inc()
            RECORDER.record("corpus-pregate", lane="engine",
                            detail=self._last_corpus_pregate)
            log.warning("corpus pregate SKIPPED: %s",
                        self._last_corpus_pregate["reason"])
            return self._last_corpus_pregate
        if breach is not None:
            metrics_mod.corpus_pregate.labels("breach").inc()
            metrics_mod.snapshot_rejected.labels("engine").inc()
            self._last_corpus_pregate = {
                "result": "breach",
                "replayed": report["replayed"],
                "replayed_rows": report.get("replayed_rows", 0),
                "flips": report["flips"],
                "guards": breach["guards"],
                "suspects": breach["suspects"],
                "origins": report.get("origins", {}),
                "elapsed_ms": elapsed_ms,
            }
            RECORDER.record(corpus_pregate_mod.CORPUS_PREGATE_ANOMALY,
                            lane="engine", detail={
                                "baseline_generation": baseline.generation,
                                "breach": breach,
                                "origins": report.get("origins", {}),
                                "replayed": report["replayed"],
                                "elapsed_ms": elapsed_ms,
                            })
            top = breach["top_flips"][:3]
            findings = [
                f"corpus pregate breach: {', '.join(breach['guards'])} over "
                f"{report['replayed']} weighted corpus decision(s) "
                f"({report['flips']['newly_denied']} newly denied, "
                f"{report['flips']['newly_allowed']} newly allowed)"
            ] + [
                f"{g['authconfig']} rule[{g['rule_index']}] {g['rule']} "
                f"{g['direction']} weight {g['count']} "
                f"(origins: {', '.join(g.get('origins') or []) or 'n/a'})"
                for g in top
            ]
            log.error("corpus pregate REJECTED the candidate snapshot "
                      "(generation %d keeps serving, zero live exposure): "
                      "%s", baseline.generation, "; ".join(findings))
            exc = SnapshotRejected(findings)
            exc.corpus_diff = breach  # the full attributed evidence
            raise exc
        self._last_corpus_pregate = {
            "result": "pass",
            "replayed": report["replayed"],
            "replayed_rows": report.get("replayed_rows", 0),
            "flips": report["flips"],
            "origins": report.get("origins", {}),
            "truncated": report["skipped"]["truncated"],
            "elapsed_ms": elapsed_ms,
        }
        metrics_mod.corpus_pregate.labels("pass").inc()
        RECORDER.record("corpus-pregate", lane="engine",
                        detail=self._last_corpus_pregate)
        log.info("corpus pregate PASS: %d weighted decision(s) "
                 "(%d row(s)) replayed, %d flip(s), %.0fms",
                 report["replayed"], report.get("replayed_rows", 0),
                 report["flips"]["total"], elapsed_ms)
        return self._last_corpus_pregate

    def _enter_canary(self, snap: "_Snapshot",
                      entries: Sequence[EngineEntry],
                      override: bool = True,
                      preflight: Optional[Dict[str, Any]] = None) -> None:
        """Start the canary phase: the reconcile's host index (pipeline
        semantics) lands immediately, but the compiled VERDICT lane splits
        — the hash-fraction cohort rides the new generation, everyone else
        keeps the baseline.  Swap listeners (native frontend rebuild,
        snapshot publisher) deliberately do NOT fire here: the native fast
        lane and the replica fleet hold the baseline until promotion, so a
        breach never has to claw anything back from them."""
        new_index: HostIndex[EngineEntry] = HostIndex()
        for e in entries:
            for host in e.hosts:
                new_index.set(e.id, host, e, override=override)
        self._build_heat(snap)
        baseline = self._snapshot
        # the per-config guards watch only what this reconcile CHANGED
        # (the PR 8 fingerprint diff): unchanged configs share the
        # baseline's artifacts and can only differ by cohort selection
        # bias — see change_safety.CanaryGuard
        from ..snapshots.diff import snapshot_diff

        changed = set(snapshot_diff(baseline.fingerprints or {},
                                    snap.fingerprints or {})["recompile"])
        # preflight-tightened guards (ISSUE 13): a candidate whose replay
        # diff came back CLEAN over a real traffic window has already
        # proved itself on yesterday's requests — its canary watches with
        # halved deny-delta thresholds, so a live-only regression (a
        # metadata dependency, a traffic shift the capture window missed)
        # trips earlier.  A skipped/flipping-but-under-threshold preflight
        # keeps the operator's thresholds untouched.
        thresholds = self.canary_thresholds
        if preflight is not None and preflight.get("result") == "pass" \
                and not preflight.get("flips_total"):
            import dataclasses

            base_th = thresholds or safety_mod.GuardThresholds()
            thresholds = dataclasses.replace(
                base_th, deny_delta=base_th.deny_delta / 2,
                config_deny_delta=base_th.config_deny_delta / 2)
            preflight = dict(preflight, guards_tightened=True)
        phase = safety_mod.CanaryPhase(
            snap=snap, baseline=baseline, entries=entries,
            index=new_index, baseline_index=self.index,
            fraction=self.canary_fraction, window_s=self.canary_window_s,
            guard=safety_mod.CanaryGuard(thresholds, changed=changed),
            preflight=preflight)
        with self._swap_lock:
            self.generation += 1
            snap.generation = self.generation
            self._canary = phase
            self.index = new_index
        self._g_canary.set(1)
        RECORDER.record("canary-start", lane="engine", detail={
            "generation": snap.generation,
            "baseline_generation": baseline.generation,
            "fraction": self.canary_fraction,
            "window_s": self.canary_window_s,
            "configs": len(snap.by_id)})
        self._record_control_plane(snap)
        log.info("canary started: generation %d serving %.1f%% of traffic "
                 "for %.1fs (baseline %d serves the rest)",
                 snap.generation, self.canary_fraction * 100,
                 self.canary_window_s, baseline.generation)
        phase.start_timer(lambda: self._canary_conclude(phase))

    def _canary_conclude(self, phase) -> None:
        """Window-expiry decision (the phase timer's callback): one final
        guard evaluation (forced past the rate limit — a per-batch check
        moments earlier must not turn this into a blind promote), then
        promote or roll back."""
        if self._draining:
            return
        try:
            b = phase.guard.breach(force=True)
            if b is not None:
                self._canary_rollback(phase, reason="guard-breach",
                                      detail=b)
            else:
                self._canary_promote(phase)
        except Exception:
            log.exception("canary conclude failed")

    def _canary_guard_check(self, phase) -> None:
        """Per-feed breach/expiry check (worker threads only — promotion
        and rollback fan out to swap listeners, which must never run on a
        serving event loop)."""
        if self._canary is not phase or self._draining:
            return
        b = phase.guard.breach()
        if b is not None:
            self._canary_rollback(phase, reason="guard-breach", detail=b)
        elif phase.expired():
            self._canary_conclude(phase)

    def _canary_promote(self, phase, manual: bool = False) -> bool:
        """Clean window (or operator override): the canary generation goes
        to 100% — a pointer swap; the baseline joins the rollback history
        and the swap listeners (native rebuild, publisher) finally fire."""
        with self._swap_lock:
            if self._canary is not phase:
                return False
            self._canary = None
            self._snapshot = phase.snap
            if phase.baseline is not None and (
                    phase.baseline.policy is not None
                    or phase.baseline.sharded is not None):
                self._history.append((phase.baseline, phase.baseline_index))
            metrics_mod.snapshot_generation.labels("engine").set(
                phase.snap.generation)
        phase.cancel_timer()
        phase.guard.close()
        self._g_canary.set(0)
        RECORDER.record("canary-promote", lane="engine", detail={
            "generation": phase.snap.generation, "manual": manual,
            "age_s": round(time.monotonic() - phase.t_start, 3)})
        log.info("canary promoted to 100%%: generation %d now serves all "
                 "traffic%s", phase.snap.generation,
                 " (manual override)" if manual else "")
        self.notify_swap_listeners()
        return True

    def _canary_rollback(self, phase, reason: str,
                         detail: Optional[Dict[str, Any]] = None,
                         quarantine: bool = True,
                         manual: bool = False) -> bool:
        """Guard breach (or supersede/manual): the baseline re-serves 100%
        immediately — a pointer swap to a CLONE of the retained baseline
        (fresh generation: in-flight batches pinned to the original keep
        resolving/inserting under their own tokens), then the poison
        configs are quarantined and the rest of the reconcile re-applied."""
        t_detect = time.monotonic()
        clone = phase.baseline.clone()
        clone.change_safety = {"rollback": {
            "from_generation": phase.snap.generation, "reason": reason}}
        with self._swap_lock:
            if self._canary is not phase:
                return False
            self._canary = None
            self.generation += 1
            clone.generation = self.generation
            self._snapshot = clone
            self.index = phase.baseline_index
            metrics_mod.snapshot_generation.labels("engine").set(
                clone.generation)
        phase.cancel_timer()
        phase.guard.close()
        self._g_canary.set(0)
        metrics_mod.snapshot_rollbacks.labels(reason).inc()
        self._last_rollback = {
            "t": time.time(), "reason": reason, "manual": manual,
            "from_generation": phase.snap.generation,
            "to_generation": clone.generation,
            "detect_ms": round((t_detect - phase.t_start) * 1e3, 3),
            "detail": detail, "quarantined": [],
        }
        RECORDER.record("snapshot-rollback", lane="engine", detail={
            "reason": reason,
            "from_generation": phase.snap.generation,
            "to_generation": clone.generation,
            "guard": detail})
        log.error("canary ROLLED BACK (%s): generation %d abandoned, "
                  "baseline re-serving as generation %d%s", reason,
                  phase.snap.generation, clone.generation,
                  f" — guard: {detail}" if detail else "")
        self.notify_swap_listeners()
        if quarantine and reason == "guard-breach":
            try:
                self._quarantine_poison(phase, detail, t_detect)
            except Exception:
                log.exception("quarantine re-apply failed (rolled-back "
                              "baseline keeps serving)")
        return True

    def _quarantine_poison(self, phase, detail: Optional[Dict[str, Any]],
                           t_detect: float) -> None:
        """Post-rollback quarantine: the PR 8 fingerprint diff names what
        the reconcile changed, the guard's per-config deny deltas pin the
        spike — their intersection is the poison set (every changed config
        when the breach had no per-config attribution).  The reconcile is
        then re-applied with ONLY the poison configs reverted to their
        prior compiled artifacts; the compile cache makes that nearly
        free.  Quarantine persists across resyncs (apply_snapshot keeps
        substituting) until the operator ships a FIXED config."""
        from ..snapshots.diff import snapshot_diff

        d = snapshot_diff(phase.baseline.fingerprints or {},
                          phase.snap.fingerprints or {})
        changed = set(d["recompile"])
        suspects = [s for s in (detail or {}).get("suspects", [])
                    if s in changed]
        poison = suspects or sorted(changed)
        if not poison:
            return
        base_by_id = phase.baseline.by_id
        configs: Dict[str, Dict[str, Any]] = {}
        prior: Dict[str, EngineEntry] = {}
        for e in phase.entries:
            if e.id not in poison:
                continue
            configs[e.id] = {
                "poison": (phase.snap.fingerprints or {}).get(e.id),
                "prior": (phase.baseline.fingerprints or {}).get(e.id),
            }
            pe = base_by_id.get(e.id)
            if pe is not None:
                prior[e.id] = pe
            # pe is None → the poison config is NEW this reconcile: it has
            # no prior artifact and quarantines out entirely (the
            # substitution below drops it while keeping it quarantined)
        if not configs:
            return
        self._quarantine = {
            "since": time.time(), "reason": "guard-breach",
            "from_generation": phase.snap.generation,
            "configs": configs,
        }
        self._quarantine_prior = prior
        self._g_quarantine.set(len(configs))
        RECORDER.record("quarantine", lane="engine", detail={
            "configs": sorted(configs),
            "from_generation": phase.snap.generation})
        log.warning("quarantined %d poison config(s) %s: re-applying the "
                    "reconcile with their prior artifacts", len(configs),
                    sorted(configs))
        # re-apply the ORIGINAL entries: the quarantine is armed above, so
        # _substitute_quarantined swaps each poison entry for its prior
        # artifact (or drops a no-prior one) exactly like a control-plane
        # resync would — one substitution path, and the quarantine record
        # stays intact for configs that have no prior to serve
        self._apply_entries(phase.entries, override=True,
                            allow_canary=False)
        if self._last_rollback is not None:
            self._last_rollback["quarantined"] = sorted(configs)
            self._last_rollback["recover_ms"] = round(
                (time.monotonic() - t_detect) * 1e3, 3)

    def _substitute_quarantined(
            self, entries: Sequence[EngineEntry]) -> Sequence[EngineEntry]:
        """Resync guard: while a quarantine is active, incoming entries
        that still carry the POISON fingerprint are substituted with their
        prior artifacts (the control plane keeps resyncing the same bad
        spec — it must not re-serve it); an entry whose fingerprint
        changed (neither poison nor prior) was fixed by the operator and
        is released back to the normal (canaried) path."""
        q = self._quarantine
        if not q:
            return entries
        from ..snapshots.fingerprint import rules_fingerprint

        qc: Dict[str, Dict[str, Any]] = q["configs"]
        out: List[EngineEntry] = []
        still: Dict[str, Dict[str, Any]] = {}
        for e in entries:
            rec = qc.get(e.id)
            if rec is None:
                out.append(e)
                continue
            fp = rules_fingerprint(e.rules) if e.rules is not None else None
            if fp == rec["poison"]:
                still[e.id] = rec
                pe = self._quarantine_prior.get(e.id)
                if pe is not None:
                    out.append(EngineEntry(id=e.id, hosts=list(e.hosts),
                                           runtime=pe.runtime,
                                           rules=pe.rules))
                # no prior artifact: stays quarantined out
            elif fp == rec["prior"]:
                # already the prior artifact (our own quarantine re-apply,
                # or the operator reverting by hand): serve it, keep the
                # quarantine armed against the poison spec resyncing back
                still[e.id] = rec
                out.append(e)
            else:
                log.info("quarantine released for %s: fingerprint changed "
                         "(operator fix) — the new spec takes the normal "
                         "path", e.id)
                out.append(e)
        if still != qc:
            if still:
                self._quarantine = dict(q, configs=still)
            else:
                self.clear_quarantine(note="all poison configs changed")
            self._g_quarantine.set(len(still))
        return out

    def clear_quarantine(self, note: str = "") -> bool:
        q = self._quarantine
        if q is None:
            return False
        RECORDER.record("quarantine-clear", lane="engine", detail={
            "note": note, "configs": sorted(q["configs"])})
        log.info("quarantine cleared (%s): %s", note or "operator",
                 sorted(q["configs"]))
        self._quarantine = None
        self._quarantine_prior = {}
        self._g_quarantine.set(0)
        return True

    @property
    def quarantine_active(self) -> bool:
        return self._quarantine is not None

    def canary_promote(self) -> bool:
        """Operator override (analysis CLI --promote / /debug/canary):
        promote the in-progress canary immediately, guard unconsulted."""
        phase = self._canary
        return self._canary_promote(phase, manual=True) \
            if phase is not None else False

    def canary_rollback(self, reason: str = "manual") -> bool:
        """Operator override: roll back the in-progress canary (no
        quarantine — the operator is driving), or, with no canary active,
        pointer-swap back to the newest retained history generation."""
        phase = self._canary
        if phase is not None:
            return self._canary_rollback(phase, reason=reason,
                                         quarantine=False, manual=True)
        return self.rollback_last(reason=reason)

    def rollback_last(self, reason: str = "manual") -> bool:
        """Manual rollback outside a canary: re-serve the newest retained
        (snapshot, index) pair from the bounded generation history."""
        with self._swap_lock:
            if not self._history:
                return False
            prev_snap, prev_index = self._history.pop()
            clone = prev_snap.clone()
            from_gen = (self._snapshot.generation
                        if self._snapshot is not None else 0)
            self.generation += 1
            clone.generation = self.generation
            clone.change_safety = {"rollback": {
                "from_generation": from_gen, "reason": reason}}
            self._snapshot = clone
            self.index = prev_index
            metrics_mod.snapshot_generation.labels("engine").set(
                clone.generation)
        metrics_mod.snapshot_rollbacks.labels(reason).inc()
        self._last_rollback = {
            "t": time.time(), "reason": reason, "manual": True,
            "from_generation": from_gen,
            "to_generation": clone.generation,
            "detect_ms": None, "detail": None, "quarantined": [],
        }
        RECORDER.record("snapshot-rollback", lane="engine", detail={
            "reason": reason, "from_generation": from_gen,
            "to_generation": clone.generation})
        log.warning("manual rollback: generation %d re-serving as %d",
                    from_gen, clone.generation)
        self.notify_swap_listeners()
        return True

    def canary_observe_external(self, rows, firing, heat,
                                shards=None) -> None:
        """Baseline-cohort guard evidence from OUTSIDE the engine's own
        dispatch — the native fast lane serves the baseline during a
        canary (its C++ snapshot only rebuilds on promotion), so its
        per-batch attribution strengthens the comparison.  Breach handling
        hops to the encode pool: the caller may be a readback thread that
        must never run swap listeners."""
        phase = self._canary
        if phase is None or heat is None or firing is None:
            return
        try:
            phase.guard.observe_batch(False, rows, firing, heat,
                                      shards=shards)
            if phase.guard.breach() is not None or phase.expired():
                _encode_pool(self.dispatch_workers).submit(
                    self._canary_guard_check, phase)
        except Exception:
            log.exception("external canary guard feed failed")

    def change_safety_vars(self) -> Dict[str, Any]:
        """JSON-safe change-safety state (pure read — /debug/canary,
        /debug/vars, the native frontend's mirror, bench artifacts)."""
        phase = self._canary
        q = self._quarantine
        with self._swap_lock:
            # a reconcile thread appends to the bounded deque under this
            # lock; iterating it unguarded can raise mid-reconcile —
            # exactly when the operator is reading the debug surface
            history = [s.generation for s, _ in self._history]
        return {
            "canary_fraction": self.canary_fraction,
            "canary_window_s": self.canary_window_s,
            "canary": phase.to_json() if phase is not None else None,
            "quarantine": ({
                "since": q["since"], "reason": q["reason"],
                "from_generation": q["from_generation"],
                "configs": sorted(q["configs"]),
            } if q is not None else None),
            "history_generations": history,
            "last_rollback": self._last_rollback,
        }

    def _run_policy_analysis(self, entries: Sequence[EngineEntry],
                             snap: "_Snapshot") -> None:
        """Cedar-style semantic pass, once per reconcile (never per
        request): constant-allow/deny rules, shadowed/duplicate rules,
        duplicate-host routing.  Findings are logged ONCE here, counted in
        auth_server_policy_analysis_findings_total{kind,authconfig}, and
        kept JSON-safe for /debug/vars.  Advisory only — a failure inside
        the analyzer must never fail the reconcile."""
        try:
            from ..analysis.policy_analysis import analyze_snapshot

            findings, summary = analyze_snapshot(
                entries, snap.policy, sharded=snap.sharded)
            for f in findings:
                metrics_mod.policy_analysis_findings.labels(
                    f.kind, str(f.detail.get("config", ""))).inc()
            if findings:
                by_kind: Dict[str, int] = {}
                for f in findings:
                    by_kind[f.kind] = by_kind.get(f.kind, 0) + 1
                log.warning(
                    "policy analysis (generation %d): %d finding(s) %s — "
                    "first: %s (full list on /debug/vars)",
                    snap.generation, len(findings), by_kind,
                    findings[0])
            skipped = summary.get("skipped", [])
            for s in skipped:
                metrics_mod.policy_analysis_skipped.labels(
                    str(s.get("config", ""))).inc()
            # the per-config list is bounded (100 entries); any remainder
            # still counts, attributed to the catch-all label so the total
            # always equals skipped_wide
            extra = int(summary.get("skipped_wide", 0)) - len(skipped)
            if extra > 0:
                metrics_mod.policy_analysis_skipped.labels("").inc(extra)
            self._analysis = {
                "generation": snap.generation,
                "findings": [f.to_json() for f in findings],
                "summary": summary,
            }
        except Exception:
            log.exception("policy analysis failed (reconcile unaffected)")

    def _run_lowerability(self, entries: Sequence[EngineEntry],
                          snap: "_Snapshot") -> None:
        """Lowerability report (ISSUE 6 layer 3): classify every config as
        fast-lane or slow-lane with a reason code, once per reconcile.
        Advisory only — surfaced on /debug/vars, counted per (lane,
        reason) in auth_server_lowerability_configs_total, and never a
        reconcile failure."""
        try:
            from ..analysis.translation_validate import (
                lowerability_report,
                snapshot_policies,
            )

            # mesh snapshots compile per-shard policies; the classifier
            # reads each config's CPU-assist leaves from its owning shard
            report = lowerability_report(entries, snapshot_policies(snap))
            for lane, reason, n in report["series"]:
                metrics_mod.lowerability_configs.labels(lane, reason).inc(n)
            # would-be-fast-if-fixed rollup (ISSUE 14): gauges trend the
            # per-reason exile counts across reconciles
            for reason, b in (report.get("blocking_reasons") or {}).items():
                metrics_mod.lowerability_blocking.labels(
                    reason, "configs").set(b["configs"])
                metrics_mod.lowerability_blocking.labels(
                    reason, "sole_blocker").set(b["sole_blocker"])
            report["generation"] = snap.generation
            self._lowerability = report
        except Exception:
            log.exception("lowerability report failed (reconcile unaffected)")

    def snapshot_policy(self) -> Optional[CompiledPolicy]:
        snap = self._snapshot
        return snap.policy if snap else None

    def debug_vars(self) -> Dict[str, Any]:
        """JSON-safe live state for the /debug/vars endpoint: config
        generation, the global dispatcher's backlog + in-flight window
        occupancy, and the compiled snapshot's shape.  Read-only,
        GIL-atomic reads."""
        snap = self._snapshot
        out: Dict[str, Any] = {
            "generation": self.generation,
            "max_batch": self.max_batch,
            "max_delay_s": self.max_delay_s,
            "members_k": self.members_k,
            "queue_depth": len(self._queue),
            "inflight_batches": self._inflight,
            "inflight_peak": self.inflight_peak,
            "max_inflight_batches": self.max_inflight_batches,
            "dispatch_workers": self.dispatch_workers,
            "batch_dedup": self.batch_dedup,
            "verdict_cache": (self._verdict_cache.counts()
                              if self._verdict_cache is not None else None),
            "strict_verify": self.strict_verify,
            "control_plane": self._control_plane,
            "policy_analysis": self._analysis,
            "lowerability": self._lowerability,
            "translation_validation": (getattr(snap, "translation", None)
                                       if snap is not None else None),
            "breaker": self.breaker.to_json(),
            "draining": self._draining,
            "device_timeout_s": self.device_timeout_s,
            "device_rtt_ewma_s": self._device_ewma,
            "admission": self.admission.to_json(),
            "adaptive": self.controller.to_json(),
            "brownout": {
                "enabled": self.brownout,
                "max_batch": self.brownout_max_batch,
                "inflight": self._brownout_inflight,
                "concurrency_limit": self._brownout_limit,
                "decisions": self._brownout_total,
            },
            # lane selection (ISSUE 12): cost-model EWMAs, per-reason
            # decision counts, rows served per lane, speculative outcomes
            "lane_select": self.lanes.to_json(),
            # tenant QoS plane (ISSUE 15, docs/tenancy.md): weights, fair-
            # cut evidence, per-tenant admission/wait state, top-tenant
            # stats and containment — also served on /debug/tenants
            "tenancy": self.tenancy.to_json(),
            "faults": (faults.FAULTS.describe() if faults.ACTIVE else
                       {"armed": False}),
            # decision observability (ISSUE 9, docs/observability.md):
            # heat-map shape + fold evidence, the dead-rule cross-reference
            # against the static findings, decision-log state, the SLO
            # burn-rate windows, and the flight recorder's tail
            "provenance": {
                "expose_deny_reason": prov_mod.EXPOSE_DENY_REASON,
                "heat": (snap.heat.to_json()
                         if snap is not None and snap.heat is not None
                         else None),
                "dead_rules": prov_mod.dead_rule_report(
                    getattr(snap, "heat", None) if snap else None,
                    self._analysis),
                "decisions": {
                    "capacity": prov_mod.DECISIONS.capacity,
                    "sample_n": prov_mod.DECISIONS.sample_n,
                    "records_total": prov_mod.DECISIONS.records_total,
                },
            },
            "slo": self.slo.to_json() if self.slo is not None else None,
            # metadata prefetch cache (ISSUE 14): pinned-document counts,
            # staleness/refresh knobs, hit/miss/stale counters
            "metadata_prefetch": (self.metadata_prefetcher.to_json()
                                  if self.metadata_prefetcher is not None
                                  else None),
            "flight_recorder": RECORDER.to_json(),
            # durable local state plane (ISSUE 20, docs/robustness.md
            # "Crash recovery & warm restart"): warm-start outcome per
            # phase, live staleness, write-behind cadence.  Set by cli.py
            # when --state-dir is armed; None otherwise.
            "state_plane": (self.state_plane.to_json()
                            if getattr(self, "state_plane", None) is not None
                            else None),
            # kernel cost observatory (ISSUE 16, docs/performance.md
            # "Kernel cost model"): the process-wide structural ledger
            # (launches/bytes/pad-waste per lane), the modeled per-row
            # cost lineage, and the jit entry points the serving snapshot
            # can dispatch through (the warm-grid audit surface)
            "kernel_cost": {
                "ledger": LEDGER.to_json(),
                "modeled": self._cost_model.to_json(),
                "entry_points": kernel_cost_mod.entry_points(
                    policy=getattr(snap, "policy", None),
                    sharded=getattr(snap, "sharded", None)),
            },
            "change_safety": self.change_safety_vars(),
            # traffic replay (ISSUE 13, docs/replay.md): capture-log state
            # + the last preflight verdict (also on /debug/replay)
            "replay": {
                "capture": CAPTURE.to_json(),
                "pregate": {
                    "enabled": self.replay_pregate,
                    "budget_s": self.replay_pregate_budget_s,
                    "last": self._last_pregate,
                },
            },
            # decision corpus (ISSUE 19, docs/policy_ci.md): the pregate
            # corpus source, its row counts by origin, the synthesis
            # summary for the serving baseline, and the last verdict
            "corpus": {
                "enabled": bool(self.corpus_pregate),
                "source": self.corpus_pregate or None,
                "budget_s": self.corpus_pregate_budget_s,
                "rows_captured": (len(self._corpus_rows)
                                  if self._corpus_rows is not None else 0),
                "rows_synthetic": len(self._corpus_synth[1]),
                "synthesis": self._corpus_synth[2] or None,
                "load_error": self._corpus_load_error,
                "last": self._last_corpus_pregate,
            },
            "snapshot": None,
        }
        if snap is not None:
            policy = snap.policy
            out["snapshot"] = {
                "configs": len(snap.by_id),
                "sharded": snap.sharded is not None,
                "compiled_configs": (len(policy.config_ids)
                                     if policy is not None else 0),
                "n_attrs": int(getattr(policy, "n_attrs", 0)) if policy else 0,
                "n_leaves": int(getattr(policy, "n_leaves", 0)) if policy else 0,
            }
            if snap.sharded is not None:
                # mesh lane (ISSUE 11): per-device breaker trail, occupancy
                # windows, failover counts, and the per-shard upload bytes
                # of the serving snapshot
                try:
                    out["mesh"] = snap.sharded.mesh_vars()
                except Exception:
                    out["mesh"] = None
        return out

    # ---- request path ----------------------------------------------------

    def lookup(self, host: str) -> Optional[EngineEntry]:
        """Host lookup with :port-stripping retry
        (ref: pkg/service/auth.go:270-289)."""
        entry = self.index.get(host)
        if entry is None and ":" in host:
            entry = self.index.get(host.rsplit(":", 1)[0])
        return entry

    async def check(self, request: CheckRequestModel, span=None,
                    deadline: Optional[float] = None) -> AuthResult:
        """Full request-time flow (ref: pkg/service/auth.go:239-310).
        ``deadline`` is the propagated Envoy Check() deadline (monotonic
        seconds): it bounds the pipeline and arms deadline-aware shedding
        in the batch dispatcher."""
        entry = self.lookup(request.host())
        if entry is None:
            return AuthResult(code=NOT_FOUND, message="Service not found")
        pipeline = AuthPipeline(request, entry.runtime, timeout=self.timeout_s,
                                span=span, deadline=deadline)
        return await pipeline.evaluate()

    def admission_precheck(self, deadline: Optional[float] = None):
        """Front-door overload check for the gRPC/HTTP servers at the
        ACTUAL queue depth: a request arriving into a full hard cap, or
        doomed on arrival while the lane is OVERLOADED, is answered typed
        before a span or pipeline is built.  Deterministic — the
        submit-time gate stays the one true admission point (this never
        consumes CoDel pacing state) and never rejects anything that gate
        would accept.  Returns an AuthResult to serve, or None to
        proceed."""
        rej = self.admission.precheck(len(self._queue), deadline=deadline,
                                      rtt_s=self._device_ewma)
        if rej is None:
            return None
        code, reason = rej
        self.admission.count_reject(reason)
        if code == DEADLINE_EXCEEDED:
            metrics_mod.deadline_shed.labels("engine").inc()
            return AuthResult(code=code,
                              message="rejected at admission: deadline "
                                      "cannot be met")
        return AuthResult(code=code,
                          message=f"server overloaded ({reason})")

    # ---- micro-batching verdicts ----------------------------------------

    def provider_for(self, config_name: str):
        """BatchedVerdictProvider bound to one compiled config — handed to
        PatternMatching evaluators at translate time."""

        async def provider(pipeline, evaluator_slot: int) -> Tuple[bool, bool]:
            rule, skipped, snap = await self.submit(
                pipeline.authorization_json(), config_name, span=pipeline.span,
                deadline=getattr(pipeline, "deadline", None),
                return_snapshot=True)
            # pin the evaluating snapshot on the pipeline: a deny built
            # moments later attributes against THIS corpus, not whatever
            # a concurrent reconcile swapped in since
            pipeline.eval_snapshot = snap
            e = evaluator_slot
            return bool(rule[e]), bool(skipped[e])

        return provider

    def attribution_for(self, config_name: str):
        """Deny-attribution resolver bound to one config (ISSUE 9): handed
        to PatternMatching evaluators at translate time alongside
        provider_for.  Called ONLY on the deny path (slow lane — fast-lane
        denials are attributed per batch instead); returns the provenance
        dict for Envoy dynamic_metadata / X-Ext-Auth-Reason, or None when
        no compiled snapshot covers the config."""

        def attributor(evaluator_slot: int, snap=None):
            # prefer the snapshot that evaluated the request (pinned on
            # the pipeline by provider_for); fall back to the serving one
            # for inline/interpreter callers with no pinned snapshot
            if snap is None:
                snap = self._snapshot
            heat = getattr(snap, "heat", None) if snap is not None else None
            if heat is None:
                return None
            try:
                if snap.sharded is not None:
                    shard, row = snap.sharded.locator[config_name]
                    src = heat.source(row, evaluator_slot, shard=shard)
                else:
                    row = snap.policy.config_ids[config_name]
                    src = heat.source(row, evaluator_slot)
            except (KeyError, AttributeError):
                return None
            return prov_mod.deny_provenance(config_name, evaluator_slot,
                                            src, lane="engine")

        return attributor

    async def submit(self, doc: Any, config_name: str, span: Any = None,
                     deadline: Optional[float] = None,
                     return_snapshot: bool = False,
                     ) -> Tuple[np.ndarray, np.ndarray]:
        """Queue one request for the next micro-batch; resolves to that
        request's per-evaluator (rule_results [E], skipped [E]).  ``span``
        (the request's RequestSpan, optional) lets the batch's DeviceBatch
        span link back to this request's trace.  ``deadline`` (monotonic
        seconds, the propagated Check() deadline) arms deadline-aware
        shedding: a request that cannot make it is failed fast with a
        typed DEADLINE_EXCEEDED before encode, never a wasted kernel.

        The dispatch decision is deferred one loop iteration (call_soon):
        every submit scheduled in the same iteration — a gather, a burst of
        connection reads — lands in one batch cut, while a lone light-load
        request still dispatches immediately after its iteration, never
        waiting a delay timer."""
        if self._draining:
            # graceful drain: stop admitting — already-queued work keeps
            # flowing, but nothing new may extend the drain
            raise CheckAbort(UNAVAILABLE, "server draining")
        # admission control (ISSUE 7): doomed or beyond-the-wait-target
        # work is rejected HERE, typed, before it queues — never after an
        # encode, never as a raw exception.  A doomed-deadline rejection
        # also counts as a deadline shed (it is one, just earlier).
        # Tenant-aware doom depth (ISSUE 15): the deadline predictor sees
        # THIS tenant's fair-share effective depth, not the global queue —
        # one tenant's standing backlog cannot doom another's deadlines.
        ten = self.tenancy
        # tenant-scoped admission (ISSUE 15) runs BEFORE the global gate:
        # quota token bucket, then containment pacing.  Typed
        # RESOURCE_EXHAUSTED naming the tenant; the global OVERLOADED
        # latch and its CoDel state are untouched — every other tenant
        # keeps its full admission budget.  Ordering matters: a contained
        # hot tenant's flood must be paced HERE, or it keeps the shared
        # queue at the global cap and the global gate rejects every
        # tenant's arrivals indiscriminately — the exact collateral
        # containment exists to stop.
        if ten.enabled:
            trej = ten.admit(config_name, depth=len(self._queue),
                             effective_cap=self.admission.effective_cap())
            if trej is not None:
                code, reason = trej
                self.admission.count_reject(reason)
                ten.count_reject(config_name, reason)
                phase = self._canary
                if phase is not None:
                    # per-tenant canary guard feed (ISSUE 15): a canaried
                    # change that pushes its own tenant into tenant-scoped
                    # rejections must accumulate breach evidence
                    try:
                        in_can = phase.in_cohort(doc) or \
                            config_name not in phase.baseline.by_id
                        phase.guard.observe_tenant_rejection(
                            in_can, config_name)
                        self._canary_guard_check(phase)
                    except Exception:
                        log.exception("tenant canary feed failed")
                raise CheckAbort(
                    code, f"tenant {config_name} over its QoS budget "
                          f"({reason}): admission rejected")
        doom_depth = ten.doom_depth(config_name, len(self._queue)) \
            if ten.enabled else None
        rej = self.admission.admit(len(self._queue), deadline=deadline,
                                   rtt_s=self._device_ewma,
                                   doom_depth=doom_depth)
        if rej is not None:
            code, reason = rej
            self.admission.count_reject(reason)
            if code == DEADLINE_EXCEEDED:
                metrics_mod.deadline_shed.labels("engine").inc()
                if ten.enabled and doom_depth is not None:
                    # the tenant-aware predictor doomed it: the shed is
                    # scoped to this tenant's own standing queue — and it
                    # feeds the per-tenant canary guard like every other
                    # tenant-scoped rejection (the guard's documented
                    # attempt set includes tenant-aware doomed sheds)
                    ten.count_reject(config_name, "doomed-deadline")
                    phase = self._canary
                    if phase is not None:
                        try:
                            in_can = phase.in_cohort(doc) or \
                                config_name not in phase.baseline.by_id
                            phase.guard.observe_tenant_rejection(
                                in_can, config_name)
                            self._canary_guard_check(phase)
                        except Exception:
                            log.exception("tenant canary feed failed")
                raise CheckAbort(code, "rejected at admission: deadline "
                                       "cannot be met")
            raise CheckAbort(code, f"server overloaded ({reason}): "
                                   "admission rejected")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        # canary cohort (ISSUE 10): stamped at submit — deterministic over
        # the request's identity, so retries/redispatches keep the cohort
        phase = self._canary
        # a config ADDED by the canaried reconcile has no baseline artifact:
        # its traffic must ride the candidate regardless of cohort (the
        # baseline snapshot cannot decide it — encoding against it would
        # hard-fail and walk the breaker open on healthy hardware)
        in_canary = phase is not None and (
            phase.in_cohort(doc)
            or config_name not in phase.baseline.by_id)
        with self._queue_lock:
            self._queue.append(_Pending(doc, config_name, fut, loop,
                                        span=span, t_enq=time.monotonic(),
                                        deadline=deadline,
                                        canary=in_canary))
            self.controller.observe_arrivals()
            ten.on_enqueue(config_name)
        loop.call_soon(self._maybe_dispatch)
        rule, skipped, snap = await fut
        if return_snapshot:
            # deny attribution (ISSUE 9): the caller gets the snapshot
            # that EVALUATED this request, so a reconcile landing between
            # verdict and deny-response build cannot relabel the rule
            return rule, skipped, snap
        return rule, skipped

    # ---- pipelined dispatch ----------------------------------------------

    def _maybe_dispatch(self) -> None:
        """Cut and launch batches while the window has free slots and the
        queue is non-empty.  Runs on event loops (post-submit) AND on the
        completion thread (post-readback) — redundant calls are cheap
        no-ops, so no timer is ever needed: a full window guarantees a
        future completion, and that completion cuts the next batch.

        The window bound is the ADAPTIVE controller's live window (≤ the
        max_inflight_batches cap); the cut stays completion-driven (grows
        with load).  With the window saturated and a standing queue forming
        (head-of-queue age past half the admission wait target), small
        head-of-queue batches spill to the exact host oracle instead —
        brownout: docs/robustness.md "Overload & brownout"."""
        while True:
            brown = False
            hostsel = None
            diverted = []
            ten = self.tenancy
            with self._queue_lock:
                depth = len(self._queue)
                if not self._queue:
                    break
                # canary phase (ISSUE 10): the cut partitions by cohort —
                # every launched batch rides exactly ONE snapshot
                # generation, so no request can ever observe a torn swap
                phase = self._canary
                if self._inflight < self.controller.window:
                    # the cut itself stays completion-driven (grow with
                    # load, bounded by max_batch): clamping it to the
                    # controller's advisory target would fragment standing
                    # queues into cold pad shapes — see AdaptiveWindow
                    n = min(depth, self.max_batch)
                    # weighted-fair cut (ISSUE 15, docs/tenancy.md): under
                    # contention (more queued than the cut takes) the cut
                    # is a deficit-round-robin selection over per-tenant
                    # virtual queues — a 10x hot tenant fills at most its
                    # weighted share of THIS batch while cold rows keep
                    # arrival order.  Uncontended cuts take everything:
                    # fairness only reorders service, it never re-decides.
                    if ten.enabled and depth > n:
                        batch = ten.cut(self._queue, n)
                    else:
                        batch = [self._queue.popleft() for _ in range(n)]
                    ten.on_dequeue(batch)
                    # noisy-neighbor containment (ISSUE 15): a contained
                    # tenant's rows peel off to the exact host-oracle lane
                    # (verdicts identical by construction) so the device
                    # window and the global brownout latch never see its
                    # overload — bounded by the host concurrency cap;
                    # past it the rows stay in the (already fair) cut.
                    if ten.has_contained():
                        keep, div = ten.split_contained(batch)
                        if div and (self.lanes.host_inflight
                                    < self.lanes.host_limit):
                            batch = keep
                            diverted = _split_cohorts(div, phase)
                            self.lanes.host_inflight += len(diverted)
                    if not batch:
                        parts = []
                    else:
                        # lane selection (ISSUE 12): the cost model decides
                        # at the cut whether these rows are answered
                        # host-side (small cut, host_cost < device_cost) or
                        # ride the device — the host lane consumes NO
                        # window slot
                        which, why = self.lanes.decide(
                            len(batch), self._inflight,
                            self.controller.window)
                        parts = _split_cohorts(batch, phase)
                        if which == L_HOST:
                            self.lanes.host_inflight += len(parts)
                            hostsel = why
                        else:
                            self._inflight += len(parts)
                            if self._inflight > self.inflight_peak:
                                self.inflight_peak = self._inflight
                            inflight = self._inflight
                elif (self.brownout
                      and self._brownout_inflight < self._brownout_limit
                      and (time.monotonic() - self._queue[0].t_enq)
                      > self.admission.target_s / 2):
                    # device pipeline saturated + a standing wait forming:
                    # the OLDEST requests (most deadline-critical) spill to
                    # the host lane — no window slot consumed
                    n = min(depth, self.brownout_max_batch)
                    batch = [self._queue.popleft() for _ in range(n)]
                    ten.on_dequeue(batch)
                    parts = _split_cohorts(batch, phase)
                    self._brownout_inflight += len(parts)
                    brown = True
                else:
                    break
            if not brown and parts:
                # ONE decision per CUT (the metric's unit), outside the
                # queue lock, even when a canary splits the cut into
                # cohort parts.  The inflight counters stay per PART —
                # each part is its own job and decrements once, so the
                # accounting balances (during a canary the host bound may
                # transiently sit one above host_limit: a throttle, not
                # an invariant)
                self.lanes.count(which, why)
            for is_canary, part in diverted:
                # contained-tenant rows: host-oracle lane, its own reason
                # label — NOT brownout (the global spill counters must not
                # read a tenant-scoped clamp as process overload)
                self.lanes.count(L_HOST, TEN_R_CONTAINED)
                _encode_pool(self.dispatch_workers).submit(
                    self._host_lane_job, self._snap_for(phase, is_canary),
                    part, None, TEN_R_CONTAINED)
            for is_canary, part in parts:
                # pinned per batch: double-buffer swap safety.  During a
                # canary the cohort picks its generation; a phase that
                # concluded since the stamp collapses to the (promoted or
                # rolled-back) serving snapshot — still one generation.
                snap = self._snap_for(phase, is_canary)
                if brown:
                    _encode_pool(self.dispatch_workers).submit(
                        self._brownout_job, snap, part)
                elif hostsel is not None:
                    _encode_pool(self.dispatch_workers).submit(
                        self._host_lane_job, snap, part, None, hostsel)
                else:
                    self._g_inflight.set(inflight)
                    _encode_pool(self.dispatch_workers).submit(
                        self._encode_launch_job, snap, part)
        self._g_depth.set(len(self._queue))

    def _snap_for(self, phase, is_canary: bool) -> "Optional[_Snapshot]":
        if phase is None:
            return self._snapshot
        return phase.snap if is_canary else phase.baseline

    def _encode_launch_job(self, snap: Optional[_Snapshot],
                           batch: List[_Pending], attempt: int = 0,
                           spec: Optional[Speculation] = None) -> None:
        """Encode stage (dispatch-worker thread): host encode + fused H2D
        staging + non-blocking kernel launch, then hand the in-flight batch
        to the completion stage.  Never blocks on the device.

        Fault-tolerant (ISSUE 5): expired-deadline requests are shed before
        encode; an open circuit breaker skips the device and decides the
        whole batch through the host oracle; any launch failure routes to
        the retry-once-then-degrade path (_batch_failed).

        Lane selection (ISSUE 12): the latency-critical head — requests
        whose propagated deadline lands inside the expected device answer
        but which the host lane can still meet — is rescued host-side
        BEFORE the shedder would fail it typed; and when this dispatch
        claims the breaker's half-open probe slot, the batch additionally
        rides the host lane speculatively, resolving first-wins (``spec``
        carries the first-wins token across the retry path)."""
        if attempt == 0 and spec is None:
            batch = self._rescue_urgent(snap, batch)
        if spec is None:
            # speculative retries skip the shedder: the host twin owns the
            # deadline story for this batch (it either already answered or
            # will shed at horizon 0 itself) — shedding here too would
            # double-count deadline_shed for rows the twin resolved
            batch = self._shed_expired(batch)
        if not batch:
            self._launch_done()
            return
        if snap is None or (snap.policy is None and snap.sharded is None):
            if spec is not None and not spec.acquire(L_DEVICE):
                self._launch_done()
                return  # the host twin answered: nothing left to fail
            self._resolve_error(batch, CheckAbort(
                UNAVAILABLE, "no compiled policy snapshot"))
            self._launch_done()
            return
        allowed, probe = self.breaker.admit_device()
        if not allowed:
            # a speculative retry arriving into a re-opened breaker must
            # ACQUIRE before degrading (the docstring contract of
            # _batch_failed): a host twin finishing mid-degrade would
            # otherwise fold provenance and count SLO/service twice
            if spec is not None and not spec.acquire(L_DEVICE):
                self.lanes.count_speculative("device-fail")
                self._launch_done()
                return
            self._degrade_batch(snap, batch, reason="breaker-open")
            self._launch_done()
            return
        if (probe and spec is None and attempt == 0
                and self.lanes.enabled and self.lanes.speculative):
            # speculative dual-dispatch: the probe batch is the one batch
            # whose device answer is in genuine doubt (the breaker just
            # half-opened) — race the exact host twin against it so the
            # clients never wait out a probe against a still-sick device.
            # The device half keeps the window slot AND the breaker
            # verdict; the host half is bounded by the host concurrency
            # cap (skipped, not queued, when the cap is taken).
            with self._queue_lock:
                if self.lanes.host_inflight < self.lanes.host_limit:
                    self.lanes.host_inflight += 1
                    spec = Speculation("engine")
            if spec is not None:
                self.lanes.count(L_HOST, R_SPECULATIVE)
                self.lanes.count_speculative("launched")
                _encode_pool(self.dispatch_workers).submit(
                    self._host_lane_job, snap, list(batch), spec,
                    R_SPECULATIVE)
        try:
            if faults.ACTIVE:
                faults.FAULTS.check("encode", "engine")
            item = self._encode_and_launch(snap, batch)
            item.snap = snap
            item.attempt = attempt
            item.spec = spec
        except Exception as e:
            self._batch_failed(snap, batch, attempt, e, spec=spec)
            return
        _completer_submit(item)

    def _shed_expired(self, batch: List[_Pending],
                      horizon_s: Optional[float] = None) -> List[_Pending]:
        """Deadline-aware admission: requests whose propagated Check()
        deadline cannot be met — it lands inside ``horizon_s`` (default:
        one expected device round trip, EWMA) — fail fast with a typed
        DEADLINE_EXCEEDED instead of riding (and wasting) a kernel launch
        whose answer arrives dead.  The brownout lane passes 0: the host
        oracle answers in microseconds, so only already-expired deadlines
        shed there."""
        if all(p.deadline is None for p in batch):
            return batch
        now = time.monotonic()
        horizon = now + (self._device_ewma if horizon_s is None
                         else horizon_s)
        live = [p for p in batch if p.deadline is None or p.deadline > horizon]
        shed = [p for p in batch if p.deadline is not None
                and p.deadline <= horizon]
        if shed:
            metrics_mod.deadline_shed.labels("engine").inc(len(shed))
            self._resolve_error(shed, CheckAbort(
                DEADLINE_EXCEEDED,
                "request shed before dispatch: deadline cannot be met"))
        return live

    def _batch_failed(self, snap: _Snapshot, batch: List[_Pending],
                      attempt: int, exc: Exception,
                      spec: Optional[Speculation] = None) -> None:
        """One launched (or launching) micro-batch failed: count it against
        the circuit breaker, retry ONCE on a fresh dispatch, then re-decide
        every request exactly through the host expression oracle.  The
        in-flight window slot stays held until the batch finally resolves
        (the retry owns it; _launch_done runs exactly once per cut).

        Speculative batches (ISSUE 12): when the host twin already WON the
        race, the clients are answered — the device half's only remaining
        job was the breaker verdict (recorded above), so the slot frees
        without a retry or a second resolution; otherwise the device path
        acquires the batch before degrading, so a host twin finishing
        mid-degrade can never double-resolve or double-fold."""
        self.breaker.record_failure()
        if spec is not None and spec.winner == L_HOST:
            self.lanes.count_speculative("device-fail")
            self._launch_done()
            return
        if attempt == 0:
            metrics_mod.batch_retries.labels("engine").inc()
            log.warning("micro-batch of %d failed (%r): retrying once on a "
                        "fresh dispatch", len(batch), exc)
            _encode_pool(self.dispatch_workers).submit(
                self._encode_launch_job, snap, batch, 1, spec)
            return
        if spec is not None and not spec.acquire(L_DEVICE):
            # the host twin answered while the retry was in flight
            self.lanes.count_speculative("device-fail")
            self._launch_done()
            return
        self._degrade_batch(snap, batch, exc=exc)
        self._launch_done()

    def _rescue_urgent(self, snap: Optional[_Snapshot],
                       batch: List[_Pending]) -> List[_Pending]:
        """Latency-critical head of a device cut (ISSUE 12): requests whose
        propagated deadline lands inside the expected device answer time —
        exactly the set the deadline shedder would fail typed — are peeled
        off and answered on the host lane instead, when its cost model says
        it can make them.  Bounded by the host concurrency cap: past it the
        batch ships whole and the shedder keeps the old behavior."""
        if (not self.lanes.enabled or snap is None
                or all(p.deadline is None for p in batch)):
            return batch
        # the device horizon is the LARGER of the cost model's estimate and
        # the shedder's own EWMA (_shed_expired's horizon): anything the
        # shedder would fail is by definition rescue-eligible, even before
        # the cost model has observed a single device batch
        host = self.lanes.cost.host_cost(1)
        dev = max(self.lanes.cost.device_cost(self._inflight,
                                              self.controller.window),
                  self._device_ewma)
        if not (dev > 0.0) or host >= dev:
            return batch
        now = time.monotonic()
        urgent = [p for p in batch
                  if p.deadline is not None
                  and p.deadline <= now + dev      # device cannot make it
                  and p.deadline > now + host]     # ... but the host can
        if not urgent:
            return batch
        # bound the rescue like any host cut (host_max_rows, tightest
        # deadlines first) and re-test against the CAPPED batch's actual
        # host cost: the oracle decides row-by-row, so admitting 500 rows
        # against host_cost(1) would blow the very deadlines the rescue
        # promised to meet
        urgent.sort(key=lambda p: p.deadline)
        urgent = urgent[:self.lanes.host_max_rows]
        bound = now + self.lanes.cost.host_cost(len(urgent))
        urgent = [p for p in urgent if p.deadline > bound]
        if not urgent:
            return batch
        with self._queue_lock:
            if self.lanes.host_inflight >= self.lanes.host_limit:
                return batch
            self.lanes.host_inflight += 1
        self.lanes.count(L_HOST, R_DEADLINE)
        _encode_pool(self.dispatch_workers).submit(
            self._host_lane_job, snap, urgent, None, R_DEADLINE)
        u = set(id(p) for p in urgent)
        return [p for p in batch if id(p) not in u]

    def _host_lane_job(self, snap: Optional[_Snapshot],
                       batch: List[_Pending],
                       spec: Optional[Speculation] = None,
                       reason: str = R_COST) -> None:
        """First-class host serving lane (ISSUE 12, encode-pool thread):
        one batch decided through the exact host oracle because the cost
        model chose it (small cut / deadline rescue / speculative twin) —
        NOT a failure and NOT overload spill (the breaker and the brownout
        counters stay untouched).  Holds no window slot; bounded by the
        lane's own concurrency counter.

        Speculative twins resolve first-wins: the twin acquires the batch
        before any request-level effect (resolution, SLO burn, admission
        service count, provenance fold), so whichever lane loses the race
        contributes nothing but its own cost-model observation."""
        released = False

        def release_slot() -> None:
            # the concurrency slot bounds oracle CPU, not resolution
            # fan-out: release it as soon as the decisions are computed,
            # so a caller awaiting one of these futures can land its next
            # small cut back on the host lane instead of racing the pool
            # thread to the slot and spilling to the device as host-busy
            nonlocal released
            if released:
                return
            released = True
            with self._queue_lock:
                self.lanes.host_inflight -= 1

        try:
            # host lane horizon 0: the oracle answers in microseconds, so
            # only already-expired deadlines shed here
            live = self._shed_expired(batch, horizon_s=0.0)
            if not live:
                return
            if snap is None or (snap.policy is None and snap.sharded is None):
                if spec is None or spec.acquire(L_HOST):
                    self._resolve_error(live, CheckAbort(
                        UNAVAILABLE, "no compiled policy snapshot"))
                return
            by_loop, failed, n_ok, results = self._host_decide_batch(
                snap, live, fold=False)
            if spec is not None:
                if failed:
                    # exactness first: a partially-failed host twin never
                    # claims — the device half owns the whole batch
                    self.lanes.count_speculative("host-fail")
                    return
                if not spec.acquire(L_HOST):
                    return  # the device answered first: confirmation only
                self.lanes.count_speculative("host-win")
            # request-level effects — exactly once per batch, winner-only
            self._fold_host_provenance(snap, live, results,
                                       lane="engine-host")
            if n_ok:
                self.lanes.count_rows(L_HOST, n_ok)
                self.admission.observe_service(n_ok)
                n_bad = 0
                if self.slo is not None:
                    now = time.monotonic()
                    n_bad = min(n_ok, sum(
                        1 for p in live
                        if p.t_enq and now - p.t_enq > self.slo.slo_s))
                    self.slo.observe(n_ok, n_bad)
                self.lanes.cost.observe_slo(L_HOST, n_ok, n_bad)
            release_slot()
            self._resolve_host_decisions(by_loop, failed)
        except Exception:
            log.exception("host-lane batch failed")
            if spec is not None:
                self.lanes.count_speculative("host-fail")
            else:
                self._resolve_error(batch, CheckAbort(
                    UNAVAILABLE, "policy evaluation unavailable"))
        finally:
            release_slot()
            self._maybe_dispatch()

    def _host_decide_batch(self, snap: _Snapshot, batch: List[_Pending],
                           fold: bool = True, lane: str = "engine"):
        """Row-by-row exact host decisions for one batch (the oracle is the
        kernel's differential-test reference, membership overflow
        included).  Returns (resolutions-by-loop, failed-futures-by-loop,
        n_ok, results); rows whose oracle run itself failed land in
        ``failed`` and resolve typed UNAVAILABLE, fail closed.
        ``fold=False`` defers the provenance fold to the caller — the
        speculative host twin must not fold until it WINS the race
        (exactly one fold per batch, whoever resolves).

        Attribution (ISSUE 9): the oracle's (rule, skipped) columns fold
        into the SAME heat map / decision log as the device lane — a
        degraded or brownout decision attributes identically to the kernel
        decision it replaced (the oracle is the kernel's reference)."""
        from ..models.policy_model import host_results

        t0 = time.monotonic()
        by_loop: Dict[Any, list] = {}
        failed: Dict[Any, list] = {}
        n_ok = 0
        if snap.sharded is not None:
            results = snap.sharded.host_decide_many(
                [p.config_name for p in batch], [p.doc for p in batch])
        else:
            results = []
            for p in batch:
                try:
                    row = snap.policy.config_ids[p.config_name]
                    _, rule, skipped = host_results(snap.policy, p.doc, row)
                    results.append((rule, skipped))
                except Exception:
                    log.exception("host oracle failed for config %r "
                                  "(fail-closed UNAVAILABLE)", p.config_name)
                    results.append(None)
        for p, res in zip(batch, results):
            if res is None:
                failed.setdefault(p.loop, []).append(p.future)
            else:
                n_ok += 1
                by_loop.setdefault(p.loop, []).append(
                    (p.future,) + tuple(res) + (snap,))
        # cost-model feed (ISSUE 12): EVERY host-oracle batch teaches the
        # per-row service EWMA — lane-selected, brownout and degrade alike
        # (an engine that spent its warm-up degrading must not enter lane
        # selection with the optimistic cold-start estimate)
        if batch:
            self.lanes.cost.observe_host(time.monotonic() - t0, len(batch))
            # structural cost fold (ISSUE 16): every host-oracle batch —
            # lane-selected, brownout, degrade — counts ZERO device
            # launches and zero H2D/D2H bytes, exactly
            LEDGER.observe("host", rows=len(batch))
        if fold:
            self._fold_host_provenance(snap, batch, results, lane=lane)
        return by_loop, failed, n_ok, results

    def _fold_host_provenance(self, snap: _Snapshot, batch: List[_Pending],
                              results, lane: str = "engine") -> None:
        """Heat-map/decision-log fold for the host-oracle lanes (degrade +
        brownout): stack the per-row (rule, skipped) columns and run the
        same per-batch fold the device completion uses."""
        try:
            heat = getattr(snap, "heat", None)
            if heat is None:
                return
            pendings, rows, shards, rules, skips = [], [], [], [], []
            for p, res in zip(batch, results):
                if res is None:
                    continue
                if snap.sharded is not None:
                    s, r = snap.sharded.locator[p.config_name]
                    shards.append(s)
                    rows.append(r)
                else:
                    rows.append(snap.policy.config_ids[p.config_name])
                pendings.append(p)
                rules.append(np.asarray(res[0], dtype=bool))
                skips.append(np.asarray(res[1], dtype=bool))
            if not rows:
                return
            self._observe_provenance(
                snap, pendings, np.asarray(rows), np.stack(rules),
                np.stack(skips),
                shards=(np.asarray(shards) if snap.sharded is not None
                        else None), lane=lane)
        except Exception:
            log.exception("host-lane provenance fold failed "
                          "(decision unaffected)")

    def _observe_provenance(self, snap: _Snapshot, pendings: List[_Pending],
                            rows, own_rule, own_skipped, shards=None,
                            lane: str = "engine", waits=None):
        """Per-batch decision-observability fold: which-rule-fired columns →
        the snapshot's heat map (vectorized composite-key bincount), plus at
        most ONE head-sampled decision record.  Never raises — a telemetry
        bug must not re-dispatch a decided batch."""
        phase = self._canary
        try:
            heat = getattr(snap, "heat", None)
            if heat is None:
                return None
            from ..ops.pattern_eval import firing_columns

            firing = firing_columns(own_rule, own_skipped)
            p = pendings[0] if pendings else None
            now_m = time.monotonic()
            prov_mod.fold_and_sample(
                heat, rows, firing, len(pendings), lane=lane, shards=shards,
                host=_doc_host(p.doc) if p is not None else "",
                latency_ms=((now_m - p.t_enq) * 1e3
                            if p is not None and p.t_enq else 0.0),
                generation=snap.generation,
                # stratified sampling (ISSUE 15): each sampled TENANT's
                # record carries ITS OWN request's host/latency, not the
                # batch head's — called only for sampled tenants, bounded
                host_of=lambda i: _doc_host(pendings[i].doc),
                latency_of=lambda i: ((now_m - pendings[i].t_enq) * 1e3
                                      if pendings[i].t_enq else 0.0))
            # tenant axis (ISSUE 15): the SAME per-batch seam feeds the
            # per-tenant request/deny counters, wait EWMAs and SLO burn —
            # and because EVERY lane's completion funnels through here
            # (device finalize, host lane, brownout spill, host-oracle
            # degrade), contained and degraded traffic burns the right
            # tenant's accounting too (the old gap the parity test pins).
            # Two clocks, deliberately distinct: ``waits`` (queue waits,
            # captured at the CUT by the device path; sojourn on the
            # host-oracle lanes where service is microseconds) feed the
            # per-tenant CoDel wait signal, while the SLO bad mask reads
            # the full SOJOURN at completion — end-to-end latency is what
            # the --slo-ms budget is about.
            if self.tenancy.enabled:
                sojourn = np.asarray([(now_m - q.t_enq) if q.t_enq else 0.0
                                      for q in pendings])
                self.tenancy.fold(
                    heat, rows, firing=firing, shards=shards,
                    waits=(waits if waits is not None else sojourn),
                    bad_mask=(sojourn > self.slo.slo_s
                              if self.slo is not None else None),
                    lane=lane)
            # traffic capture (ISSUE 13): the full-fidelity sampled request
            # log rides the same per-batch seam as the decision sampler —
            # one enabled check per batch when off; when on, each sampled
            # decision's raw (authconfig, doc, verdict) tuple is queued for
            # the capture log's own drain thread (encode/persist happen
            # there, never here)
            if CAPTURE.enabled:
                pf = self.metadata_prefetcher
                md_digests: Dict[str, Optional[str]] = {}
                for i in CAPTURE.sample_indices(len(pendings)):
                    pi = pendings[i]
                    # metadata reproducibility (ISSUE 14): stamp which
                    # pinned prefetched documents this config's decision
                    # evaluated under (None: nothing pinned)
                    md = None
                    if pf is not None:
                        if pi.config_name not in md_digests:
                            md_digests[pi.config_name] = pf.digest_for(
                                pi.config_name)
                        md = md_digests[pi.config_name]
                    CAPTURE.offer(pi.config_name, pi.doc, int(firing[i]),
                                  lane, snap.generation,
                                  metadata_doc_digest=md)
            # canary guards (ISSUE 10): the SAME attribution columns feed
            # the per-cohort deny-rate comparison — batches are cohort-
            # homogeneous, so the evaluating snapshot names the cohort
            if phase is not None and \
                    (snap is phase.snap or snap is phase.baseline):
                phase.guard.observe_batch(snap is phase.snap, rows, firing,
                                          heat, shards=shards)
        except Exception:
            log.exception("provenance fold failed (decision unaffected)")
            return None
        if phase is not None:
            try:
                self._canary_guard_check(phase)
            except Exception:
                log.exception("canary guard check failed")
        return firing

    @staticmethod
    def _resolve_host_decisions(by_loop, failed) -> None:
        for loop, resolutions in by_loop.items():
            try:
                loop.call_soon_threadsafe(_resolve_many, resolutions)
            except RuntimeError:
                pass  # loop closed since submit: its futures are moot
        for loop, futs in failed.items():
            try:
                loop.call_soon_threadsafe(_fail_many, futs, CheckAbort(
                    UNAVAILABLE, "policy evaluation unavailable"))
            except RuntimeError:
                pass

    def _degrade_batch(self, snap: _Snapshot, batch: List[_Pending],
                       exc: Optional[Exception] = None,
                       reason: str = "device-failure") -> None:
        """Final fallback lane: every request re-decided row-by-row through
        the host expression oracle.  Fail-closed typed UNAVAILABLE ONLY for
        rows where the oracle itself fails."""
        by_loop, failed, n_ok, _ = self._host_decide_batch(snap, batch)
        if n_ok:
            metrics_mod.degraded_decisions.labels("engine").inc(n_ok)
            self.admission.observe_service(n_ok)
            if self.slo is not None:
                now = time.monotonic()
                n_bad = sum(1 for p in batch if p.t_enq
                            and now - p.t_enq > self.slo.slo_s)
                self.slo.observe(n_ok, min(n_bad, n_ok))
            if exc is not None:
                log.warning("micro-batch of %d re-decided host-side after "
                            "device failure (%r)", len(batch), exc)
        n_failed = sum(len(futs) for futs in failed.values())
        self.error_total += n_failed
        phase = self._canary
        if n_failed and phase is not None and batch:
            # typed-error guard feed (ISSUE 10): rows the degrade oracle
            # itself fails are serving errors too — a canary artifact
            # broken on BOTH lanes must still accumulate breach evidence
            try:
                phase.guard.observe_errors(bool(batch[0].canary), n_failed)
                self._canary_guard_check(phase)
            except Exception:
                log.exception("canary error feed failed")
        self._resolve_host_decisions(by_loop, failed)

    def _brownout_job(self, snap: Optional[_Snapshot],
                      batch: List[_Pending]) -> None:
        """Brownout lane (encode-pool thread): a small head-of-queue batch
        decided through the exact host oracle while the device window is
        saturated.  Identical verdicts to the device by construction (the
        oracle is the kernel's reference); throughput degrades, correctness
        never.  No window slot is held — brownout concurrency is bounded by
        its own counter."""
        try:
            # horizon 0: the host oracle answers in microseconds — a
            # deadline the DEVICE's inflated RTT could not meet is exactly
            # what this lane exists to rescue
            batch = self._shed_expired(batch, horizon_s=0.0)
            if not batch:
                return
            if snap is None or (snap.policy is None and snap.sharded is None):
                self._resolve_error(batch, CheckAbort(
                    UNAVAILABLE, "no compiled policy snapshot"))
                return
            by_loop, failed, n_ok, _ = self._host_decide_batch(snap, batch)
            if n_ok:
                metrics_mod.brownout_decisions.labels("engine").inc(n_ok)
                metrics_mod.brownout_batches.labels("engine").inc()
                self._brownout_total += n_ok
                self.admission.observe_service(n_ok)
                if self.slo is not None:
                    now = time.monotonic()
                    n_bad = sum(1 for p in batch if p.t_enq
                                and now - p.t_enq > self.slo.slo_s)
                    self.slo.observe(n_ok, min(n_bad, n_ok))
            self._resolve_host_decisions(by_loop, failed)
        except Exception:
            # a brownout bug must fail its own batch typed, never leak or
            # wedge the queue
            log.exception("brownout batch failed")
            self._resolve_error(batch, CheckAbort(
                UNAVAILABLE, "policy evaluation unavailable"))
        finally:
            with self._queue_lock:
                self._brownout_inflight -= 1
            self._maybe_dispatch()

    @staticmethod
    def _route_done(item: "_Inflight", ok: bool) -> None:
        """Terminal mesh-route accounting for one in-flight batch:
        per-device breaker verdicts + occupancy release (idempotent; no-op
        on the single-corpus lane)."""
        route = item.route
        if route is None:
            return
        item.route = None
        try:
            sharded = getattr(item.snap, "sharded", None) \
                if item.snap is not None else None
            if sharded is not None:
                sharded.complete_route(route, ok, lane="engine")
            else:
                route.release()
        except Exception:
            log.exception("mesh route accounting failed (batch unaffected)")

    def _watchdog_fire(self, item: "_Inflight") -> None:
        """Completer watchdog hand-off: an in-flight batch wedged past
        --device-timeout is abandoned (its readback may still arrive — the
        handle is simply dropped) and fed the retry/degrade path as a
        breaker-counted failure."""
        self._route_done(item, ok=False)
        metrics_mod.watchdog_timeouts.labels("engine").inc()
        RECORDER.record("watchdog-timeout", lane="engine", detail={
            "requests": len(item.batch), "attempt": item.attempt,
            "device_timeout_s": self.device_timeout_s})
        log.warning("device batch (%d requests, attempt %d) wedged past "
                    "--device-timeout %.3fs: abandoning the handle",
                    len(item.batch), item.attempt, self.device_timeout_s)
        self._batch_failed(item.snap, item.batch, item.attempt,
                           TimeoutError("device readback watchdog timeout"),
                           spec=item.spec)

    # ---- graceful drain --------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop admitting new requests (submit fails fast with a typed
        UNAVAILABLE; /readyz flips to 503 so the LB stops routing here).
        Queued and in-flight work keeps flowing to completion."""
        if not self._draining:
            self._draining = True
            phase = self._canary
            if phase is not None:
                # a mid-drain window expiry must not promote/rollback into
                # a tearing-down process (swap listeners would rebuild a
                # stopped native frontend); the canary stays undecided and
                # cohort routing keeps serving until exit
                phase.cancel_timer()
            if self.metadata_prefetcher is not None:
                # the refresher must not re-pin into a tearing-down
                # process; stale pins only ever fall through to the live
                # fetch, so stopping early is always safe
                self.metadata_prefetcher.stop(timeout_s=0.5)
            RECORDER.record("drain", lane="engine", detail={
                "queue": len(self._queue), "inflight": self._inflight})
            log.info("engine draining: admission stopped "
                     "(queue=%d, inflight=%d)", len(self._queue),
                     self._inflight)

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued request and in-flight batch has
        resolved (or the timeout expires — False).  Call from a worker
        thread (the CLI's SIGTERM path runs it via run_in_executor);
        begin_drain() is implied."""
        self.begin_drain()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._queue_lock:
                idle = (not self._queue and self._inflight == 0
                        and self._brownout_inflight == 0
                        and self.lanes.host_inflight == 0)
            if idle:
                return True
            time.sleep(0.01)
        with self._queue_lock:
            log.warning("engine drain timed out after %.1fs "
                        "(queue=%d, inflight=%d, brownout=%d)", timeout_s,
                        len(self._queue), self._inflight,
                        self._brownout_inflight)
        return False

    # ---- fleet plane (ISSUE 18) ------------------------------------------

    def fleet_health(self) -> Dict[str, Any]:
        """The fleet router's per-replica health dict — exactly the
        /readyz + admission + breaker evidence (service/http_server.py
        readyz; runtime/admission.py health_signal), so in-process
        replicas and process replicas polled over HTTP publish one shape.
        Every read here is GIL-atomic: safe from the router's decision
        path under load."""
        h = self.admission.health_signal(len(self._queue))
        h["ready"] = self._snapshot is not None and not self._draining
        h["draining"] = self._draining
        h["breaker_open"] = self.breaker.state != "closed"
        h["generation"] = self.generation
        return h

    def fleet_fold(self) -> Dict[str, Any]:
        """One replica's fold for the fleet aggregator (fleet/aggregate.py):
        health + CUMULATIVE counters (the aggregator differences
        consecutive folds into deltas; cumulatives survive a missed
        publish) + the per-tenant rate EWMAs whose fleet-wide sum is the
        global tenant share.  Small and cadence-published — never anything
        per-request."""
        fold = self.fleet_health()
        fold["errors"] = self.error_total
        if self.slo is not None:
            fold["slo_total"] = self.slo.total
            fold["slo_bad"] = self.slo.bad_total
        else:
            fold["slo_total"] = fold["slo_bad"] = 0
        ten = self.tenancy
        if ten.enabled:
            fold["tenants"] = ten.stats.export_fold()
            fold["tenant_rejects"] = {
                t: sum(r.values())
                for t, r in list(ten.admission.rejected.items())}
        else:
            fold["tenants"] = {}
            fold["tenant_rejects"] = {}
        # fleet-pressure gate for the GLOBAL containment check: this
        # replica's wait is hot or its admission gate left HEALTHY
        fold["wait_hot"] = bool(
            self.admission.wait_ewma > self.admission.target_s
            or self.admission.overloaded)
        fold["admission_state"] = ("OVERLOADED" if self.admission.overloaded
                                   else "HEALTHY")
        return fold

    def _cache_keys(self, keys, n, snap, rows=None):
        """Full verdict-cache keys for one batch.  Single-corpus snapshots
        key per config: (encoding epoch, config source fingerprint, row
        bytes) — entries for configs a swap did NOT touch stay reachable
        across the swap (ISSUE 8: the verdict cache survives churn).  Mesh
        snapshots carry the same tokens per (shard, row)
        (snap.mesh_tokens, built in _encode_and_launch_sharded); the
        generation fallback here only serves snapshots with no tokens at
        all (loaded replicas)."""
        if keys is None or self._verdict_cache is None:
            return None
        tokens = snap.cache_tokens
        if tokens is not None and rows is not None:
            return [(tokens[rows[r]], keys[r]) for r in range(n)]
        gen = snap.generation
        return [(gen, keys[r]) for r in range(n)]

    def _dedup_plan(self, keys, ckeys, n, eligible):
        """Shared cache-lookup + within-batch-collapse plan for one
        micro-batch.  ``eligible(r)`` gates verdict-cache participation
        (cacheable config AND not a lossy host-fallback row — the
        fallback flag itself already rides the row keys).  ``ckeys`` are
        the full cache keys (per-config tokens folded in; None = cache
        off).  Returns (cached {row: value}, miss_rows, unique_rows,
        inverse, eligible_misses)."""
        from ..compiler.pack import dedup_rows

        cache = self._verdict_cache
        cached: Dict[int, Any] = {}
        eligible_misses = 0
        if cache is not None and ckeys is not None:
            miss_rows: List[int] = []
            for r in range(n):
                if eligible(r):
                    v = cache.get(ckeys[r])
                    if v is not None:
                        cached[r] = v
                        continue
                    eligible_misses += 1
                miss_rows.append(r)
        else:
            miss_rows = list(range(n))
        if self.batch_dedup and keys is not None:
            unique_rows, inverse = dedup_rows(keys, miss_rows)
        else:
            unique_rows, inverse = miss_rows, np.arange(len(miss_rows))
        return cached, miss_rows, unique_rows, inverse, eligible_misses

    def _cache_insert(self, ckeys, unique_rows, eligible,
                      own_rule, own_skipped) -> int:
        """Insert freshly-evaluated unique rows under their full cache
        keys (captured from the batch's PINNED snapshot at encode time —
        a swap admitted mid-dispatch can never relabel in-flight work);
        returns the eviction delta for this batch's metrics fold."""
        cache = self._verdict_cache
        if cache is None or ckeys is None:
            return 0
        evict0 = cache.evictions
        for r in unique_rows:
            if eligible(r):
                cache.put(ckeys[r],
                          (own_rule[r].copy(), own_skipped[r].copy()))
        return cache.evictions - evict0

    def _encode_and_launch(self, snap: _Snapshot,
                           batch: List[_Pending]) -> _Inflight:
        """Encode + launch one micro-batch; returns the in-flight handle.
        The finalize closure runs on the completion stage with the readback
        as numpy and applies the host-fallback oracle there.

        Between encode and launch sit the two hot-path cuts of ISSUE 3:
        rows whose (generation, row-digest) verdict is cached resolve
        WITHOUT the device, and the remaining rows collapse to unique rows
        only — the fused H2D buffer carries unique work, verdicts fan back
        out through the inverse map on completion (bit-identical: the
        kernel is a pure per-row function of the operand bytes)."""
        n = len(batch)
        pad = _bucket(n)
        t0 = time.monotonic()
        waits = np.array([(t0 - p.t_enq) if p.t_enq else 0.0 for p in batch])
        # the CoDel signal rides the batch cut: the cut's MINIMUM wait is
        # the standing-queue indicator the admission state flips on.  A
        # RETRIED batch re-feeds waits measured from the original enqueue,
        # so the signal is total sojourn (queue + failed attempts) by
        # design: a device so flaky that work is stuck re-dispatching is
        # overload from the client's seat, whatever the queue depth says
        self.admission.observe_waits(waits, now=t0)
        binfo = {"batch_size": n, "pad": pad, "eff": 0,
                 "start_ns": time.time_ns(), "duration_s": 0.0}
        docs = [p.doc for p in batch]
        names = [p.config_name for p in batch]
        if snap.sharded is not None:
            return self._encode_and_launch_sharded(
                snap, batch, docs, names, n, pad, t0, binfo, waits)
        from ..compiler.pack import batch_row_keys, pack_batch, select_rows
        from ..ops.pattern_eval import (dispatch_fused, packed_width,
                                        staged_h2d_bytes, unpack_verdicts)

        policy = snap.policy
        rows = [policy.config_ids[name] for name in names]
        enc = encode_batch(policy, docs, rows, batch_pad=pad)
        db = pack_batch(policy, enc)
        has_dfa = snap.params["dfa_tables"] is not None
        cacheable = policy.config_cacheable
        keys = (batch_row_keys(db, n)
                if n and (self.batch_dedup or self._verdict_cache is not None)
                else None)
        ckeys = self._cache_keys(keys, n, snap, rows=rows)

        def eligible(r: int) -> bool:
            return bool(cacheable[rows[r]]) and not bool(db.host_fallback[r])

        cached, miss_rows, unique_rows, inverse, elig_miss = self._dedup_plan(
            keys, ckeys, n, eligible)
        u = len(unique_rows)
        if u == n:
            db_u, pad_u = db, pad  # nothing collapsed: ship the batch as-is
        elif u:
            pad_u = _bucket(u)
            db_u = select_rows(db, unique_rows, batch_pad=pad_u)
        else:
            db_u, pad_u = None, 0  # every row cache-resolved: no dispatch
        binfo["pad"] = pad_u
        binfo["device_rows"] = u
        binfo["eff"] = (int(db_u.attr_bytes.shape[-1])
                        if has_dfa and db_u is not None else 0)
        metrics_mod.observe_pipeline_stage(
            "engine", "encode", time.monotonic() - t0)
        # span window opens at the launch: encode/pack are host work
        t1 = time.monotonic()
        binfo["start_ns"] = time.time_ns()
        if db_u is not None:
            if faults.ACTIVE:
                faults.FAULTS.check("h2d", "engine")
                faults.FAULTS.check("kernel", "engine")
            handle = dispatch_fused(snap.params, db_u)
            if faults.ACTIVE:
                handle = faults.FAULTS.wrap_handle(handle, "engine")
        else:
            handle = np.zeros((0, 1), dtype=np.uint8)  # completes instantly
        metrics_mod.observe_pipeline_stage(
            "engine", "launch", time.monotonic() - t1)
        E = int(policy.eval_rule.shape[1])
        # structural cost fold (ISSUE 16): ONE launch per well-formed cut;
        # a fully cache/dedup-resolved cut counts zero launches and zero
        # bytes.  H2D = the fused staging buffer bytes, D2H = the bitpacked
        # [pad_u, W] readback
        LEDGER.observe(
            "engine", rows=n, device_rows=u,
            launches=1 if db_u is not None else 0,
            h2d_bytes=staged_h2d_bytes(db_u) if db_u is not None else 0,
            d2h_bytes=pad_u * packed_width(1 + 2 * E) if db_u is not None else 0,
            pad_rows=pad_u,
            dedup_avoided_rows=len(miss_rows) - u,
            cache_avoided_rows=len(cached))
        max_fallback = self.max_fallback_per_batch

        def finalize(packed):
            # padded eval columns are TRUE_SLOT/False — same tail semantics
            # as the kernel's own padded rows
            own_rule = np.ones((n, E), dtype=bool)
            own_skipped = np.zeros((n, E), dtype=bool)
            if u:
                unpacked = unpack_verdicts(packed, 1 + 2 * E)
                mr = np.asarray(miss_rows)
                own_rule[mr] = unpacked[inverse, 1:1 + E]
                own_skipped[mr] = unpacked[inverse, 1 + E:1 + 2 * E]
            for r, (c_rule, c_skip) in cached.items():
                own_rule[r] = c_rule
                own_skipped[r] = c_skip
            n_fallback = int(np.count_nonzero(db.host_fallback[:n]))
            if n_fallback:
                # compact payload was lossy for these rows (membership
                # overflow): exact re-decision on host via the expression
                # oracle, bounded by the fallback cap (beyond it: deny
                # fail-closed + counter)
                from ..models.policy_model import apply_host_fallback, host_results

                apply_host_fallback(
                    lambda r: host_results(policy, docs[r], rows[r])[1:],
                    np.nonzero(db.host_fallback[:n])[0],
                    own_rule, own_skipped, max_fallback,
                )
            evict_d = self._cache_insert(ckeys, unique_rows, eligible,
                                         own_rule, own_skipped)
            metrics_mod.observe_dedup("engine", n, u, len(cached),
                                      elig_miss, evict_d)
            # attribution (ISSUE 9): one per-batch fold over the FINAL
            # columns — cache hits, dedup fan-out and fallback rows are
            # already folded back in, so every path attributes identically.
            # ``waits`` are the cut-time QUEUE waits (the tenant wait
            # signal must not absorb the device round trip)
            self._observe_provenance(snap, batch, rows, own_rule,
                                     own_skipped, waits=waits)
            return own_rule, own_skipped, n_fallback

        return _Inflight(self, batch, handle, finalize, binfo, waits)

    def _encode_and_launch_sharded(self, snap, batch, docs, names, n, pad,
                                   t0, binfo, waits) -> _Inflight:
        """Mesh-sharded mirror of the dedup/cache encode stage: the row key
        additionally folds in shard_of/row_of (config identity on the
        mesh), and the unique sub-batch re-pads to the dp-aligned bucket."""
        from ..ops.pattern_eval import unpack_verdicts

        sharded = snap.sharded
        # occupancy-shaped padding (ISSUE 17, fused lane only): the stacked
        # pad bucket follows the BUSIEST shard's row count replicated over
        # the dp axis, so a shard-skewed batch pads each dp slice to uniform
        # per-shard work instead of the global cut size.  Opt-in with the
        # fused layout — the unfused mesh path keeps its exact pad pins.
        if getattr(sharded, "has_fused", False) and n:
            from ..ops.fused_kernel import occupancy_pad

            counts = [0] * sharded.n_shards
            for nm in names[:n]:
                loc = sharded.locator.get(nm)
                if loc is not None:
                    counts[loc[0]] += 1
            pad = occupancy_pad(counts, sharded.mesh.shape["dp"], n,
                                floor=16)
        enc = sharded.encode(docs, names, batch_pad=pad)
        keys = (sharded.row_keys(enc, n)
                if n and (self.batch_dedup or self._verdict_cache is not None)
                else None)
        # mesh verdict-cache keying (ISSUE 11, PR 8 parity): (encoding
        # epoch of the owning shard, config source fingerprint) tokens —
        # entries of configs a reconcile did not touch survive the swap;
        # generation keying remains only as the loaded-snapshot fallback
        tokens = getattr(snap, "mesh_tokens", None)
        if keys is not None and self._verdict_cache is not None \
                and tokens is not None:
            ckeys = [(tokens[enc.shard_of[r]][enc.row_of[r]], keys[r])
                     for r in range(n)]
        else:
            ckeys = self._cache_keys(keys, n, snap)

        def eligible(r: int) -> bool:
            return (bool(sharded.config_cacheable[enc.shard_of[r],
                                                  enc.row_of[r]])
                    and not bool(enc.host_fallback[r]))

        cached, miss_rows, unique_rows, inverse, elig_miss = self._dedup_plan(
            keys, ckeys, n, eligible)
        u = len(unique_rows)
        binfo["device_rows"] = u
        if u == n:
            enc_u = enc
            binfo["pad"] = int(enc.attrs_val.shape[0])
        elif u:
            enc_u = sharded.select_rows(enc, unique_rows, batch_pad=_bucket(u))
            binfo["pad"] = int(enc_u.attrs_val.shape[0])
        else:
            enc_u = None
            binfo["pad"] = 0
        metrics_mod.observe_pipeline_stage(
            "engine", "encode", time.monotonic() - t0)
        t1 = time.monotonic()
        binfo["start_ns"] = time.time_ns()
        route = None
        if enc_u is not None:
            if faults.ACTIVE:
                faults.FAULTS.check("h2d", "engine")
                faults.FAULTS.check("kernel", "engine")
            # breaker-aware routed launch (ISSUE 11): full-mesh shard_map
            # when every device is healthy; a device that fails its probe
            # records on ITS breaker and the batch fails over to the
            # healthy device with the emptiest in-flight window.
            # MeshUnavailable (all devices down) propagates into the
            # existing retry-once-then-degrade path — host-oracle decisions
            # begin only past that point.
            handle, route = sharded.dispatch_routed(enc_u, lane="engine")
            if faults.ACTIVE:
                handle = faults.FAULTS.wrap_handle(handle, "engine")
        else:
            handle = np.zeros((0, 1), dtype=np.uint8)
        metrics_mod.observe_pipeline_stage(
            "engine", "launch", time.monotonic() - t1)
        # structural cost fold (ISSUE 16), mesh lane: the shard-step
        # launch + bytes were counted at the dispatch site (one collective
        # launch per step, failovers included); this fold adds the
        # batch-level story — real rows, dedup/cache cuts, pad waste
        LEDGER.observe(
            "mesh", rows=n, device_rows=u, pad_rows=binfo["pad"],
            dedup_avoided_rows=len(miss_rows) - u,
            cache_avoided_rows=len(cached))
        E = int(sharded.shards[0].eval_rule.shape[1])
        max_fallback = self.max_fallback_per_batch

        def finalize(packed):
            own_rule = np.ones((n, E), dtype=bool)
            own_skipped = np.zeros((n, E), dtype=bool)
            if u:
                unpacked = unpack_verdicts(np.asarray(packed), 1 + 2 * E)
                mr = np.asarray(miss_rows)
                own_rule[mr] = unpacked[inverse, 1:1 + E]
                own_skipped[mr] = unpacked[inverse, 1 + E:1 + 2 * E]
            for r, (c_rule, c_skip) in cached.items():
                own_rule[r] = c_rule
                own_skipped[r] = c_skip
            sharded.apply_fallback(enc.host_fallback, docs, names,
                                   own_rule, own_skipped, max_fallback)
            evict_d = self._cache_insert(ckeys, unique_rows, eligible,
                                         own_rule, own_skipped)
            metrics_mod.observe_dedup("engine", n, u, len(cached),
                                      elig_miss, evict_d)
            self._observe_provenance(snap, batch, enc.row_of[:n], own_rule,
                                     own_skipped, shards=enc.shard_of[:n],
                                     waits=waits)
            return own_rule, own_skipped, None

        item = _Inflight(self, batch, handle, finalize, binfo, waits)
        item.route = route
        return item

    def _complete(self, item: _Inflight) -> None:
        """Completion stage (worker pool, handed off by the completer once
        the readback arrived): finalize → loop-affine future resolution →
        free the window slot.  A readback/finalize failure is a DEVICE
        failure and rides the retry-once-then-degrade path (which owns the
        slot until the batch resolves); anything that fails AFTER the
        device provably answered — telemetry, tracing, resolution — is a
        host-side bug and must never feed the breaker or re-dispatch a
        succeeded batch."""
        try:
            t_done = time.monotonic()
            if faults.ACTIVE:
                faults.FAULTS.check("readback", "engine")
            packed = np.asarray(item.handle)
            # speculative first-wins (ISSUE 12): acquire BEFORE finalize —
            # a batch the host twin already resolved skips finalize (and
            # with it the provenance fold + cache insert) entirely; the
            # device readback was confirmation + the breaker's probe
            # verdict.  acquire() is idempotent for the device lane, so
            # the finalize-failure path below keeps ownership.
            spec_won = item.spec is None or item.spec.acquire(L_DEVICE)
            if spec_won:
                own_rule, own_skipped, fallback_n = item.finalize(packed)
        except Exception as e:
            # device/readback failure: per-device breaker attribution +
            # occupancy release for a routed mesh batch, then retry once
            # (the fresh dispatch routes around the sick device), then
            # host-oracle degrade
            self._route_done(item, ok=False)
            self._batch_failed(item.snap, item.batch, item.attempt, e,
                               spec=item.spec)
            return
        # the mesh devices answered: per-device breaker success + window
        # release, before any telemetry that could fail host-side
        self._route_done(item, ok=True)
        slo_counted = False
        try:
            # the device answered: clear the breaker's consecutive-failure
            # count (and close a half-open probe) BEFORE resolution work.
            # A fully cache-resolved batch (zero device rows) proves
            # nothing about the device — it only releases a claimed probe.
            if item.binfo.get("device_rows", 1) == 0:
                self.breaker.release_probe()
            else:
                self.breaker.record_success()
            dur = t_done - item.t_launch
            self._device_ewma = (dur if not self._device_ewma
                                 else 0.8 * self._device_ewma + 0.2 * dur)
            # lane-selection cost model (ISSUE 12): every device completion
            # feeds the RTT/congestion EWMAs the next cut decides on —
            # EXCEPT fully cache-resolved batches (zero device rows): they
            # never touched the link, and their ~100µs turnaround would
            # read as a fast device and pin small cuts device-side under
            # cache-hit-heavy traffic (the exact regression this lane
            # removes; the native lane has the same guard)
            if item.binfo.get("device_rows", 1) != 0:
                self.lanes.cost.observe_device(
                    dur, item.binfo["batch_size"], len(self._queue),
                    self._inflight, self.controller.window)
            sharded = (getattr(item.snap, "sharded", None)
                       if item.snap is not None else None)
            if sharded is not None:
                # mesh lane cost feed (ISSUE 12): a partially-down mesh
                # concentrates load on the survivors — the device cost the
                # selector compares against rises accordingly
                try:
                    self.lanes.cost.mesh_penalty = sharded.cost_feed()
                except Exception:
                    pass
            # overload controllers: the batch's device round trip + size
            # steps the adaptive window/cut; completed rows feed the
            # admission gate's service-rate estimate
            self.controller.observe_batch(dur, item.binfo["batch_size"],
                                          len(self._queue), now=t_done)
            if not spec_won:
                # the host twin already answered the clients: request-level
                # accounting (admission service, SLO, spans, resolution)
                # happened exactly once on the host side
                return
            if item.spec is not None:
                self.lanes.count_speculative("device-win")
            self.lanes.count_rows(L_DEVICE, item.binfo["batch_size"])
            self.admission.observe_service(item.binfo["batch_size"],
                                           now=t_done)
            if self.slo is not None:
                # per-request latency ≈ queue wait + this batch's device
                # stage — one vectorized compare per batch (ISSUE 9)
                lat = np.asarray(item.waits) + dur
                n_bad = int(np.count_nonzero(lat > self.slo.slo_s))
                self.slo.observe(len(item.batch), n_bad)
                slo_counted = True
                # per-lane burn bias feed (ISSUE 12): selection leans
                # toward the lane that is NOT burning budget
                self.lanes.cost.observe_slo(L_DEVICE, len(item.batch),
                                            n_bad)
                # SLO-delta canary guard feed (ISSUE 10): per-cohort bad
                # fractions ride the same per-batch counts
                phase = self._canary
                if phase is not None and \
                        item.snap in (phase.snap, phase.baseline):
                    phase.guard.observe_slo(item.snap is phase.snap,
                                            len(item.batch), n_bad)
            binfo = item.binfo
            binfo["duration_s"] = t_done - item.t_launch
            metrics_mod.observe_pipeline_stage("engine", "device",
                                               binfo["duration_s"])
            metrics_mod.observe_batch(
                "engine", binfo["batch_size"], binfo["pad"],
                item.waits, binfo["duration_s"], fallback_n,
                device_rows=binfo.get("device_rows"))
            if tracing_mod.tracing_active():
                # one DeviceBatch span per kernel launch, span-linked to
                # every constituent request's trace (export only: a link
                # list build per batch, nothing per request)
                links = [(p.span.trace_id, p.span.span_id)
                         for p in item.batch if p.span is not None
                         and getattr(p.span, "sampled", True)]
                if links:
                    tracing_mod.export_device_batch_span(
                        binfo["batch_size"], binfo["pad"], binfo["eff"],
                        links, binfo["start_ns"], binfo["duration_s"])
            by_loop: Dict[Any, list] = {}
            for i, p in enumerate(item.batch):
                by_loop.setdefault(p.loop, []).append(
                    (p.future, own_rule[i], own_skipped[i], item.snap))
            for loop, resolutions in by_loop.items():
                try:
                    loop.call_soon_threadsafe(_resolve_many, resolutions)
                except RuntimeError:
                    pass  # loop closed since submit: its futures are moot
            metrics_mod.observe_pipeline_stage("engine", "resolve",
                                               time.monotonic() - t_done)
        except Exception as e:
            # post-device-success host bug (telemetry exporter, metrics
            # label, resolution plumbing): fail any still-unresolved
            # futures typed — already-resolved ones keep their verdicts —
            # and free the slot.  Retrying here would re-run a healthy
            # device and could walk the breaker open off exporter noise.
            log.exception("post-completion work failed (batch verdicts "
                          "already computed)")
            if spec_won:
                self._resolve_error(item.batch, e, slo_counted=slo_counted)
        finally:
            self._launch_done()

    def _resolve_error(self, batch: List[_Pending], exc: Exception,
                       slo_counted: bool = False) -> None:
        """Fail unresolved requests with a TYPED CheckAbort — never the raw
        exception, whose repr would otherwise serve as a deny reason
        string through the gRPC/HTTP layer (ISSUE 5 satellite).  Raw causes
        are logged here; callers with a degrade path never reach this."""
        if not isinstance(exc, CheckAbort):
            log.error("batch of %d failed without a degrade path: %r",
                      len(batch), exc)
            exc = CheckAbort(UNAVAILABLE, "policy evaluation unavailable")
        if self.slo is not None and not slo_counted and \
                exc.code != DEADLINE_EXCEEDED:
            # serving errors burn the SLO budget; deadline sheds are the
            # protection mechanism working and stay out of it.  slo_counted:
            # a post-completion telemetry failure arrives here AFTER the
            # success path already observed the batch — don't double-burn
            self.slo.observe_errors(len(batch))
        if exc.code != DEADLINE_EXCEEDED:
            self.error_total += len(batch)
        phase = self._canary
        if phase is not None and batch and exc.code != DEADLINE_EXCEEDED:
            # typed-error guard (ISSUE 10): a canary generation whose
            # batches keep failing (encode raises on a bad artifact, say)
            # must breach even when it never produces a deny column.
            # Batches are cohort-homogeneous post-partition.
            try:
                phase.guard.observe_errors(bool(batch[0].canary),
                                           len(batch))
                self._canary_guard_check(phase)
            except Exception:
                log.exception("canary error feed failed")
        by_loop: Dict[Any, list] = {}
        for p in batch:
            by_loop.setdefault(p.loop, []).append(p.future)
        for loop, futs in by_loop.items():
            try:
                loop.call_soon_threadsafe(_fail_many, futs, exc)
            except RuntimeError:
                pass

    def _launch_done(self) -> None:
        with self._queue_lock:
            self._inflight -= 1
            inflight = self._inflight
        self._g_inflight.set(inflight)
        self._maybe_dispatch()


def _doc_host(doc) -> str:
    """Best-effort host of one authorization JSON (decision-log records)."""
    try:
        return str((doc.get("request") or {}).get("host", ""))
    except Exception:
        return ""


def _split_cohorts(batch, phase):
    """Partition one cut by canary cohort: [(is_canary, items), ...] with
    empties dropped.  With no canary in progress the cut ships whole."""
    if phase is None:
        return [(False, batch)]
    base = [p for p in batch if not p.canary]
    can = [p for p in batch if p.canary]
    parts = []
    if base:
        parts.append((False, base))
    if can:
        parts.append((True, can))
    return parts or [(False, batch)]


def _resolve_many(resolutions) -> None:
    for fut, rule, skipped, snap in resolutions:
        if not fut.done():
            fut.set_result((rule, skipped, snap))


def _fail_many(futs, exc) -> None:
    for fut in futs:
        if not fut.done():
            fut.set_exception(exc)


# ---------------------------------------------------------------------------
# shared pipeline stages.  Both are process-wide singletons: engines are
# created freely (tests, reconciles) and per-engine threads with no shutdown
# path would leak.
#
#   encode pool   — CPU workers for the encode stage AND per-batch finalize;
#                   its size bounds host parallelism only, NOT the in-flight
#                   device window (that is each engine's max_inflight_batches
#                   counter)
#   completer     — one thread that ONLY polls in-flight readbacks
#                   (is_ready) and hands each arrived batch to the pool the
#                   moment it lands — arrival order, not launch order, and
#                   no finalize work that could convoy other arrivals
# ---------------------------------------------------------------------------

_ENCODE_POOL = None
_ENCODE_POOL_LOCK = threading.Lock()


def _encode_pool(workers: int = 4):
    global _ENCODE_POOL
    if _ENCODE_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        with _ENCODE_POOL_LOCK:
            if _ENCODE_POOL is None:
                _ENCODE_POOL = ThreadPoolExecutor(
                    max_workers=max(1, workers),
                    thread_name_prefix="atpu-engine-encode")
    return _ENCODE_POOL


_COMPLETER: Optional[threading.Thread] = None
_COMPLETER_LOCK = threading.Lock()
_COMPLETER_ITEMS: deque = deque()
_COMPLETER_EVT = threading.Event()


def _completer_submit(item: _Inflight) -> None:
    _ensure_completer()
    _COMPLETER_ITEMS.append(item)
    _COMPLETER_EVT.set()


def _ensure_completer() -> None:
    global _COMPLETER
    if _COMPLETER is None or not _COMPLETER.is_alive():
        with _COMPLETER_LOCK:
            if _COMPLETER is None or not _COMPLETER.is_alive():
                t = threading.Thread(target=_completer_loop,
                                     name="atpu-engine-completer", daemon=True)
                t.start()
                _COMPLETER = t


def _completer_loop() -> None:
    log = logging.getLogger("authorino_tpu.engine")
    pending: List[_Inflight] = []
    while True:
        while _COMPLETER_ITEMS:
            try:
                pending.append(_COMPLETER_ITEMS.popleft())
            except IndexError:
                break
        if not pending:
            _COMPLETER_EVT.wait()
            _COMPLETER_EVT.clear()
            continue
        progressed = False
        for item in list(pending):
            if item.ready():
                pending.remove(item)
                progressed = True
                try:
                    # finalize on the worker pool, NOT here: the host-
                    # fallback oracle can be O(batch) work, and one heavy
                    # batch must not convoy the resolution of other already-
                    # arrived batches.  _complete handles its own failures
                    # and releases the window slot exactly once.
                    _encode_pool(item.engine.dispatch_workers).submit(
                        item.engine._complete, item)
                except Exception:
                    log.exception("batch completion handoff failed")
            elif item.expired():
                # watchdog: the readback is wedged past --device-timeout —
                # abandon the handle and feed the batch the retry/degrade
                # path (a breaker-counted failure).  A late arrival on the
                # dropped handle is harmless: nothing materializes it.
                pending.remove(item)
                progressed = True
                try:
                    _encode_pool(item.engine.dispatch_workers).submit(
                        item.engine._watchdog_fire, item)
                except Exception:
                    log.exception("watchdog handoff failed")
        if not progressed:
            # nothing ready: sub-ms poll — noise against the link RTT each
            # in-flight batch is waiting out, and it keeps resolution
            # FIFO-independent (no blocking on the oldest launch)
            _COMPLETER_EVT.wait(0.0005)
            _COMPLETER_EVT.clear()


from ..utils import bucket_pow2 as _bucket  # noqa: E402 — shared bucketing policy
