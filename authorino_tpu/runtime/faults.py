"""Injectable fault plane for the device dispatch path (ISSUE 5).

Both serving lanes (the asyncio engine in runtime/engine.py and the C++
device-owner frontend in runtime/native_frontend.py) call into this module
at three points of every micro-batch — encode, kernel launch (covers the
H2D enqueue), and readback — so tests, ``bench.py --chaos`` and a
``--fault-profile`` server run can make any stage raise, hang, or slow
down per batch, deterministically, without touching the device code.

Zero-cost when off: hot paths gate every hook on the module-level
``ACTIVE`` flag (one attribute read per batch); nothing else of this
module runs until ``FAULTS.arm()`` flips it.

Spec grammar (also accepted by AUTHORINO_TPU_FAULTS / --fault-profile /
bench --chaos)::

    spec  := profile | rule (";" rule)*
    rule  := stage ":" mode [":" key=value]*
    stage := encode | h2d | kernel | dispatch (= kernel) | readback | fs
    mode  := raise | hang | delay                       (device stages)
    mode  := torn | short | rename-fail | eio | enospc  (fs stage)
    keys  := p=<probability 0..1> n=<max firings> delay=<seconds>
             for=<seconds active> after=<seconds before active>
             lane=<engine|native> device=<device id>
             artifact=<snapshot-blob|manifest|hotset|capture|corpus|...>

Named profiles::

    device-down   kernel:raise               every dispatch fails
    one-device-down  kernel:raise:device=0   mesh device 0 fails, rest healthy
    flaky         kernel:raise:p=0.3         ~1 in 3 dispatches fails
    flap          kernel:raise:for=2         device down 2s, then recovers
    slow-device   kernel:delay:delay=0.05    +50ms readback latency/batch
    wedge         kernel:hang                readbacks never arrive

``device=`` scopes a rule to ONE mesh device (jax device id): it fires only
for probes that name that device (the sharded dispatcher probes each mesh
device before a launch — parallel/sharded_eval.py dispatch_routed), so a
multi-chip lane can lose exactly one chip while its neighbours keep
serving.  Device-scoped raises carry ``device_id`` on the exception — the
failover path's attribution.  The converse also holds: per-device probes
fire ONLY device-scoped rules — generic rules get their once-per-batch
chance at the lane-level check that precedes every launch, so arming e.g.
``flaky`` keeps the same per-batch probability on a mesh as on one chip.

``hang`` is realized by wrapping the in-flight result handle: is_ready()
stays False (until the rule's ``for=`` window closes), which is exactly
what a wedged device looks like to the completer — the watchdog path, not
the exception path, must catch it.  Device-stage ``delay`` rules ride the
same wrapper with a per-batch release deadline: the readback arrives
``delay_s`` late, so the measured device round trip (and everything keyed
off it — deadline shedding headroom, the adaptive window controller)
inflates exactly like a genuinely slow device; only encode-stage delays
sleep on the worker thread.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

__all__ = ["ACTIVE", "FAULTS", "FaultPlane", "FaultRule", "InjectedFault",
           "HungHandle", "PROFILES"]

log = logging.getLogger("authorino_tpu.faults")

# module-level gate: the ONLY thing serving paths read while faults are off
ACTIVE = False

PROFILES = {
    "device-down": "kernel:raise",
    "one-device-down": "kernel:raise:device=0",
    "flaky": "kernel:raise:p=0.3",
    "flap": "kernel:raise:for=2",
    "slow-device": "kernel:delay:delay=0.05",
    "wedge": "kernel:hang",
}

_STAGES = ("encode", "h2d", "kernel", "readback", "fs")
_MODES = ("raise", "hang", "delay")
# The fs stage models filesystem failure at a durable-artifact writer
# (utils/atomicio.py consults fs_fault() under the same ACTIVE gate the
# device hooks use).  Its modes are crash shapes, not exception shapes:
#   torn        a prefix of the new bytes lands over the DESTINATION
#               (power cut after a non-atomic overwrite) — readers must
#               reject the torn artifact typed, never crash or serve it
#   short       the tmp file ends up shorter than requested (quota,
#               interrupted write); the writer's size check catches it
#               and the destination is untouched
#   rename-fail os.replace itself fails; tmp is discarded, old state wins
#   eio         open/write raises EIO before any byte lands
#   enospc      a partial tmp write then ENOSPC; destination untouched
_FS_MODES = ("torn", "short", "rename-fail", "eio", "enospc")


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise`` rule — the synthetic stand-in for a
    failed H2D transfer / kernel launch / readback.  ``device_id`` names
    the mesh device a device-scoped rule fired for (None otherwise) — the
    per-device failover path reads it for breaker attribution."""

    def __init__(self, message: str, device_id: Optional[int] = None):
        super().__init__(message)
        self.device_id = device_id


@dataclass
class FaultRule:
    stage: str                    # encode | h2d | kernel | readback | fs
    mode: str                     # raise | hang | delay | <fs mode>
    lane: str = "*"               # engine | native | *
    device: Optional[int] = None  # scope to one mesh device id (None = any)
    artifact: str = "*"           # fs stage: scope to one artifact kind
    p: float = 1.0                # firing probability per eligible batch
    n: int = -1                   # max firings (-1 = unlimited)
    delay_s: float = 0.05         # mode=delay: added latency
    for_s: Optional[float] = None   # active window from arm time (None = ∞)
    after_s: float = 0.0          # inactive for this long after arm time
    fired: int = 0

    def live(self, elapsed: float) -> bool:
        if self.n >= 0 and self.fired >= self.n:
            return False
        if elapsed < self.after_s:
            return False
        if self.for_s is not None and elapsed >= self.after_s + self.for_s:
            return False
        return True

    def describe(self) -> str:
        extras = []
        if self.lane != "*":
            extras.append(f"lane={self.lane}")
        if self.device is not None:
            extras.append(f"device={self.device}")
        if self.artifact != "*":
            extras.append(f"artifact={self.artifact}")
        if self.p < 1.0:
            extras.append(f"p={self.p}")
        if self.n >= 0:
            extras.append(f"n={self.n}")
        if self.for_s is not None:
            extras.append(f"for={self.for_s}")
        if self.after_s:
            extras.append(f"after={self.after_s}")
        return ":".join([self.stage, self.mode] + extras)


class HungHandle:
    """Wraps an in-flight device handle so its readback never arrives
    (``release_at`` = monotonic deadline after which the underlying handle
    shows through again, or None for a permanent wedge)."""

    def __init__(self, handle: Any, release_at: Optional[float] = None):
        self._handle = handle
        self._release_at = release_at

    def _released(self) -> bool:
        return self._release_at is not None and time.monotonic() >= self._release_at

    def is_ready(self) -> bool:
        if self._released():
            is_ready = getattr(self._handle, "is_ready", None)
            return True if is_ready is None else bool(is_ready())
        return False

    def __array__(self, dtype=None):
        # a blocking materialization of a permanently-wedged handle would
        # deadlock the caller — fail loudly instead (the watchdog is the
        # intended consumer of a hung handle)
        import numpy as np

        if not self._released():
            raise InjectedFault("readback of a hung device handle")
        return np.asarray(self._handle, dtype=dtype)


def _parse_rule(text: str) -> FaultRule:
    parts = [p.strip() for p in text.split(":") if p.strip()]
    if len(parts) < 2:
        raise ValueError(f"fault rule {text!r}: want stage:mode[:k=v...]")
    stage, mode = parts[0].lower(), parts[1].lower()
    if stage == "dispatch":
        stage = "kernel"
    if stage not in _STAGES:
        raise ValueError(f"fault rule {text!r}: unknown stage {stage!r} "
                         f"(want one of {_STAGES})")
    if stage == "fs":
        if mode not in _FS_MODES:
            raise ValueError(f"fault rule {text!r}: unknown fs mode {mode!r} "
                             f"(want one of {_FS_MODES})")
    elif mode not in _MODES:
        raise ValueError(f"fault rule {text!r}: unknown mode {mode!r} "
                         f"(want one of {_MODES})")
    rule = FaultRule(stage=stage, mode=mode)
    for kv in parts[2:]:
        if "=" not in kv:
            raise ValueError(f"fault rule {text!r}: bad key {kv!r}")
        k, v = kv.split("=", 1)
        k = k.strip().lower()
        if k == "p":
            rule.p = float(v)
        elif k == "n":
            rule.n = int(v)
        elif k in ("delay", "delay_s"):
            rule.delay_s = float(v)
        elif k == "delay_ms":
            rule.delay_s = float(v) / 1000.0
        elif k in ("for", "for_s"):
            rule.for_s = float(v)
        elif k in ("after", "after_s"):
            rule.after_s = float(v)
        elif k == "lane":
            rule.lane = v.strip().lower()
        elif k == "device":
            rule.device = int(v)
        elif k == "artifact":
            rule.artifact = v.strip().lower()
        else:
            raise ValueError(f"fault rule {text!r}: unknown key {k!r}")
    return rule


class FaultPlane:
    """Process-wide fault injector (singleton: ``FAULTS``).  Thread-safe:
    hooks run on dispatcher/completer/readback threads concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[FaultRule] = []
        self._armed_at = 0.0
        self._rng = random.Random()
        self.fired: Dict[str, int] = {}   # "stage:mode:lane" → count

    # -- control -----------------------------------------------------------

    def arm(self, spec: str, seed: Optional[int] = None) -> None:
        """Parse and activate ``spec`` (a named profile or rule list).
        Re-arming replaces the previous rule set and restarts the clock."""
        global ACTIVE
        spec = (spec or "").strip()
        if not spec:
            self.disarm()
            return
        spec = PROFILES.get(spec, spec)
        rules = [_parse_rule(r) for r in spec.replace(",", ";").split(";")
                 if r.strip()]
        if seed is None:
            env_seed = os.environ.get("AUTHORINO_TPU_FAULT_SEED", "")
            seed = int(env_seed) if env_seed else 1234
        with self._lock:
            self._rules = rules
            self._armed_at = time.monotonic()
            self._rng = random.Random(seed)
            self.fired = {}
        ACTIVE = True
        log.warning("fault injection ARMED: %s",
                    "; ".join(r.describe() for r in rules))

    def disarm(self) -> None:
        global ACTIVE
        with self._lock:
            self._rules = []
        ACTIVE = False

    def describe(self) -> Dict[str, Any]:
        """JSON-safe state for /debug/vars."""
        with self._lock:
            return {
                "armed": bool(self._rules),
                "rules": [r.describe() for r in self._rules],
                "armed_for_s": (time.monotonic() - self._armed_at
                                if self._rules else 0.0),
                "fired": dict(self.fired),
            }

    # -- hooks (hot path; callers gate on faults.ACTIVE) -------------------

    def _match(self, stage: str, lane: str,
               device: Optional[int] = None) -> Optional[FaultRule]:
        with self._lock:
            elapsed = time.monotonic() - self._armed_at
            for r in self._rules:
                if r.stage != stage or r.mode == "hang":
                    continue  # hang rules fire at wrap_handle, not here
                if r.mode == "delay" and r.stage != "encode":
                    # device-stage delays model a SLOW DEVICE: they ride
                    # wrap_handle as readback latency (is_ready stays False
                    # for delay_s), never a sleep that stalls the encode
                    # worker — the adaptive window controller must see the
                    # RTT inflate, not the host thread stall
                    continue
                if r.lane not in ("*", lane):
                    continue
                if r.device is not None and r.device != device:
                    # device-scoped rule: fires only for probes that name
                    # this exact mesh device (sharded dispatch_routed)
                    continue
                if device is not None and r.device is None:
                    # per-device probe, generic rule: the lane-level check
                    # that precedes every mesh launch already gave it its
                    # once-per-batch chance — matching here too would
                    # multiply p by the device count and pin a lane-wide
                    # fault on one device's breaker
                    continue
                if not r.live(elapsed):
                    continue
                if r.p < 1.0 and self._rng.random() >= r.p:
                    continue
                r.fired += 1
                key = f"{r.stage}:{r.mode}:{lane}"
                self.fired[key] = self.fired.get(key, 0) + 1
                return r
        return None

    def check(self, stage: str, lane: str,
              device: Optional[int] = None) -> None:
        """Raise/delay hook for one batch at ``stage``.  ``hang`` rules are
        not handled here — they ride ``wrap_handle`` at launch.  ``device``
        is the mesh device id a per-device probe names; device-scoped rules
        fire only when it matches."""
        rule = self._match(stage, lane, device=device)
        if rule is None:
            return
        from ..utils import metrics as metrics_mod

        metrics_mod.injected_faults.labels(stage, rule.mode, lane).inc()
        if rule.mode == "raise":
            raise InjectedFault(
                f"injected {stage} fault ({lane} lane"
                + (f", device {device}" if rule.device is not None else "")
                + ")",
                device_id=rule.device if rule.device is not None else None)
        if rule.mode == "delay":
            time.sleep(rule.delay_s)

    def fs_fault(self, artifact: str) -> Optional[FaultRule]:
        """Durable-writer hook: return the armed ``fs`` rule matching
        ``artifact`` (or None).  The caller — utils/atomicio.py — realizes
        the crash shape (torn/short/rename-fail/eio/enospc); this method
        only does the rule bookkeeping so firing counts, ``n=``/``for=``
        windows and the deterministic rng behave exactly like the device
        stages.  Callers gate on ``faults.ACTIVE`` first (zero-cost off)."""
        with self._lock:
            elapsed = time.monotonic() - self._armed_at
            for r in self._rules:
                if r.stage != "fs":
                    continue
                if r.artifact not in ("*", artifact):
                    continue
                if not r.live(elapsed):
                    continue
                if r.p < 1.0 and self._rng.random() >= r.p:
                    continue
                r.fired += 1
                key = f"fs:{r.mode}:{artifact}"
                self.fired[key] = self.fired.get(key, 0) + 1
                rule = r
                break
            else:
                return None
        from ..utils import metrics as metrics_mod

        metrics_mod.injected_faults.labels("fs", rule.mode, artifact).inc()
        return rule

    def rand(self) -> float:
        """One draw from the deterministic rng (seeded at arm time) —
        fs-mode writers use it to pick torn/short prefix lengths so a
        given AUTHORINO_TPU_FAULT_SEED reproduces the same crash bytes."""
        with self._lock:
            return self._rng.random()

    def wrap_handle(self, handle: Any, lane: str) -> Any:
        """Launch-time hook for device-stage ``hang`` and ``delay`` rules:
        the in-flight handle is wrapped so its readback never arrives
        (hang — until the rule's active window closes, when the real
        handle shows through: a recovering wedge) or arrives ``delay_s``
        late (a slow device: is_ready turns True after the delay, and the
        measured round trip inflates accordingly)."""
        with self._lock:
            elapsed = time.monotonic() - self._armed_at
            rule = None
            for r in self._rules:
                if r.mode not in ("hang", "delay") or r.stage == "encode":
                    continue
                if r.device is not None:
                    continue  # device scoping is raise-only (probe-time)
                if r.lane not in ("*", lane):
                    continue
                if not r.live(elapsed):
                    continue
                if r.p < 1.0 and self._rng.random() >= r.p:
                    continue
                r.fired += 1
                key = f"{r.stage}:{r.mode}:{lane}"
                self.fired[key] = self.fired.get(key, 0) + 1
                rule = r
                break
        if rule is None:
            return handle
        from ..utils import metrics as metrics_mod

        metrics_mod.injected_faults.labels(rule.stage, rule.mode, lane).inc()
        if rule.mode == "delay":
            return HungHandle(handle,
                              release_at=time.monotonic() + rule.delay_s)
        release = (None if rule.for_s is None
                   else self._armed_at + rule.after_s + rule.for_s)
        return HungHandle(handle, release_at=release)


FAULTS = FaultPlane()
