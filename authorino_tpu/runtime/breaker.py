"""Device circuit breaker (ISSUE 5): consecutive micro-batch failures trip
the lane OPEN — whole batches route host-side without touching the device —
and a half-open probe re-admits one batch after the cooldown to test
recovery.

State machine (per lane; the engine and the native frontend each own one):

    CLOSED ──(threshold consecutive batch failures)──▶ OPEN
    OPEN ──(reset_s cooldown elapsed)──▶ HALF_OPEN (ONE probe batch admitted)
    HALF_OPEN ──(probe batch succeeds)──▶ CLOSED
    HALF_OPEN ──(probe batch fails)──▶ OPEN (cooldown restarts)

Thread-safe: dispatcher, completer and watchdog threads all report into
one breaker.  Every transition is counted in
auth_server_circuit_transitions_total{lane,state} and the live state rides
the auth_server_circuit_state{lane} gauge (0=closed, 1=half-open, 2=open),
/readyz and /debug/vars.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ..utils import metrics as metrics_mod

__all__ = ["CircuitBreaker", "DeviceBreakerSet", "CLOSED", "HALF_OPEN",
           "OPEN"]

log = logging.getLogger("authorino_tpu.breaker")

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_GAUGE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(self, lane: str, threshold: int = 5, reset_s: float = 5.0):
        self.lane = lane
        self.threshold = max(1, int(threshold))
        self.reset_s = float(reset_s)
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.transitions: List[Dict[str, Any]] = []  # bounded trail for bench
        self._g_state = metrics_mod.circuit_state.labels(lane)
        self._g_state.set(0)

    # -- internal ----------------------------------------------------------

    def _transition(self, state: str, reason: str) -> None:
        # caller holds _lock
        if state == self._state:
            return
        self._state = state
        self._g_state.set(_GAUGE_VALUE[state])
        metrics_mod.circuit_transitions.labels(self.lane, state).inc()
        self.transitions.append(
            {"t": time.time(), "state": state, "reason": reason})
        del self.transitions[:-64]
        # flight recorder (ISSUE 9): every transition rides the lifecycle
        # ring; entering OPEN is an anomaly trigger (auto-dump).  record()
        # is a deque append — safe under this lock, fail-safe inside
        from .flight_recorder import RECORDER

        RECORDER.record("breaker-open" if state == OPEN else "breaker",
                        lane=self.lane,
                        detail={"state": state, "reason": reason})
        log.warning("circuit breaker (%s lane) -> %s (%s)",
                    self.lane, state.upper(), reason)

    # -- dispatch-time gate ------------------------------------------------

    def allow_device(self) -> bool:
        """True when this batch may touch the device.  OPEN past the
        cooldown atomically claims the single half-open probe slot; every
        other caller stays host-side until that probe resolves."""
        return self.admit_device()[0]

    def admit_device(self) -> "tuple[bool, bool]":
        """(allowed, probe): like ``allow_device``, but reports whether
        this admission claimed the half-open probe slot.  Speculative
        dual-dispatch (ISSUE 12, runtime/lane_select.py) arms exactly on
        probes: the probe batch rides BOTH lanes and resolves first-wins,
        so clients never wait out a probe against a still-sick device —
        while the device half's outcome still decides the breaker."""
        with self._lock:
            if self._state == CLOSED:
                return True, False
            if self._state == OPEN:
                if time.monotonic() - self._opened_at < self.reset_s:
                    return False, False
                self._transition(HALF_OPEN, "cooldown elapsed; probing")
                self._probe_inflight = True
                return True, True
            # HALF_OPEN: exactly one probe at a time
            if self._probe_inflight:
                return False, False
            self._probe_inflight = True
            return True, True

    # -- batch outcomes ----------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition(CLOSED, "probe batch succeeded")

    def release_probe(self) -> None:
        """The admitted batch never actually touched the device (e.g. every
        row was verdict-cache-resolved): free the half-open probe slot
        without recording a verdict, so the next real batch can probe."""
        with self._lock:
            self._probe_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == HALF_OPEN:
                self._probe_inflight = False
                self._opened_at = time.monotonic()
                self._transition(OPEN, "probe batch failed")
            elif self._state == CLOSED and self._consecutive >= self.threshold:
                self._opened_at = time.monotonic()
                self._transition(
                    OPEN, f"{self._consecutive} consecutive batch failures")

    # -- introspection -----------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "threshold": self.threshold,
                "reset_s": self.reset_s,
                "transitions": list(self.transitions),
            }
            if self._state == OPEN:
                out["retry_in_s"] = max(
                    0.0, self.reset_s - (time.monotonic() - self._opened_at))
            return out

    # -- mesh routing peeks (no probe claim) --------------------------------

    def candidate(self) -> bool:
        """True when this breaker would plausibly admit a dispatch right
        now — CLOSED, OPEN past its cooldown (a probe is due), or HALF_OPEN
        with no probe in flight.  A pure PEEK: unlike ``allow_device`` it
        never claims the half-open probe slot, so the mesh router can rank
        many devices without stranding probes on the ones it skips."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return time.monotonic() - self._opened_at >= self.reset_s
            return not self._probe_inflight


class DeviceBreakerSet:
    """Per-device circuit breakers for the mesh lane (ISSUE 11): one
    ``CircuitBreaker`` per mesh device, so a single sick chip routes its
    batches to healthy neighbours instead of tripping the whole lane to the
    host oracle.  The engine's lane-global breaker stays the outer guard
    (it only opens once the WHOLE mesh stops answering)."""

    def __init__(self, lane: str, device_ids, threshold: int = 3,
                 reset_s: float = 5.0):
        self.lane = lane
        self.breakers: Dict[int, CircuitBreaker] = {
            int(d): CircuitBreaker(f"{lane}-dev{int(d)}", threshold=threshold,
                                   reset_s=reset_s)
            for d in device_ids
        }

    def get(self, device_id: int) -> CircuitBreaker:
        return self.breakers[int(device_id)]

    def all_closed(self) -> bool:
        """True when every mesh device is healthy — the full-mesh
        shard_map launch is the right plan."""
        return all(b.state == CLOSED for b in self.breakers.values())

    def candidates(self) -> List[int]:
        """Device ids a single-device dispatch may target right now,
        healthy (CLOSED) devices first.  Pure peek — the router claims the
        actual probe slot via ``get(id).allow_device()`` only on the device
        it picks."""
        closed = [i for i, b in self.breakers.items() if b.state == CLOSED]
        probing = [i for i, b in self.breakers.items()
                   if b.state != CLOSED and b.candidate()]
        return closed + probing

    def record_failure(self, device_id: int) -> None:
        b = self.breakers.get(int(device_id))
        if b is not None:
            b.record_failure()

    def record_success(self, device_ids) -> None:
        for d in device_ids:
            b = self.breakers.get(int(d))
            if b is not None:
                b.record_success()

    def to_json(self) -> Dict[str, Any]:
        return {str(i): b.to_json() for i, b in sorted(self.breakers.items())}
