"""Decision provenance (ISSUE 9): which-rule-fired attribution, the runtime
rule heat map, and the head-sampled decision log.

The PR 3 bitpacked readback already ships per-rule result/skip columns
alongside every verdict (``ops/pattern_eval.py eval_verdicts`` →
``rule_results``); until this layer they were decoded to one verdict and
thrown away.  Here they become:

- **attribution**: the first evaluator column that evaluated false and was
  not condition-skipped is *the* rule that denied the request
  (``ops.pattern_eval.firing_columns`` — the reference pipeline's
  short-circuit order).  Both lanes decode it per BATCH, and the fan-out
  paths (within-batch dedup, verdict-cache hits, brownout, host-oracle
  degrade) attribute identically because they all reproduce the same
  (rule, skipped) columns;
- **rule heat map**: ``auth_server_rule_fired_total{authconfig,rule}``,
  folded per batch via column-sum (``np.bincount`` over a composite
  (config row, firing column) key — the per-batch Python cost is bounded
  by the number of DISTINCT (config, rule) pairs in the batch, never the
  batch size).  The never-fired set cross-references the static
  constant/shadowed findings (PR 4 policy analysis) in the dead-rule
  report on ``/debug/vars``;
- **decision log**: a bounded ring of head-sampled structured decision
  records (host, authconfig, verdict, firing rule, lane, latency, snapshot
  generation) served on ``/debug/decisions`` and pretty-printed by
  ``python -m authorino_tpu.analysis --decisions``.  Sampling is 1-in-N
  *decisions* with at most one record per batch, so the native fast lane
  pays one counter compare per batch and a dict build only when sampled.

Privacy: rule SOURCE strings reach clients (X-Ext-Auth-Reason) only behind
``--expose-deny-reason`` (module flag ``EXPOSE_DENY_REASON``); Envoy
``dynamic_metadata`` provenance and the operator surfaces (/metrics,
/debug/*) always carry them — they are mesh-internal."""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import metrics as metrics_mod

__all__ = ["EXPOSE_DENY_REASON", "RULE_LABEL_MAX", "HeatMap", "DecisionLog",
           "DECISIONS", "DecisionSchemaError", "check_decision_schema",
           "rule_label", "deny_provenance", "deny_reason",
           "dead_rule_report", "fired_pairs", "fold_and_sample",
           "flush_heatmaps"]

# --expose-deny-reason: when False (default), deny responses keep the
# generic "Unauthorized" reason and attribution rides only dynamic_metadata
# + operator surfaces.  Set by the CLI; module-level so the evaluator seam
# (evaluators/authorization/pattern_matching.py) needs no plumbing.
EXPOSE_DENY_REASON = False

# rule-source label truncation: heat-map label values must stay bounded
# (Prometheus label cardinality is per distinct VALUE, and sources are
# operator-authored — truncation only shortens, never merges rules, because
# the evaluator index prefixes the label)
RULE_LABEL_MAX = 120


def rule_label(col: int, source: str) -> str:
    src = source if len(source) <= RULE_LABEL_MAX else \
        source[:RULE_LABEL_MAX - 1] + "…"
    return f"{col}:{src}"


# process-wide fired set, merged across lanes and snapshot generations:
# (authconfig, evaluator column) pairs that have attributed at least one
# denial since process start.  The dead-rule report subtracts it from the
# serving snapshot's registered rules.
_FIRED: set = set()
_FIRED_LOCK = threading.Lock()


def fired_pairs() -> set:
    with _FIRED_LOCK:
        return set(_FIRED)


def _reset_fired_for_tests() -> None:
    with _FIRED_LOCK:
        _FIRED.clear()


# live heat maps, flushed at Prometheus scrape time by _FlushCollector (so
# rule-fired counters are current on every scrape even when traffic — and
# with it the amortized in-fold flush — has stopped)
_LIVE_HEATMAPS: "weakref.WeakSet" = weakref.WeakSet()


def flush_heatmaps() -> None:
    """Flush every live heat map's accumulated deltas into their Prometheus
    children.  The HTTP /metrics handler calls this BEFORE exposition:
    collector iteration order puts the registered _FlushCollector after the
    counter families, so relying on it alone would lag the rule-fired
    series by one scrape once traffic (and the in-fold flush) stops."""
    for heat in list(_LIVE_HEATMAPS):
        try:
            heat.flush()
        except Exception:
            pass


class _FlushCollector:
    """Zero-series collector whose collect() flushes every live heat map —
    registering it ties scrape time to flush time for registry consumers
    that bypass the HTTP handler (one-scrape lag at worst)."""

    def collect(self):
        flush_heatmaps()
        return []


try:
    from prometheus_client import REGISTRY as _PROM_REGISTRY

    _PROM_REGISTRY.register(_FlushCollector())
except Exception:  # pragma: no cover - prometheus is baked in, but stay safe
    pass


class HeatMap:
    """Per-snapshot attribution folder: kernel config rows → (authconfig
    name, per-evaluator rule sources), with cached Prometheus label
    children per (row, firing column).

    ``fold(rows, firing)`` is the one entry point both lanes call once per
    batch: rows/firing are int arrays; the composite-key bincount keeps the
    Python work bounded by distinct (config, rule) pairs."""

    # Prometheus flush cadence: fold() accumulates into a plain int64 array
    # (one vectorized np.add.at per batch — Python work is O(1) per batch);
    # the per-(config,rule) counter children only see the accumulated
    # deltas every FLUSH_S seconds, on a /debug read, or at scrape time
    # (the registered _FlushCollector).  Counters may lag a flush period;
    # they never lose counts.
    FLUSH_S = 2.0

    def __init__(self, names_by_row: Sequence[str],
                 sources_by_row: Sequence[Sequence[str]], n_evaluators: int,
                 configs_per_shard: Optional[int] = None):
        self.names_by_row = list(names_by_row)
        self.sources_by_row = [list(s) for s in sources_by_row]
        self.E = int(n_evaluators)
        # mesh corpora: rows arrive (shard, row) and flatten as
        # shard * configs_per_shard + row; None = single corpus
        self.configs_per_shard = configs_per_shard
        self._children: Dict[int, Any] = {}   # composite key -> counter child
        self._lock = threading.Lock()
        n_keys = max(1, len(self.names_by_row)) * (self.E + 1)
        self._counts = np.zeros(n_keys, dtype=np.int64)
        self._flushed = np.zeros(n_keys, dtype=np.int64)
        self._last_flush = time.monotonic()
        self.fold_calls = 0       # per-batch evidence for the perf guard
        self.fold_seconds = 0.0   # cumulative fold cost (bench overhead delta)
        _LIVE_HEATMAPS.add(self)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_policy(cls, policy) -> "HeatMap":
        names = [""] * policy.n_configs
        for name, row in policy.config_ids.items():
            names[row] = name
        return cls(names, policy.rule_sources(),
                   int(policy.eval_rule.shape[1]))

    @classmethod
    def from_sharded(cls, sharded) -> "HeatMap":
        """Mesh corpora: rows flatten as shard * G + row (the same flat key
        native _post_complete_telemetry already bins by)."""
        G = sharded.configs_per_shard
        names = [""] * (sharded.n_shards * G)
        sources: List[List[str]] = [[] for _ in range(sharded.n_shards * G)]
        for s, pol in enumerate(sharded.shards):
            srcs = pol.rule_sources()
            for name, row in pol.config_ids.items():
                names[s * G + row] = name
                sources[s * G + row] = srcs[row]
        return cls(names, sources, int(sharded.shards[0].eval_rule.shape[1]),
                   configs_per_shard=G)

    @classmethod
    def for_snapshot(cls, policy=None, sharded=None) -> "Optional[HeatMap]":
        if sharded is not None:
            return cls.from_sharded(sharded)
        if policy is not None:
            return cls.from_policy(policy)
        return None

    # -- folding -----------------------------------------------------------

    def fold(self, rows, firing, shards=None) -> None:
        """Fold one batch's attribution into the heat map: ONE vectorized
        np.add.at into the composite-key count array — Python work is O(1)
        per batch, independent of batch size AND of the number of distinct
        rules.  Prometheus children are refreshed by flush() (amortized
        here on the FLUSH_S cadence, and forced by scrapes/debug reads).

        fold_seconds meters THREAD CPU time, not wall: on a saturated box
        the encode-pool thread gets preempted mid-fold, and a wall meter
        would bill those descheduled gaps to the fold (observed ~100x
        inflation on the CPU-only bench image, where the 'device' kernel
        competes for the same cores)."""
        t0 = time.thread_time()
        rows = np.asarray(rows, dtype=np.int64)
        firing = np.asarray(firing, dtype=np.int64)
        if shards is not None and self.configs_per_shard:
            rows = np.asarray(shards, dtype=np.int64) * \
                self.configs_per_shard + rows
        self.fold_calls += 1
        denied = firing >= 0
        if denied.any():
            comp = rows[denied] * (self.E + 1) + firing[denied]
            with self._lock:
                np.add.at(self._counts, comp, 1)
        if time.monotonic() - self._last_flush > self.FLUSH_S:
            self._flush_locked_free()
        self.fold_seconds += time.thread_time() - t0

    def flush(self) -> None:
        """Push accumulated deltas into the per-(config,rule) Prometheus
        children and the process-wide fired set.  Cost is bounded by the
        number of distinct pairs that moved since the last flush — paid on
        the flush cadence / scrape, never per batch."""
        self._flush_locked_free()

    def _flush_locked_free(self) -> None:
        with self._lock:
            delta = self._counts - self._flushed
            moved = np.nonzero(delta)[0]
            if moved.size == 0:
                self._last_flush = time.monotonic()
                return
            np.copyto(self._flushed, self._counts)
            self._last_flush = time.monotonic()
            amounts = delta[moved]
        for key, n in zip(moved, amounts):
            self._bump(int(key), int(n))

    def _bump(self, comp_key: int, n: int) -> None:
        child = self._children.get(comp_key)
        if child is None:
            row, col = divmod(comp_key, self.E + 1)
            if row >= len(self.names_by_row):
                return  # padded/unknown row: nothing to attribute
            name = self.names_by_row[row]
            sources = self.sources_by_row[row] if row < len(
                self.sources_by_row) else []
            src = sources[col] if col < len(sources) else "<padded>"
            with self._lock:
                child = self._children.get(comp_key)
                if child is None:
                    child = metrics_mod.rule_fired.labels(
                        name, rule_label(col, src))
                    self._children[comp_key] = child
            with _FIRED_LOCK:
                _FIRED.add((name, col))
        child.inc(n)

    # -- attribution lookups ----------------------------------------------

    def source(self, row: int, col: int, shard: Optional[int] = None) -> str:
        if shard is not None and self.configs_per_shard:
            row = shard * self.configs_per_shard + row
        sources = self.sources_by_row[row] if 0 <= row < len(
            self.sources_by_row) else []
        return sources[col] if 0 <= col < len(sources) else ""

    def name(self, row: int, shard: Optional[int] = None) -> str:
        if shard is not None and self.configs_per_shard:
            row = shard * self.configs_per_shard + row
        return self.names_by_row[row] if 0 <= row < len(
            self.names_by_row) else ""

    # -- reporting ---------------------------------------------------------

    def registered_rules(self):
        """Every real (authconfig, column, source) rule in this snapshot."""
        for row, sources in enumerate(self.sources_by_row):
            name = self.names_by_row[row]
            if not name:
                continue  # padded config row
            for col, src in enumerate(sources):
                yield name, col, src

    def to_json(self) -> Dict[str, Any]:
        self.flush()
        return {
            "configs": sum(1 for n in self.names_by_row if n),
            "rules": sum(len(s) for r, s in enumerate(self.sources_by_row)
                         if self.names_by_row[r]),
            "fold_calls": self.fold_calls,
            "fold_seconds": round(self.fold_seconds, 6),
        }


def dead_rule_report(heat: Optional[HeatMap],
                     analysis: Optional[Dict[str, Any]],
                     limit: int = 100) -> Optional[Dict[str, Any]]:
    """Cross-reference the heat map's never-fired set against the static
    policy-analysis findings (PR 4): a rule that static analysis already
    called constant-allow CANNOT fire (it never denies) — expected-dead;
    a never-fired rule with no static explanation is runtime-dead policy
    surface worth pruning.  /debug/vars ``engine.provenance.dead_rules``."""
    if heat is None:
        return None
    heat.flush()  # the fired set must reflect every folded batch
    # keyed (config, evaluator index): a constant-allow finding on
    # evaluator 0 must not "explain" evaluator 1's silence — per-config
    # keying would mark live-but-quiet rules as safe to prune
    static_by_rule: Dict[Any, List[str]] = {}
    for f in (analysis or {}).get("findings", []):
        kind = f.get("kind", "")
        if kind in ("constant-allow", "shadowed-rule", "duplicate-rule"):
            d = f.get("detail") or {}
            cfg = str(d.get("config", ""))
            ev = d.get("evaluator")
            key = (cfg, int(ev)) if ev is not None else cfg
            static_by_rule.setdefault(key, []).append(kind)
    fired = fired_pairs()
    never: List[Dict[str, Any]] = []
    total = fired_n = 0
    for name, col, src in heat.registered_rules():
        total += 1
        if (name, col) in fired:
            fired_n += 1
            continue
        if len(never) < limit:
            never.append({
                "authconfig": name,
                "rule": rule_label(col, src),
                # evaluator-keyed findings first; config-wide ones (no
                # evaluator in the finding detail) apply to every column
                "static_findings": (static_by_rule.get((name, col), []) +
                                    static_by_rule.get(name, [])),
            })
    return {
        "rules_total": total,
        "rules_fired": fired_n,
        "never_fired_count": total - fired_n,
        "never_fired": never,
        "statically_explained": sum(1 for d in never if d["static_findings"]),
    }


# ---------------------------------------------------------------------------
# decision log: bounded ring of head-sampled structured decision records
# ---------------------------------------------------------------------------

# pinned record schema (tests/test_provenance.py): every record carries
# exactly these keys, so downstream log pipelines can rely on the shape.
# Schema 2 (ISSUE 13 satellite): each RECORD is stamped with the schema it
# was written under — a saved /debug/decisions JSON (or a capture segment
# embedding decision fields) names its own version, so offline readers
# reject skew with the typed DecisionSchemaError instead of misparsing.
DECISION_SCHEMA = 2
DECISION_FIELDS = ("schema", "t", "lane", "host", "authconfig", "verdict",
                   "rule", "rule_index", "latency_ms", "generation")


class DecisionSchemaError(ValueError):
    """A decision-log payload was written under a different schema version
    than this reader understands.  Typed so offline tooling (analysis
    --decisions, replay readers) fails loudly instead of misparsing."""


def check_decision_schema(payload: Any) -> None:
    """Raise :class:`DecisionSchemaError` when ``payload`` (a
    /debug/decisions-shaped dict) names a schema this reader does not
    speak.  A payload without a schema field predates versioning and is
    rejected too — silence is exactly the misparse this gate exists to
    stop."""
    got = payload.get("schema") if isinstance(payload, dict) else None
    if got != DECISION_SCHEMA:
        raise DecisionSchemaError(
            f"decision-log schema skew: payload schema {got!r} != reader "
            f"schema {DECISION_SCHEMA} (refusing to misparse; re-save the "
            f"log with a matching build)")


class DecisionLog:
    """Head-sampled decision ring, sampled STRATIFIED per tenant (ISSUE 15
    satellite).  The sampler used to be one global 1-in-N counter with at
    most one record per batch — under a zipf-headed workload the hot
    tenant's batches won essentially every fire AND its records evicted
    every cold-tenant record from the bounded ring, so /debug/decisions
    showed exactly one tenant.  Now:

    - ``should_sample_tenant(tenant, n)`` keeps an independent 1-in-N
      counter PER tenant (bounded LRU table), and ``fold_and_sample``
      fires it once per distinct tenant in the batch — at most one record
      per tenant per batch, Python work bounded by distinct tenants (the
      same composite-key discipline as the heat-map fold);
    - alongside the global ring, each tenant keeps a small per-tenant
      sub-ring (``tenant_capacity`` newest records, LRU-bounded tenants),
      so a hot tenant filling the global ring can never evict a cold
      tenant's last records — ``/debug/decisions?tenant=NAME`` serves
      them.

    ``should_sample(n)`` (the legacy global gate) remains for callers with
    no tenant axis."""

    MAX_TENANTS = 512

    def __init__(self, capacity: int = 1024, sample_n: int = 64,
                 tenant_capacity: int = 4):
        self.capacity = max(1, int(capacity))
        self.sample_n = max(1, int(sample_n))
        self.tenant_capacity = max(1, int(tenant_capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        # guards ring append vs snapshot: both lanes record concurrently
        # while /debug/decisions lists the ring, and iterating a deque
        # that another thread appends to raises RuntimeError
        self._lock = threading.Lock()
        self._seen = 0
        self._next_fire = 1  # first decision samples (head of the stream)
        # tenant -> [seen, next_fire]; insertion order is the LRU axis
        self._tenant_gate: Dict[str, list] = {}
        # tenant -> deque(maxlen=tenant_capacity) of its newest records
        self._tenant_ring: Dict[str, deque] = {}
        self.records_total = 0

    def configure(self, capacity: Optional[int] = None,
                  sample_n: Optional[int] = None) -> None:
        if capacity is not None and int(capacity) != self.capacity:
            self.capacity = max(1, int(capacity))
            with self._lock:
                self._ring = deque(self._ring, maxlen=self.capacity)
        if sample_n is not None:
            self.sample_n = max(1, int(sample_n))
            # re-arm from here: a tighter rate must not wait out the fire
            # point the old (possibly much larger) rate scheduled
            self._next_fire = self._seen + self.sample_n
            with self._lock:
                self._tenant_gate.clear()

    def should_sample(self, n_decisions: int) -> bool:
        """Advance the decision counter by this batch's size; True when the
        1-in-N sampler fires inside the batch — at most one record per
        batch, O(1) per batch (a racing add under free threading can only
        lose a sample, never add per-request work)."""
        if n_decisions <= 0:
            return False
        seen = self._seen = self._seen + n_decisions
        if seen >= self._next_fire:
            self._next_fire = seen + self.sample_n
            return True
        return False

    def should_sample_tenant(self, tenant: str, n_decisions: int) -> bool:
        """The stratified gate: this TENANT's own 1-in-N counter, advanced
        by its decision count within the batch.  The first decision a
        tenant ever shows always samples (cold tenants become visible on
        their first batch, not after N of them)."""
        if n_decisions <= 0:
            return False
        gate = self._tenant_gate.get(tenant)
        if gate is None:
            if len(self._tenant_gate) >= self.MAX_TENANTS:
                with self._lock:
                    # LRU-ish bound: drop the oldest-inserted third
                    for t in list(self._tenant_gate)[:self.MAX_TENANTS // 3]:
                        self._tenant_gate.pop(t, None)
            gate = self._tenant_gate[tenant] = [0, 1]
        gate[0] += n_decisions
        if gate[0] >= gate[1]:
            gate[1] = gate[0] + self.sample_n
            return True
        return False

    def record(self, lane: str, host: str, authconfig: str, verdict: bool,
               rule: Optional[str], rule_index: int, latency_ms: float,
               generation: Any) -> None:
        rec = {
            "schema": DECISION_SCHEMA,
            "t": time.time(),
            "lane": lane,
            "host": host,
            "authconfig": authconfig,
            "verdict": "allow" if verdict else "deny",
            "rule": rule,
            "rule_index": rule_index,
            "latency_ms": round(float(latency_ms), 3),
            "generation": generation,
        }
        with self._lock:
            self._ring.append(rec)
            self.records_total += 1
            if authconfig:
                sub = self._tenant_ring.get(authconfig)
                if sub is None:
                    if len(self._tenant_ring) >= self.MAX_TENANTS:
                        for t in list(self._tenant_ring)[
                                :self.MAX_TENANTS // 3]:
                            self._tenant_ring.pop(t, None)
                    sub = self._tenant_ring[authconfig] = deque(
                        maxlen=self.tenant_capacity)
                sub.append(rec)
        metrics_mod.decision_records.labels(lane).inc()

    def to_json(self, n: Optional[int] = None,
                tenant: Optional[str] = None) -> Dict[str, Any]:
        with self._lock:
            if tenant is not None:
                records = list(self._tenant_ring.get(tenant, ()))
            else:
                records = list(self._ring)
            tenants_tracked = len(self._tenant_ring)
        if n is not None:
            n = max(0, int(n))
            records = records[-n:] if n else []
        out = {
            "schema": DECISION_SCHEMA,
            "capacity": self.capacity,
            "sample_n": self.sample_n,
            "records_total": self.records_total,
            "records": records,
            "stratified": {
                "tenants_tracked": tenants_tracked,
                "per_tenant_capacity": self.tenant_capacity,
            },
        }
        if tenant is not None:
            out["tenant"] = tenant
        return out


# one ring per process: both lanes sample into it, the analysis CLI and
# /debug/decisions read it
DECISIONS = DecisionLog()


def fold_and_sample(heat: HeatMap, rows, firing, n: int, *, lane: str,
                    shards=None, host: str = "", latency_ms: float = 0.0,
                    generation: Any = None, host_of=None,
                    latency_of=None) -> None:
    """The one per-batch observability sequence every lane's completion
    runs: fold the batch's attribution into the heat map, then sample
    decision records STRATIFIED per tenant — at most one record per
    distinct tenant (authconfig) per batch, each tenant gated by its own
    1-in-N counter, so a zipf-hot tenant can neither win every sample nor
    evict the cold tenants' records (ISSUE 15 satellite).  Python work is
    bounded by distinct tenants in the batch, never the batch size.
    Keeping it here means a schema or sampling change lands once, not once
    per lane."""
    heat.fold(rows, firing, shards=shards)
    if not n:
        return
    rows_a = np.asarray(rows, dtype=np.int64)
    flat = rows_a
    if shards is not None and heat.configs_per_shard:
        flat = np.asarray(shards, dtype=np.int64) * \
            heat.configs_per_shard + rows_a
    uniq, first, counts = np.unique(flat, return_index=True,
                                    return_counts=True)
    for u, i, k in zip(uniq, first, counts):
        name = heat.name(int(u))
        if not DECISIONS.should_sample_tenant(name, int(k)):
            continue
        i = int(i)
        col = int(firing[i])
        row_i = int(rows_a[i])
        shard_i = int(shards[i]) if shards is not None else None
        # per-record resolvers (``host_of``/``latency_of``, called only
        # for SAMPLED tenants): each tenant's record carries ITS OWN
        # request's host/latency — the batch head's values belong to a
        # different tenant in a mixed batch, which is exactly the wrong
        # evidence in the per-tenant sub-rings
        DECISIONS.record(
            lane=lane,
            host=(host_of(i) if host_of is not None else host),
            authconfig=name,
            verdict=col < 0,
            rule=(rule_label(col, heat.source(row_i, col, shard=shard_i))
                  if col >= 0 else None),
            rule_index=col,
            latency_ms=(latency_of(i) if latency_of is not None
                        else latency_ms),
            generation=generation)


# ---------------------------------------------------------------------------
# deny-response attribution (the X-Ext-Auth-Reason / dynamic_metadata seam)
# ---------------------------------------------------------------------------


def deny_provenance(authconfig: str, rule_index: int, source: str,
                    lane: str = "engine") -> Dict[str, Any]:
    """The JSON-safe provenance object a denied response carries in Envoy
    dynamic_metadata (always) and X-Ext-Auth-Reason (knob-gated)."""
    return {
        "authconfig": authconfig,
        "rule_index": int(rule_index),
        "rule": source,
        "lane": lane,
    }


def deny_reason(prov: Optional[Dict[str, Any]]) -> str:
    """The deny message: attributed behind --expose-deny-reason, the
    reference's generic 'Unauthorized' otherwise."""
    if prov and EXPOSE_DENY_REASON:
        return (f"denied by {prov['authconfig']} "
                f"rule[{prov['rule_index']}]: {prov['rule']}")
    return "Unauthorized"
