"""Overload resilience (ISSUE 7): admission control + the adaptive window.

PR 5 made the stack survive *device* failure; this module makes it survive
*traffic* failure.  A burst above window × batch / RTT used to grow the
dispatch queue without bound until every queued request blew its deadline
at once — the classic open-loop overload collapse.  Two controllers fix
that, one per concern:

``AdmissionController`` — a CoDel-style, wait-targeted admission gate on
the submit queue.  Instead of a fixed request cap (which is either too
small at high service rates or useless at low ones), the *effective* queue
bound is derived from the observed service rate and the wait target::

    effective_cap = service_rate_ewma × target_s        (≥ a small floor)

so the standing queue can never hold more work than drains within one wait
target — under 2× overload the queue fills to the cap, every arrival
beyond it is rejected with a typed ``RESOURCE_EXHAUSTED`` at admission
(before encode, before a kernel is spent), and accepted work still meets
its deadline.  On top of the bound, the CoDel signal proper: when the
*minimum* observed queue wait stays above ``target_s`` for a full
``interval_s`` (a standing queue, not a transient burst), the controller
flips to the OVERLOADED state — surfaced on ``/readyz`` and
``auth_server_admission_state`` — and paces additional rejections with the
CoDel control law (``interval / sqrt(drop_count)``) for consumers that
have no per-request depth signal (the native slow lane).  Requests whose
propagated deadline lands inside the predicted wait + one device RTT are
rejected as ``DEADLINE_EXCEEDED`` at admission — doomed work never queues.

``AdaptiveWindow`` — the SLO-tracked controller that replaces the static
``--max-inflight-batches`` guess (and the dead ``max_delay_s`` knob) with
a measured one.  Little's law sets the target::

    window* = ceil(arrival_rate × device_RTT / batch_cut) + 1

tracked from EWMAs of the observed arrival rate, device round trip and
batch-cut size; the live window slews toward the target (fast up, slow
down, never while a backlog is standing) and is HARD-clamped to
``[1, cap]`` where ``cap`` is the configured ``max_inflight_batches`` —
the perf_guard invariant tests pin exactly that clamp.  The analogous
batch-cut target ``cut* = arrival_rate × RTT / window`` (pow2-bucketed)
keeps pads full under load without a gather timer at light load.

Both controllers are import-light, allocation-free on the hot path, and
thread-safe (submit runs on event loops; observations arrive from encode
workers and the completer).  See docs/robustness.md "Overload & brownout".
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..utils import bucket_pow2
from ..utils import metrics as metrics_mod
from ..utils.rpc import DEADLINE_EXCEEDED, RESOURCE_EXHAUSTED

__all__ = ["AdmissionController", "AdaptiveWindow", "ADMIT", "OVERLOADED"]

ADMIT, OVERLOADED = "admit", "overloaded"
_STATE_VALUE = {ADMIT: 0, OVERLOADED: 1}

# rejection reasons (the `reason` label of
# auth_server_admission_rejected_total)
R_QUEUE_FULL = "queue-full"    # hard queue_cap exceeded
R_OVERLOAD = "overload"        # wait-targeted effective cap exceeded
R_DOOMED = "doomed-deadline"   # could not complete inside the deadline


class AdmissionController:
    """Wait-targeted admission gate for one serving lane.

    Feeds (any thread):
      - ``observe_waits(waits)``   per-request queue waits of one batch cut
      - ``observe_service(rows)``  rows completed (service-rate estimator)
    Decisions:
      - ``admit(depth, deadline)`` at submit time — None, or a typed
        ``(code, reason)`` rejection; mutates CoDel drop state
      - ``precheck(deadline)``     deterministic front-door subset (no
        pacing state consumed) for the gRPC/HTTP servers
      - ``drop_now()``             CoDel-paced drop signal for consumers
        without a depth feed (the native slow lane)
    """

    def __init__(self, lane: str, target_s: float = 0.05,
                 interval_s: float = 0.5, queue_cap: int = 0,
                 min_cap: int = 64):
        self.lane = lane
        self.target_s = float(target_s)
        self.interval_s = float(interval_s)
        # hard bound on the submit queue (0 = none beyond the dynamic cap)
        self.queue_cap = int(queue_cap)
        # the dynamic cap's floor: before any service-rate observation the
        # gate must not reject a cold-start burst
        self.min_cap = max(1, int(min_cap))
        self._lock = threading.Lock()
        self._state = ADMIT
        self.wait_ewma = 0.0           # mean queue wait (estimates)
        self._min_wait = None          # min wait inside the current interval
        self._above_since: Optional[float] = None
        self._service_rate = 0.0       # rows/s EWMA
        self._svc_count = 0
        self._svc_t0: Optional[float] = None
        self._drop_next = 0.0
        self._drop_count = 0
        self._last_wait_obs = 0.0
        # lane-aware doomed-deadline floor (ISSUE 12): a callable returning
        # the FASTEST serving lane's expected service time in seconds.
        # With lane selection on, a deadline only the microsecond host lane
        # can meet is no longer doomed just because the device RTT says so
        # — the host lane will answer it.  None = device-RTT-only (the
        # pre-lane-selection behavior).
        self.lane_floor: Optional[Any] = None
        self.rejected: Dict[str, int] = {}
        self._g_state = metrics_mod.admission_state.labels(lane)
        self._g_state.set(0)
        self._g_wait = metrics_mod.admission_queue_wait.labels(lane)

    # -- feeds ---------------------------------------------------------------

    def observe_waits(self, waits, now: Optional[float] = None) -> None:
        """Fold one batch cut's per-request queue waits (seconds,
        array-like or scalar).  The batch MINIMUM drives the CoDel signal
        (a high min = a standing queue; a high mean alone = one burst)."""
        try:
            n = len(waits)
        except TypeError:
            waits, n = (waits,), 1
        if not n:
            return
        if hasattr(waits, "min"):
            # numpy path (the engine's per-cut wait array): vectorized —
            # builtin min()/sum() would iterate element-by-element on the
            # encode hot path
            w_min = float(waits.min())
            w_mean = float(waits.mean())
        else:
            w_min = min(waits)
            w_mean = sum(waits) / n
        now = time.monotonic() if now is None else now
        with self._lock:
            self._last_wait_obs = now
            self.wait_ewma = (w_mean if not self.wait_ewma
                              else 0.8 * self.wait_ewma + 0.2 * w_mean)
            self._g_wait.set(self.wait_ewma)
            if self._min_wait is None or w_min < self._min_wait:
                self._min_wait = w_min
            if self._min_wait <= self.target_s:
                # the standing queue cleared inside the interval
                self._above_since = None
                self._min_wait = None
                self._set_state(ADMIT)
            elif self._above_since is None:
                self._above_since = now
            elif now - self._above_since >= self.interval_s:
                if self._state is not OVERLOADED:
                    self._set_state(OVERLOADED)
                    self._drop_count = 0
                    self._drop_next = now
                self._min_wait = None  # re-measure each interval
                self._above_since = now

    def observe_service(self, rows: int, now: Optional[float] = None) -> None:
        """Count completed rows toward the service-rate EWMA (fed by batch
        completions — device, degraded and brownout lanes all count: they
        all drain the queue)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._svc_t0 is None:
                self._svc_t0 = now
                self._svc_count = rows
                return
            self._svc_count += rows
            dt = now - self._svc_t0
            if dt < 0.1:
                return  # too short a window for a stable rate
            rate = self._svc_count / dt
            self._service_rate = (rate if not self._service_rate
                                  else 0.7 * self._service_rate + 0.3 * rate)
            self._svc_t0, self._svc_count = now, 0

    # -- decisions -----------------------------------------------------------

    def effective_cap(self) -> int:
        """The wait-targeted queue bound: no more standing work than the
        observed service rate drains within one wait target."""
        dyn = int(self._service_rate * self.target_s)
        cap = max(self.min_cap, dyn)
        if self.queue_cap:
            cap = min(cap, self.queue_cap)
        return cap

    def predicted_wait(self, depth: int) -> float:
        """Expected queue wait of a request admitted at ``depth``."""
        if self._service_rate > 0:
            return depth / self._service_rate
        return self.wait_ewma

    def _doomed(self, depth: int, now: float, deadline: Optional[float],
                rtt_s: float) -> bool:
        if deadline is None:
            return False
        if self.lane_floor is not None:
            # predicted-wait is lane-aware: the service-time term is the
            # FASTEST lane's, not the device RTT — the cost model routes
            # tight-deadline work host-side instead of shedding it
            try:
                rtt_s = min(rtt_s, float(self.lane_floor()))
            except Exception:
                pass
        return deadline - now <= self.predicted_wait(depth) + rtt_s

    def _maybe_idle_reset(self, now: float) -> None:
        """Clear a stale OVERLOADED flag once the load has vanished (no
        wait observations for 2×interval) — without this, an engine that
        went overloaded and then fully idle would latch the state (no
        batch cuts = no observations) and 504 the first arrivals of the
        next quiet-period burst.  Called from every decision point."""
        if self._state is not OVERLOADED:
            return
        with self._lock:
            if (self._state is OVERLOADED
                    and now - self._last_wait_obs > 2 * self.interval_s):
                self._above_since = None
                self._min_wait = None
                self._set_state(ADMIT)

    def admit(self, depth: int, now: Optional[float] = None,
              deadline: Optional[float] = None,
              rtt_s: float = 0.0,
              doom_depth: Optional[int] = None) -> Optional[Tuple[int, str]]:
        """Admission decision for one submit at queue ``depth``.  Returns
        None (admitted) or (rpc code, reason) — the caller raises the typed
        CheckAbort and counts the metric via ``count_reject``.

        ``doom_depth`` (ISSUE 15): the depth the DOOMED-deadline predictor
        uses, when it differs from the global queue depth — the tenant QoS
        plane passes the submitting tenant's fair-share effective depth,
        so one tenant's standing backlog cannot doom another tenant's
        deadlines (the queue-bound checks below always use the real global
        ``depth``; fairness must never weaken the memory bound)."""
        now = time.monotonic() if now is None else now
        self._maybe_idle_reset(now)
        if self._doomed(depth if doom_depth is None else doom_depth,
                        now, deadline, rtt_s):
            return (DEADLINE_EXCEEDED, R_DOOMED)
        if self.queue_cap and depth >= self.queue_cap:
            return (RESOURCE_EXHAUSTED, R_QUEUE_FULL)
        if depth >= self.effective_cap():
            return (RESOURCE_EXHAUSTED, R_OVERLOAD)
        return None

    def precheck(self, depth: int, now: Optional[float] = None,
                 deadline: Optional[float] = None,
                 rtt_s: float = 0.0) -> Optional[Tuple[int, str]]:
        """Deterministic front-door subset for the gRPC/HTTP servers at
        the ACTUAL queue ``depth``: a request that arrives into a full
        hard cap, or that is doomed on arrival while the lane is
        overloaded, is rejected before a span/pipeline is even built.
        Never consumes pacing state, and never rejects anything the
        submit-time ``admit`` would accept — that gate stays the one true
        admission point."""
        now = time.monotonic() if now is None else now
        self._maybe_idle_reset(now)
        if self.queue_cap and depth >= self.queue_cap:
            return (RESOURCE_EXHAUSTED, R_QUEUE_FULL)
        if self._state is OVERLOADED and self._doomed(
                depth, now, deadline, rtt_s):
            return (DEADLINE_EXCEEDED, R_DOOMED)
        return None

    def drop_now(self, now: Optional[float] = None) -> bool:
        """CoDel-paced drop signal while OVERLOADED, for consumers without
        a per-request depth feed (the native slow lane): drops start one
        per interval and accelerate by 1/sqrt(n) until the standing queue
        clears."""
        now = time.monotonic() if now is None else now
        self._maybe_idle_reset(now)
        with self._lock:
            if self._state is not OVERLOADED:
                return False
            if now < self._drop_next:
                return False
            self._drop_count += 1
            self._drop_next = now + self.interval_s / math.sqrt(self._drop_count)
            return True

    def count_reject(self, reason: str) -> None:
        metrics_mod.admission_rejected.labels(self.lane, reason).inc()
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1

    # -- introspection -------------------------------------------------------

    def health_signal(self, depth: int) -> Dict[str, Any]:
        """Router-facing health slice (ISSUE 18): the per-replica load and
        pressure signals the fleet router's spillover/load-shift decisions
        consume, at the caller's observed queue ``depth``.  One shape for
        in-process replicas and /readyz-polled process replicas — the
        router never knows the difference."""
        return {
            "overloaded": self.overloaded,
            "queue_depth": int(depth),
            "predicted_wait_s": self.predicted_wait(depth),
            "effective_cap": self.effective_cap(),
            "rejected_total": sum(self.rejected.values()),
        }

    def _set_state(self, state: str) -> None:
        # caller holds _lock
        if state != self._state:
            self._state = state
            self._g_state.set(_STATE_VALUE[state])
            # flight recorder (ISSUE 9): admission flips ride the lifecycle
            # ring; entering OVERLOADED is an anomaly trigger (auto-dump)
            from .flight_recorder import RECORDER

            RECORDER.record(
                "admission-overloaded" if state is OVERLOADED
                else "admission", lane=self.lane, detail={"state": state})

    @property
    def state(self) -> str:
        return self._state

    @property
    def overloaded(self) -> bool:
        return self._state is OVERLOADED

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "target_s": self.target_s,
                "interval_s": self.interval_s,
                "queue_cap": self.queue_cap,
                "effective_cap": self.effective_cap(),
                "queue_wait_ewma_s": round(self.wait_ewma, 6),
                "service_rate_rps": round(self._service_rate, 1),
                "rejected": dict(self.rejected),
            }


class AdaptiveWindow:
    """Little's-law window + batch-cut controller for one serving lane.

    The live window starts AT the cap (exactly the old static behavior, so
    a cold burst is never window-starved).  Two regimes:

    - **backlog standing** (queue depth > 0 at observation): the window is
      not draining offered load — open it toward the cap (+cap/8 per
      completion) and cut full batches.  Work-conserving by construction;
      the Little's-law target is deliberately NOT consulted here, because
      a saturated lane measures arrival rate == achieved rate and tracking
      it would pin the controller to a self-consistent low-throughput
      fixed point.
    - **queue clear**: track the Little's-law target
      ``window* = ceil(rate × rtt / cut) + 1`` — up fast (+cap/4), down by
      1 per observation — so idle lanes gradually return device memory.

    ``batch_cut`` is the controller's ADVISORY cut target (Little's-law
    ``rate × rtt / window``, pow2-bucketed): surfaced on the gauge and
    /debug/vars for operators sizing --batch-size, but deliberately NOT
    clamped onto the dispatch path — the engine's cut is completion-driven
    (it grows with load and is bounded by max_batch), and fragmenting a
    standing queue into smaller cuts would land cold pad shapes (inline
    XLA compiles) on live traffic for zero pipelining gain.

    The clamp IS the contract: ``window`` and ``batch_cut`` can never
    leave their bounds whatever the observations (perf_guard-tested)."""

    def __init__(self, lane: str, cap: int, batch_cap: int,
                 enabled: bool = True):
        self.lane = lane
        self.cap = max(1, int(cap))
        self.batch_cap = max(1, int(batch_cap))
        # idle floor: even a quiet lane keeps a few slots open so the next
        # batch's encode overlaps the previous batch's wait (shrinking all
        # the way to 1 serializes encode behind the RTT)
        self.min_window = min(4, self.cap)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._window = self.cap
        self._batch_cut = self.batch_cap
        self.rtt_ewma = 0.0
        self.rate_ewma = 0.0
        self.cut_ewma = 0.0
        # monotonic arrival counter: observe_arrivals only ever ADDS (under
        # the caller's queue lock); the rate estimator reads deltas against
        # its own watermark, so there is no reset for a concurrent
        # read-modify-write to resurrect
        self._arrivals = 0
        self._arrivals_seen = 0
        self._rate_t0: Optional[float] = None
        self._g_window = metrics_mod.adaptive_window.labels(lane)
        self._g_window.set(self._window)
        self._g_cut = metrics_mod.adaptive_batch_cut.labels(lane)
        self._g_cut.set(self._batch_cut)

    # -- feeds ---------------------------------------------------------------

    def observe_arrivals(self, n: int = 1) -> None:
        """Count admitted submits.  MONOTONIC add only (callers hold their
        queue lock, so adds never race each other); the rate estimator
        never writes this counter — it tracks its own watermark."""
        self._arrivals += n

    def observe_batch(self, rtt_s: float, batch_size: int, queue_depth: int,
                      now: Optional[float] = None) -> None:
        """One batch completed: fold its device round trip and size, refresh
        the arrival-rate estimate, and step the window/cut toward target."""
        now = time.monotonic() if now is None else now
        if not (rtt_s >= 0.0) or not math.isfinite(rtt_s):
            rtt_s = 0.0  # junk observation: never poisons the EWMA
        batch_size = max(1, int(batch_size))
        with self._lock:
            self.rtt_ewma = (rtt_s if not self.rtt_ewma
                             else 0.8 * self.rtt_ewma + 0.2 * rtt_s)
            self.cut_ewma = (float(batch_size) if not self.cut_ewma
                             else 0.8 * self.cut_ewma + 0.2 * batch_size)
            if self._rate_t0 is None:
                self._rate_t0 = now
                self._arrivals_seen = self._arrivals
            else:
                dt = now - self._rate_t0
                if dt >= 0.1:
                    cur = self._arrivals
                    rate = max(0, cur - self._arrivals_seen) / dt
                    self.rate_ewma = (rate if not self.rate_ewma
                                      else 0.7 * self.rate_ewma + 0.3 * rate)
                    self._rate_t0, self._arrivals_seen = now, cur
            if not self.enabled:
                return
            w = self._window
            if queue_depth > 0:
                # WORK-CONSERVING under backlog: a standing queue means the
                # current window is not draining offered load, so open up
                # toward the cap and cut full batches to amortize the RTT.
                # (The Little's-law target below is NOT usable here: with a
                # saturated lane the measured arrival rate equals the
                # achieved rate, and tracking it pins the controller to
                # whatever throughput the too-small window happens to
                # produce — a self-consistent low fixed point.)
                w = w + max(1, self.cap // 8)
                cut = self.batch_cap
            else:
                target = max(self._target_window(), self.min_window)
                if target > w:
                    w = min(target, w + max(1, self.cap // 4))
                elif target < w:
                    w = w - 1
                cut = self._target_cut()
            self._window = min(self.cap, max(1, w))
            self._g_window.set(self._window)
            self._batch_cut = min(self.batch_cap, max(1, cut))
            self._g_cut.set(self._batch_cut)

    def _target_window(self) -> int:
        # +2 headroom over the Little's-law point: one slot so the next
        # cut's encode overlaps the current batch's wait, one for rate
        # estimation lag
        cut = max(1.0, self.cut_ewma)
        return int(math.ceil(self.rate_ewma * self.rtt_ewma / cut)) + 2

    def _target_cut(self) -> int:
        if not self.rate_ewma or not self.rtt_ewma:
            return self.batch_cap
        per_batch = self.rate_ewma * self.rtt_ewma / max(1, self._window)
        # floored at 16 (or the cap, if smaller): light load cuts whatever
        # is queued anyway, and a burst arriving into a quiet lane must not
        # be sliced into 1-row batches while the controller re-ramps
        floor = min(16, self.batch_cap)
        return max(floor, int(bucket_pow2(max(1, int(math.ceil(per_batch))))))

    # -- reads (hot path: GIL-atomic attribute reads) ------------------------

    @property
    def window(self) -> int:
        return self._window if self.enabled else self.cap

    @property
    def batch_cut(self) -> int:
        return self._batch_cut if self.enabled else self.batch_cap

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "window": self._window,
                "window_cap": self.cap,
                "batch_cut": self._batch_cut,
                "batch_cap": self.batch_cap,
                "rtt_ewma_s": round(self.rtt_ewma, 6),
                "arrival_rate_rps": round(self.rate_ewma, 1),
                "cut_ewma": round(self.cut_ewma, 1),
                "target_window": self._target_window(),
            }
