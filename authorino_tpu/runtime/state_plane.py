"""Durable local state plane: crash-safe warm restart (ISSUE 20).

A process that dies by SIGKILL loses everything the graceful-drain
choreography would have saved: the vetted serving snapshot, the proven
verdict-cache hot set, and with them the restart MTTR story — a cold
restart pays a full compile (or a control-plane round trip) before the
first verdict.  ``--state-dir`` closes that hole with a small local
write-behind store built entirely out of existing machinery:

  state_dir/
    snapshot-<generation>.atpusnap   last vetted snapshots (PR 8 container,
    MANIFEST.json                    PR 8 publisher: coalescing writer
                                     thread, tmp+fsync+rename, bounded GC)
    HOTSET.json                      verdict-cache hot-set digest (PR 18
                                     export/import, same trust boundary)

The publisher runs with ``include_loaded=True``: unlike a distribution
directory, the state dir also persists snapshots this process itself
LOADED from an upstream leader (a replica's own crash recovery).  cli.py
refuses ``--state-dir`` == ``--snapshot-source`` so the fleet loop
breaker is never weakened.

Warm start (BEFORE the control plane connects):

  snapshot phase   load_latest(state_dir) → engine.apply_published — the
                   exact replica admission gate: sha256-verified, typed
                   rejection, strict-verify re-lint when armed.  The
                   engine serves these verdicts fail-statically until the
                   first successful replica poll swaps in the leader's
                   blob via the normal delta path (a reachable leader
                   always wins; see tests/test_warm_restart.py).
  staleness        ``--max-snapshot-age`` bounds how old the blob may be
                   (manifest ``published_unix``): past the bound the
                   engine STILL serves (old verdicts beat no verdicts)
                   but /readyz degrades to "ok (degraded: stale
                   snapshot, age=...)", a ``stale-snapshot`` flight
                   anomaly dumps evidence, and
                   auth_server_snapshot_age_seconds exposes the age.
  hotset phase     load_hotset(state_dir) → fleet.warmjoin.import_hotset:
                   fingerprint + interner-digest proven entries only,
                   whole digest discarded on mismatch.

Write-behind (while serving): every vetted swap re-publishes through the
coalescing publisher thread (never on the swap-listener critical path),
and the hot set is exported on a periodic cadence plus best-effort at
drain.  All writes ride utils/atomicio.py, so a SIGKILL at any instant
leaves every artifact old-valid or new-valid.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from ..snapshots.distribution import (MANIFEST, SnapshotLoadError,
                                      SnapshotPublisher, load_hotset,
                                      load_latest)
from ..utils import metrics as metrics_mod

__all__ = ["StatePlane"]

log = logging.getLogger("authorino_tpu.state_plane")


class StatePlane:
    """Owns one ``--state-dir``: warm start at boot, write-behind while
    serving, best-effort hot-set flush at drain.  Attach via
    ``engine.state_plane = plane`` so /readyz and /debug/vars see it."""

    def __init__(self, engine, state_dir: str,
                 max_snapshot_age_s: float = 0.0,
                 hotset_k: int = 1024, hotset_s: float = 30.0,
                 keep: int = 4):
        self.engine = engine
        self.state_dir = state_dir
        self.max_snapshot_age_s = max(0.0, float(max_snapshot_age_s))
        self.hotset_k = max(1, int(hotset_k))
        self.hotset_s = max(0.5, float(hotset_s))
        self.publisher = SnapshotPublisher(state_dir, keep=keep,
                                           include_loaded=True)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # warm-start provenance: which engine generation the state-dir blob
        # became, and when the leader originally published it — staleness
        # is judged against publish time, live, for as long as that
        # generation keeps serving
        self._warm_generation: Optional[int] = None
        self._published_unix: Optional[float] = None
        self._stale_reported = False
        self._superseded_logged = False
        self.warm_summary: Dict[str, Any] = {}

    # -- warm start (boot, before the control plane) -----------------------

    def _manifest_published_unix(self) -> Optional[float]:
        try:
            with open(os.path.join(self.state_dir, MANIFEST)) as f:
                return float(json.load(f).get("published_unix", 0.0)) or None
        except Exception:
            return None

    def warm_start(self) -> Dict[str, Any]:
        """Load + apply the local snapshot and import the local hot set.
        Never raises: every failure is a typed cold start for that phase
        (result recorded in auth_server_warm_restart_total{phase,result})
        — a corrupt state dir must never keep the process from booting."""
        summary: Dict[str, Any] = {"snapshot": "miss", "hotset": "miss"}
        t0 = time.monotonic()
        if not os.path.exists(os.path.join(self.state_dir, MANIFEST)):
            metrics_mod.warm_restart.labels("snapshot", "miss").inc()
            metrics_mod.warm_restart.labels("hotset", "miss").inc()
            self.warm_summary = summary
            return summary
        # snapshot phase: the replica admission gate end-to-end
        try:
            loaded = load_latest(self.state_dir)
            self.engine.apply_published(loaded)
        except SnapshotLoadError as e:
            summary["snapshot"] = "error"
            summary["snapshot_error"] = str(e)
            metrics_mod.warm_restart.labels("snapshot", "error").inc()
            log.warning("state-dir snapshot unloadable (cold start): %s", e)
        except Exception as e:
            # SnapshotRejected (admission) and anything else: typed cold
            # start, never a boot failure
            summary["snapshot"] = "error"
            summary["snapshot_error"] = str(e)
            metrics_mod.warm_restart.labels("snapshot", "error").inc()
            log.warning("state-dir snapshot rejected at admission "
                        "(cold start): %s", e)
        else:
            self._warm_generation = self.engine.generation
            self._published_unix = self._manifest_published_unix()
            age = (time.time() - self._published_unix
                   if self._published_unix else 0.0)
            metrics_mod.snapshot_age.set(age)
            stale = (self.max_snapshot_age_s > 0
                     and age > self.max_snapshot_age_s)
            summary["snapshot"] = "stale" if stale else "ok"
            summary["snapshot_generation"] = loaded.generation
            summary["snapshot_age_s"] = round(age, 3)
            metrics_mod.warm_restart.labels(
                "snapshot", summary["snapshot"]).inc()
            if stale:
                self._record_stale(age)
            log.info("warm restart: serving state-dir snapshot "
                     "generation %d fail-statically (age %.1fs%s) until "
                     "the control plane answers", loaded.generation, age,
                     ", STALE" if stale else "")
        # hotset phase: advisory — any failure is a cold cache, nothing more
        try:
            digest = load_hotset(self.state_dir)
            if digest is None:
                metrics_mod.warm_restart.labels("hotset", "miss").inc()
            else:
                from ..fleet.warmjoin import import_hotset

                imported, skipped = import_hotset(self.engine, digest)
                summary["hotset"] = "ok"
                summary["hotset_imported"] = imported
                summary["hotset_skipped"] = skipped
                metrics_mod.warm_restart.labels("hotset", "ok").inc()
        except Exception as e:
            summary["hotset"] = "error"
            summary["hotset_error"] = str(e)
            metrics_mod.warm_restart.labels("hotset", "error").inc()
            log.warning("state-dir hotset import failed (cold cache): %s", e)
        summary["warm_start_s"] = round(time.monotonic() - t0, 4)
        self.warm_summary = summary
        return summary

    def _record_stale(self, age: float) -> None:
        if self._stale_reported:
            return
        self._stale_reported = True
        from .flight_recorder import RECORDER

        RECORDER.record("stale-snapshot", lane="engine", detail={
            "age_s": round(age, 1),
            "max_snapshot_age_s": self.max_snapshot_age_s,
            "generation": self.engine.generation,
            "state_dir": self.state_dir,
        })

    # -- serving-time state ------------------------------------------------

    def serving_warm(self) -> bool:
        """True while the engine is still on the warm-start snapshot —
        i.e. no reconcile or control-plane poll has swapped since boot."""
        return (self._warm_generation is not None
                and self.engine.generation == self._warm_generation)

    def snapshot_age_s(self) -> Optional[float]:
        if not self.serving_warm() or self._published_unix is None:
            return None
        return time.time() - self._published_unix

    def stale_reason(self) -> Optional[str]:
        """The /readyz degraded reason, or None.  Judged live: a blob that
        was fresh at boot degrades once its publish time falls behind the
        bound with the control plane still unreachable; the first live
        swap clears everything."""
        age = self.snapshot_age_s()
        if age is None:
            # superseded by a live snapshot: zero the gauge once
            if not self._superseded_logged and self._warm_generation is not None \
                    and self.engine.generation != self._warm_generation:
                self._superseded_logged = True
                metrics_mod.snapshot_age.set(0.0)
            return None
        metrics_mod.snapshot_age.set(age)
        if self.max_snapshot_age_s > 0 and age > self.max_snapshot_age_s:
            self._record_stale(age)
            return f"stale snapshot, age={age:.0f}s"
        return None

    # -- write-behind ------------------------------------------------------

    def start(self) -> None:
        """Attach the coalescing publisher (every vetted swap persists,
        off the swap-listener critical path) and start the periodic
        hot-set export."""
        self.publisher.attach(self.engine)
        if self._thread is None:
            self._thread = threading.Thread(target=self._hotset_loop,
                                            name="atpu-state-hotset",
                                            daemon=True)
            self._thread.start()

    def export_hotset_once(self) -> bool:
        """One hot-set export to the state dir (periodic cadence and the
        drain path).  Best-effort: False on nothing-to-export or failure."""
        try:
            from ..fleet.warmjoin import export_hotset

            digest = export_hotset(self.engine, k=self.hotset_k)
            if digest is None:
                return False
            self.publisher.publish_hotset(digest)
            return True
        except Exception:
            log.exception("state-dir hotset export failed "
                          "(serving unaffected)")
            return False

    def _hotset_loop(self) -> None:
        while not self._stop.wait(self.hotset_s):
            self.export_hotset_once()

    def shutdown(self, timeout_s: float = 2.0) -> None:
        """Drain hook: stop the cadence, flush the publisher (so the last
        vetted swap is on disk) and export the final hot set — all
        best-effort and bounded; drain must finish on time regardless."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=min(1.0, timeout_s))
        try:
            self.publisher.flush(timeout_s=timeout_s)
        except Exception:
            pass
        self.export_hotset_once()

    def to_json(self) -> Dict[str, Any]:
        age = self.snapshot_age_s()
        return {
            "state_dir": self.state_dir,
            "max_snapshot_age_s": self.max_snapshot_age_s,
            "hotset_k": self.hotset_k,
            "hotset_s": self.hotset_s,
            "serving_warm": self.serving_warm(),
            "snapshot_age_s": (round(age, 1) if age is not None else None),
            "stale": bool(self.stale_reason()),
            "warm_start": dict(self.warm_summary),
        }
