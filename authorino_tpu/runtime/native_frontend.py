"""Python half of the native device-owner gRPC frontend (native/frontend.cpp).

One process owns the TPU; the wire runs in C++.  This module decides, per
AuthConfig, whether its FULL pipeline semantics reduce to a native
decision — the *fast lane*:

  - compiled pattern-matching authorization (`when` conditions included):
    packed column 0 is exactly the pipeline's decision
    (ops/pattern_eval.py eval_verdicts), single-corpus or mesh-sharded;
  - identity as an ordered OR of sources: anonymous, API keys (per-key
    plan variants resolved at refresh), and OIDC/JWT + mTLS through a
    verified-credential cache registered by the slow lane (TTL-bounded by
    exp/notAfter; JWKS/CA rotation swaps the cache away);
  - auth.*-only identity extensions and DynamicJSON/Plain response
    templates, precomputed per identity outcome (OK bytes per variant);
  - static denyWith templates, all-sources-failed answers per
    static-credential-presence bitmask.

It builds the C++ encode plans + byte-exact response templates (with the
same pb2 code as service/grpc_server.py so fast-lane responses match the
Python server bit for bit), and runs two kinds of Python threads:

  - dispatchers: one JAX dispatch per micro-batch (the only per-batch Python)
  - slow lane: full AuthPipeline for everything else (unknown/expired
    credentials, metadata fetches, Rego, templated denyWith, sampled
    traces, …) with continuous admission and graceful-drain shutdown

Reference parity: main.go:437-488 (one-process gRPC server),
pkg/service/auth.go:239-310 (Check flow incl. host override + port strip).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..authjson import selector as sel
from ..compiler.compile import (
    DFA_VALUE_BYTES,
    OP_CPU,
    OP_REGEX_DFA,
    OP_TREE_CPU,
    CompiledPolicy,
)
from ..compiler.intern import PAD
from ..compiler.pack import _trim_bytes, wire_dtype
from ..evaluators import credentials as cred_mod
from ..evaluators.base import DenyWithValues, RuntimeAuthConfig
from ..evaluators.authorization import OPA as OPAEval
from ..evaluators.authorization import PatternMatching
from ..evaluators.identity import APIKey, KubernetesAuth, MTLS, Noop, OAuth2
from ..evaluators.identity.api_key import INVALID_API_KEY_MSG
from ..evaluators.identity.oidc import OIDC
from ..pipeline.pipeline import AuthPipeline, AuthResult
from ..utils import bucket_pow2
from ..utils import metrics as metrics_mod
from ..utils import tracing as tracing_mod
from ..utils.verdict_cache import VerdictCache
from ..utils.rpc import (
    INVALID_ARGUMENT,
    NOT_FOUND,
    OK,
    PERMISSION_DENIED,
    UNAUTHENTICATED,
)
from . import faults
from . import provenance as prov_mod
from .admission import AdmissionController
from .breaker import CircuitBreaker
from .flight_recorder import RECORDER
from . import kernel_cost as kernel_cost_mod
from .kernel_cost import LEDGER, CostModel
from .lane_select import DEVICE as L_DEVICE, HOST as L_HOST, LaneSelector

log = logging.getLogger("authorino_tpu.native_frontend")

__all__ = ["NativeFrontend", "fast_lane_eligible", "FastLaneSpec"]

# plan kinds — must match native/frontend.cpp PlanKind
K_CONST, K_METHOD, K_PATH, K_URL_PATH, K_QUERY, K_HOST, K_SCHEME = range(7)
K_PROTOCOL, K_SIZE, K_FRAGMENT, K_HEADER, K_CTX_EXT = range(7, 12)

EV_TIMEOUT, EV_BATCH, EV_SNAP_RETIRED, EV_STOPPED = 0, 1, 3, 4

_SIMPLE = {
    ("request", "method"): (K_METHOD, ""),
    ("request", "path"): (K_PATH, ""),
    ("request", "url_path"): (K_URL_PATH, ""),
    ("request", "query"): (K_QUERY, ""),
    ("request", "host"): (K_HOST, ""),
    ("request", "scheme"): (K_SCHEME, ""),
    ("request", "protocol"): (K_PROTOCOL, ""),
    ("request", "size"): (K_SIZE, ""),
    ("request", "fragment"): (K_FRAGMENT, ""),
    ("request", "referer"): (K_HEADER, "referer"),
    ("request", "user_agent"): (K_HEADER, "user-agent"),
}


def _classify_selector(selector_str: str):
    """("req", kind, key) for a request-derived attr, ("auth",) for one that
    resolves over the identity-dependent ``auth.*`` subtree (constant per
    identity outcome), or None when the fast lane cannot encode it."""
    if not selector_str or selector_str[0] in "{[":
        return None
    try:
        segs = sel._parse_path(selector_str)
    except Exception:
        return None
    if not all(s.kind == "key" for s in segs):
        # gjson-extended selectors over the auth tree still resolve
        # constantly per identity; anything touching the request needs the
        # full engine
        keys0 = selector_str.split(".", 1)[0].split("|", 1)[0]
        if keys0 == "auth":
            return ("auth",)
        return None
    keys = tuple(s.key for s in segs)
    if keys in _SIMPLE:
        kind, key = _SIMPLE[keys]
        return ("req", kind, key)
    if len(keys) == 3 and keys[:2] == ("request", "headers"):
        return ("req", K_HEADER, keys[2])
    if len(keys) == 3 and keys[:2] == ("request", "context_extensions"):
        return ("req", K_CTX_EXT, keys[2])
    # legacy context.* mirrors that share exact semantics with the wellknown
    # forms (context_dict filters ""-valued scalar fields, so only the
    # unfiltered maps are plannable)
    if len(keys) == 5 and keys[:4] == ("context", "request", "http", "headers"):
        return ("req", K_HEADER, keys[4])
    if len(keys) == 3 and keys[:2] == ("context", "context_extensions"):
        return ("req", K_CTX_EXT, keys[2])
    if keys[0] == "auth":
        return ("auth",)
    return None


def _const_plan(policy: CompiledPolicy, attr: int, const_doc: Dict[str, Any]):
    """K_CONST plan tuple for `attr` resolved against a constant auth doc,
    or None when the compact device payload can't hold the value (membership
    overflow / DFA byte-tensor unfit) — which disqualifies the config."""
    from ..compiler.encode import _MISSING, _render

    res = sel.get(const_doc, policy.attr_selectors[attr])
    K = policy.members_k
    v = res.value if res.exists else _MISSING
    rendered = _render(v)
    vid = policy.interner.lookup(rendered)
    missing = v is _MISSING or v is None
    members: List[int] = []
    if isinstance(v, list):
        if len(v) > K:
            return None  # const membership overflow: host oracle only
        members = [policy.interner.lookup(_render(e)) for e in v]
    elif not missing:
        members = [vid]
    raw = rendered.encode("utf-8")
    if int(policy.attr_byte_slot[attr]) >= 0 and (
        len(raw) > DFA_VALUE_BYTES or 0 in raw
    ):
        return None  # const DFA operand the byte tensor can't hold
    return (int(attr), K_CONST, "", int(vid), missing, members, raw, False)


def _const_doc(identity_obj) -> Dict[str, Any]:
    """The constant auth.* subtree of a fast-lane request: identity as
    resolved, everything else empty — the authorization phase reads the doc
    BEFORE its own results are stored, and fast-lane configs have no
    metadata/callbacks."""
    return {
        "auth": {
            "identity": identity_obj,
            "metadata": {},
            "authorization": {},
            "response": {},
            "callbacks": {},
        }
    }


_ANON_IDENTITY = {"anonymous": True}


def _static_value(v) -> bool:
    return v is None or not getattr(v, "pattern", "")


# auth.* subtrees that are constant per identity outcome in BOTH lanes at
# every fast-lane resolve point.  auth.authorization is NOT: the pipeline
# stores authorization results before the response phase (and before
# later-priority authorization buckets), while the fast lane's const doc
# holds {} — and a bare `auth` selector includes it
_CONST_AUTH_ROOTS = ("identity", "metadata", "response", "callbacks")


def _auth_subroot_ok(s: str) -> bool:
    parts = s.split(".")
    if len(parts) < 2:
        return False
    sub = parts[1].split("|")[0].split("#")[0].split("@")[0]
    return sub in _CONST_AUTH_ROOTS


def _auth_only_value(v) -> bool:
    """True when a JSONValue resolves constantly per identity outcome:
    static, or selectors/templates rooted entirely in the constant parts
    of the auth.* subtree."""
    from ..authjson.value import is_template, template_selectors

    if not getattr(v, "pattern", ""):
        return True
    sels = (template_selectors(v.pattern) if is_template(v.pattern)
            else [v.pattern])
    return all(_classify_selector(s) == ("auth",) and _auth_subroot_ok(s)
               for s in sels)


def _extend_identity(idc, obj):
    """Mirror IdentityConfig.resolve_extended_properties against a CONSTANT
    identity outcome: extensions read the raw identity through the doc
    (auth.identity stays raw during the loop, exactly like the pipeline's
    _sync_auth-then-extend ordering) while mutating the extended copy."""
    if not idc.extended_properties:
        return obj
    if not isinstance(obj, dict):
        raise ValueError("cannot extend non-object identity")
    doc = _const_doc(obj)
    extended = dict(obj)
    for prop in idc.extended_properties:
        extended[prop.name] = prop.resolve_for(extended, doc)
    return extended


def _response_templates_eligible(rt: RuntimeAuthConfig) -> bool:
    """Response evaluators whose outputs are constant per identity outcome
    (DynamicJSON / Plain over auth.*-only values) can precompute their OK
    CheckResponse bytes per credential variant — the 'inject an identity
    header' pattern stays on the fast lane.  Anything per-request
    (request.* selectors, Wristbands: per-request iat/exp signatures)
    disqualifies."""
    from ..evaluators.response import DynamicJSON, Plain

    for conf in rt.response:
        if conf.conditions is not None or conf.cache is not None or conf.metrics:
            return False
        ev = conf.evaluator
        if isinstance(ev, DynamicJSON):
            vals = [p.value for p in ev.properties]
        elif isinstance(ev, Plain):
            vals = [ev.value]
        else:
            return False
        if not all(_auth_only_value(v) for v in vals):
            return False
    return True


def _deny_with_static(dw: Optional[DenyWithValues]) -> bool:
    if dw is None:
        return True
    if not _static_value(dw.message) or not _static_value(dw.body):
        return False
    return all(_static_value(h.value) for h in dw.headers)


def _deny_with_const(dw: Optional[DenyWithValues]) -> bool:
    """True when every denyWith value is constant per identity outcome:
    static, or templated over the constant auth.* subtrees — then the
    denial bytes precompute per credential variant (identity-failure
    templates resolve against the empty doc, where auth-only selectors are
    constantly missing, exactly like the pipeline's identity-None doc)."""
    if dw is None:
        return True
    vals = [dw.message, dw.body] + [h.value for h in dw.headers]
    return all(v is None or _static_value(v) or _auth_only_value(v)
               for v in vals)


# AuthCredentials location → C++ CredKind (native/frontend.cpp)
_CRED_KINDS = {
    cred_mod.LOCATION_AUTH_HEADER: 1,
    cred_mod.LOCATION_CUSTOM_HEADER: 2,
    cred_mod.LOCATION_COOKIE: 3,
    cred_mod.LOCATION_QUERY: 4,
}
# mTLS: the forwarded client certificate is the credential
_CRED_KIND_CERT = 5
MISSING_CERT_MSG = "client certificate is missing"


@dataclass
class SourceSpec:
    """One identity source of a fast-lane config, in the pipeline's
    priority-then-declaration order (identity is an OR,
    ref pkg/service/auth_pipeline.go:203-258)."""

    name: str                     # IdentityConfig name (all-fail error keys)
    cred_kind: int = 0
    cred_key: str = ""
    dyn: bool = False             # OIDC/mTLS: verified-credential cache
    # static (API key): per-key plan variants resolved at refresh time
    variants: List[Tuple[bytes, List[tuple]]] = field(default_factory=list)
    idc: Any = None               # the IdentityConfig (dyn registration)
    missing_msg: str = ""         # per-source failure when credential absent
    invalid_msg: str = ""         # static: failure when the key is unknown
    # dyn: extra TTL bound from the user's own cache opt-in (OAuth2
    # introspection / K8s TokenReview)
    ttl_cap: Optional[float] = None


def _kernel_covered(conf) -> bool:
    """True when this authorization evaluator's verdict is decided by the
    compiled kernel corpus: pattern-matching evaluators with a batched
    provider, and OPA evaluators whose decidable Rego was lowered into a
    ConfigRules slot at translate time (rego_lower)."""
    if conf.cache is not None or conf.metrics:
        return False
    ev_c = conf.evaluator
    if isinstance(ev_c, PatternMatching):
        return ev_c.batched_provider is not None and conf.conditions is None
    if isinstance(ev_c, OPAEval):
        # wrapper conditions are fine: translate compiles the same gate
        # into the kernel slot AND keeps it on the pipeline
        return ev_c.kernel_slot is not None
    return False


@dataclass
class FastLaneSpec:
    """Everything the C++ frontend needs to serve one AuthConfig natively.

    ``has_batch`` configs evaluate pattern authorization through the kernel;
    configs without authorization (identity-only) decide entirely in C++.
    ``sources`` lists the config's identity sources (empty = anonymous):
    API-key sources (ref pkg/evaluators/identity/api_key.go:72-93) carry
    per-key plan variants — each known key's ``auth.identity.*`` operands
    resolved to constants at refresh time; dyn sources (OIDC/JWT,
    ref oidc.go:41-103; mTLS, ref mtls.go:23-189) use the variant map as a
    verified-credential cache registered at runtime by the slow lane, TTL
    = min(exp/notAfter, dyn_ttl).  ``auth_attrs`` carries the attr rows a
    registration must resolve per credential.  Multi-identity configs are
    an OR: the first source (priority order) whose credential resolves a
    variant wins; all-fail answers come from static templates indexed by
    which static credentials were present."""

    plans: List[tuple] = field(default_factory=list)
    has_batch: bool = False
    sources: List[SourceSpec] = field(default_factory=list)
    auth_attrs: List[int] = field(default_factory=list)
    # anonymous configs: the (possibly extended) constant identity object —
    # response templates resolve against it at swap time
    const_identity: Any = None
    # unauthorized denyWith carries identity-templated values → per-variant
    # DENY bytes must be built (else the config-default static deny serves)
    deny_templated: bool = False
    # hybrid lane: the kernel covers only part of the authorization phase —
    # a kernel DENY answers natively, a kernel PASS hands the raw request
    # to the slow lane for the full pipeline (procedural Rego/SAR/SpiceDB
    # evaluators, arbitrary responses)
    hybrid: bool = False


# bounds on the identity-source fan-out the C++ lane carries: the all-fail
# template table is 2^n_static entries, and every extra source is a per-
# request extraction attempt
_MAX_SOURCES = 4
_MAX_STATIC_SOURCES = 3


def fast_lane_eligible(entry, policy: Optional[CompiledPolicy]) -> Optional[FastLaneSpec]:
    """Returns a FastLaneSpec when `entry`'s pipeline reduces to a native
    decision (kernel verdict and/or credential map lookup), else None.
    Mirrors pipeline.evaluate() phase by phase
    (ref pkg/service/auth_pipeline.go:451-502): every feature that would
    need per-request Python work disqualifies."""
    rt: Optional[RuntimeAuthConfig] = entry.runtime
    if rt is None:
        return None
    if rt.conditions is not None:
        return None
    if rt.metadata or rt.callbacks:
        return None
    covered = [c for c in rt.authorization if _kernel_covered(c)]
    uncovered = [c for c in rt.authorization if not _kernel_covered(c)]
    # hybrid: kernel pre-filters denials, the pipeline finishes the allows —
    # so responses (which only run on OK) need no template eligibility
    hybrid = bool(covered) and bool(uncovered)
    if rt.response and not hybrid and not _response_templates_eligible(rt):
        return None
    if not rt.identity or len(rt.identity) > _MAX_SOURCES:
        return None
    for idc in rt.identity:
        if idc.conditions is not None:
            return None
        # per-evaluator TTL caches run in the pipeline — except for the
        # revocable-credential identities (OAuth2 introspection, K8s
        # TokenReview), whose opt-in caches the dyn lane honors itself
        # (checked in the source builder)
        if idc.cache is not None and not isinstance(
                idc.evaluator, (OAuth2, KubernetesAuth)):
            return None
        if idc.metrics or metrics_mod.DEEP_METRICS_ENABLED:
            return None  # deep per-evaluator series need the pipeline
        # identity extensions are constant per identity outcome when their
        # values resolve over auth.* only (ref pkg/evaluators/
        # identity_extension.go) — applied at variant-build time
        if idc.extended_properties and not all(
                _auth_only_value(e.value) for e in idc.extended_properties):
            return None
    is_noop = len(rt.identity) == 1 and isinstance(rt.identity[0].evaluator, Noop)
    sources: List[SourceSpec] = []
    if not is_noop:
        # identity sources in the pipeline's priority-then-declaration
        # order (ascending priority buckets; within a bucket the pipeline
        # RACES — the reference's outcome there is scheduling-dependent, so
        # any single winner is within its semantics)
        ordered = sorted(enumerate(rt.identity), key=lambda p: (p[1].priority, p[0]))
        for _, idc in ordered:
            ident = idc.evaluator
            if isinstance(ident, APIKey):
                kind = _CRED_KINDS.get(ident.credentials.location, 0)
                if kind == 0:
                    return None
                key_sel = ident.credentials.key_selector
                src = SourceSpec(
                    name=idc.name, cred_kind=kind,
                    cred_key=key_sel.lower() if kind == 2 else key_sel,
                    idc=idc, missing_msg="credential not found",
                    invalid_msg=INVALID_API_KEY_MSG)
            elif isinstance(ident, OIDC):
                kind = _CRED_KINDS.get(ident.credentials.location, 0)
                if kind == 0:
                    return None
                key_sel = ident.credentials.key_selector
                src = SourceSpec(
                    name=idc.name, cred_kind=kind,
                    cred_key=key_sel.lower() if kind == 2 else key_sel,
                    dyn=True, idc=idc, missing_msg="credential not found")
            elif isinstance(ident, MTLS):
                src = SourceSpec(name=idc.name, cred_kind=_CRED_KIND_CERT,
                                 dyn=True, idc=idc,
                                 missing_msg=MISSING_CERT_MSG)
            elif isinstance(ident, (OAuth2, KubernetesAuth)):
                # revocable credentials: the AS/apiserver check IS the
                # revocation check — cacheable ONLY when the user
                # explicitly opted in via a `cache` spec keyed by the
                # credential header (the reference's own TTL-cache
                # semantics, ref pkg/evaluators/cache.go:16-89); the dyn
                # entry is then bounded by that TTL (and a response exp)
                if idc.cache is None:
                    return None
                if isinstance(ident, KubernetesAuth) and not ident.audiences:
                    return None  # default audience is the REQUEST host
                kind = _CRED_KINDS.get(ident.credentials.location, 0)
                if kind not in (1, 2):
                    return None  # header credentials map 1:1 to cache keys
                key_sel = ident.credentials.key_selector
                hdr = ("authorization" if kind == 1 else key_sel.lower())
                if idc.cache.key_pattern not in (
                        f"request.headers.{hdr}",
                        f"context.request.http.headers.{hdr}"):
                    return None
                src = SourceSpec(
                    name=idc.name, cred_kind=kind,
                    cred_key=key_sel.lower() if kind == 2 else key_sel,
                    dyn=True, idc=idc, missing_msg="credential not found",
                    ttl_cap=float(idc.cache.ttl))
            else:
                return None  # incl. Noop mixed into a multi-identity OR
            sources.append(src)
        if sum(1 for s in sources if not s.dyn) > _MAX_STATIC_SOURCES:
            return None
        # all-fail answers come from constant templates — the identity-
        # failure denyWith must resolve without a request doc (auth-only
        # values are constantly missing there, like the pipeline's)
        if not _deny_with_const(rt.deny_with.unauthenticated):
            return None

    plans: List[tuple] = []
    auth_attrs: List[int] = []
    has_batch = False
    if rt.authorization:
        if entry.rules is None or policy is None:
            return None
        row = policy.config_ids.get(entry.rules.name)
        if row is None:
            return None
        if not covered or len(covered) != len(entry.rules.evaluators):
            return None
        if uncovered:
            # a kernel pre-deny must not preempt an uncovered evaluator the
            # pipeline would have FAILED in an earlier priority bucket
            # (its denial could differ); same-bucket outcomes race in the
            # reference (ref pkg/service/auth_pipeline.go:160-199), so any
            # single winner there is within its semantics
            if max(c.priority for c in covered) > min(
                    u.priority for u in uncovered):
                return None
        if not _deny_with_const(rt.deny_with.unauthorized):
            return None
        # per-request regex/tree oracles cannot run in C++
        for leaf in policy.config_cpu_leaves[row]:
            if int(policy.leaf_op[leaf]) in (OP_CPU, OP_TREE_CPU):
                return None
        has_batch = True
        for attr in policy.config_attrs[row]:
            sel_str = policy.attr_selectors[attr]
            c = _classify_selector(sel_str)
            if c is None:
                return None
            if c[0] == "req":
                plans.append((int(attr), c[1], c[2], 0, False, [], b"", False))
            else:
                # auth.authorization-rooted pattern operands would see the
                # pipeline's earlier-bucket results but the const doc's {} —
                # only the truly constant subtrees are plannable
                if not _auth_subroot_ok(sel_str):
                    return None
                auth_attrs.append(int(attr))
    elif entry.rules is not None and entry.rules.evaluators:
        return None  # compiled rules without runtime authz configs: engine bug

    spec = FastLaneSpec(plans=plans, has_batch=has_batch, sources=sources,
                        auth_attrs=auth_attrs, hybrid=hybrid,
                        deny_templated=has_batch and not _deny_with_static(
                            rt.deny_with.unauthorized))
    if is_noop:
        try:
            spec.const_identity = _extend_identity(rt.identity[0],
                                                   dict(_ANON_IDENTITY))
        except ValueError:
            return None
        doc = _const_doc(spec.const_identity)
        for attr in auth_attrs:
            p = _const_plan(policy, attr, doc)
            if p is None:
                return None
            spec.plans.append(p)
        return spec
    # API-key sources: resolve each known key's auth.* operands to
    # constants (the fast-lane analog of precompile-at-reconcile,
    # ref pkg/evaluators/authorization/opa.go:141); dyn sources register
    # their variants at runtime (NativeFrontend._register_dyn)
    for src in sources:
        if src.dyn:
            continue
        for key, secret in src.idc.evaluator.snapshot_secrets().items():
            try:
                ident_obj = _extend_identity(src.idc,
                                             secret.to_identity_object())
            except ValueError:
                return None
            vplans: List[tuple] = []
            if auth_attrs:
                doc = _const_doc(ident_obj)
                for attr in auth_attrs:
                    p = _const_plan(policy, attr, doc)
                    if p is None:
                        return None
                    vplans.append(p)
            # the identity object rides along so refresh can precompute the
            # per-key OK/DENY bytes for response/denyWith-template configs
            # (hybrid OKs are answered by the pipeline, which runs the
            # response phase itself — no per-key OK bytes there)
            src.variants.append((
                key.encode("utf-8"), vplans,
                ident_obj if ((rt.response and not hybrid)
                              or spec.deny_templated) else None))
    return spec


@dataclass
class _SnapRec:
    snap_id: int
    policy: CompiledPolicy
    params: Any
    encoder: Any                       # NativeEncoder (owns the Policy capsule)
    sharded: Any = None                # ShardedPolicyModel (mesh corpora)
    arrays: List[Dict[str, np.ndarray]] = field(default_factory=list)
    keepalive: List[np.ndarray] = field(default_factory=list)
    fc_rows: Optional[np.ndarray] = None
    row_labels: Dict[int, Tuple[str, str]] = field(default_factory=dict)
    # jit bucket variants already compiled for this snapshot's params:
    # (batch_pad, byte_eff) pairs; 0 byte_eff = no DFA lane.  _dispatch only
    # uses warmed shapes (rounding up) so XLA compiles never land on live
    # requests (the precompile-at-reconcile discipline,
    # ref pkg/evaluators/authorization/opa.go:141)
    warm: set = field(default_factory=set)
    warm_done: threading.Event = field(default_factory=threading.Event)
    # configs with dyn sources: entry.id → (fc_idx, auth_attrs, policy,
    # {id(IdentityConfig): (source idx, ttl cap)}, hybrid) — the slow lane
    # registers verified-credential plan variants against this snapshot
    # (policy = the entry's OWN compile: its shard's on a mesh; hybrid
    # suppresses per-credential OK bytes — the pipeline answers those)
    dyn_regs: Dict[str, Tuple[int, List[int], Any,
                              Dict[int, Tuple[int, Optional[float]]],
                              bool]] = field(default_factory=dict)
    # kernel rows of HYBRID configs (same key type as row_labels): dispatch
    # attribution must count only their native denials — kernel-allowed
    # requests continue into the pipeline, which observes them itself
    hybrid_rows: set = field(default_factory=set)
    # verdict-cache eligibility per kernel row: [G] bool (single corpus) or
    # [S, G] (mesh) — compiler/compile.py config_cacheable
    cacheable: Optional[np.ndarray] = None
    # per-config verdict-cache key tokens (ISSUE 8): (encoding epoch,
    # config source fingerprint) per kernel row, inherited from the engine
    # snapshot.  Entries of configs a reconcile did NOT touch stay
    # reachable across fe snapshots — the cache survives churn.  None
    # (mesh corpora, or pre-fingerprint snapshots) falls back to PR 3's
    # snap_id keying.
    cache_tokens: Optional[list] = None
    # host (numpy) operand pytree for the host serving lane (ISSUE 12) and
    # the degraded lane: the same kernel on the CPU backend.  Built eagerly
    # by the pre-warm thread at snapshot swap (lazily as a fallback), so
    # the first host-lane decision after a reconcile is not a CPU
    # jit-compile latency spike.
    host_params: Any = None
    # CPU-backend jit variants already compiled against host_params:
    # (batch_pad, byte_eff) pairs — _host_eval rounds up into this set
    host_warm: set = field(default_factory=set)
    # decision provenance (ISSUE 9): the rule heat map binding this
    # snapshot's kernel rows to (authconfig, rule source) — shared with the
    # engine snapshot's instance when one exists, so both lanes fold into
    # one label-children cache
    heat: Any = None


class NativeFrontend:
    """Owns the C++ server lifecycle + the dispatcher/slow-lane threads."""

    def __init__(self, engine, port: int = 0, max_batch: int = 1024,
                 window_us: int = 2000, slots: int = 16, slow_cap: int = 65536,
                 dispatch_threads: int = 6, bind_all: bool = False,
                 dyn_ttl_s: float = 600.0, trace_sample_n: int = 128,
                 verdict_cache_size: int = 32768, batch_dedup: bool = True,
                 strict_verify: bool = False,
                 device_timeout_s: Optional[float] = None,
                 breaker_threshold: int = 5, breaker_reset_s: float = 5.0,
                 admission_target_s: float = 0.05,
                 brownout: bool = True, brownout_max_rows: int = 64,
                 lane_select: bool = True, lane_host_max_rows: int = 64,
                 slo_ms: float = 0.0,
                 kernel_lane: Optional[str] = None):
        self.engine = engine
        # ISSUE 17: kernel lane override (None = env default
        # AUTHORINO_TPU_KERNEL_LANE) applied when refresh() builds params
        # for snapshots the engine did not already upload
        self.kernel_lane = kernel_lane
        # fault tolerance (ISSUE 5, docs/robustness.md): a failed device
        # batch retries once, then degrades to the SAME kernel on the CPU
        # backend (fail-closed deny only if that fails too); consecutive
        # failures trip the breaker and whole batches skip the device; the
        # readback watchdog abandons batches wedged past --device-timeout
        self.breaker = CircuitBreaker("native", threshold=breaker_threshold,
                                      reset_s=breaker_reset_s)
        self.device_timeout_s = (float(device_timeout_s)
                                 if device_timeout_s else None)
        # --strict-verify: tensor-lint every snapshot in refresh() BEFORE
        # fe_swap — a corrupt corpus never becomes the serving C++ snapshot
        # (the old one keeps serving; auth_server_snapshot_rejected_total)
        self.strict_verify = bool(strict_verify)
        # batch row dedup + snapshot-scoped verdict cache, mirroring the
        # engine lane (runtime/engine.py): the device evaluates unique rows
        # only, and cached (snap_id, row-digest) verdicts skip it entirely.
        # Cache hits/misses/adds are folded into the frontend's dyn_hit/
        # dyn_miss/dyn_add stats keys (see stats()).
        self.batch_dedup = bool(batch_dedup)
        self._verdict_cache = (VerdictCache(verdict_cache_size)
                               if verdict_cache_size else None)
        # verified-token cache entries live at most this long (and never
        # past the token's own exp claim)
        self.dyn_ttl_s = float(dyn_ttl_s)
        # with tracing active, 1-in-N requests take the slow lane with full
        # span export; the rest serve natively.  AUTHORINO_TPU_TRACE_ALL=1
        # restores the reference's every-request tracing (at slow-lane
        # throughput — the reference traces in-process,
        # ref pkg/trace/trace.go:20-27)
        if os.environ.get("AUTHORINO_TPU_TRACE_ALL", "").lower() in (
                "1", "true", "yes"):
            trace_sample_n = 1
        self.trace_sample_n = max(1, int(trace_sample_n))
        self._trace_mode_logged = False
        self.port = port
        self.bind_all = bind_all
        self.max_batch = int(max_batch)
        self.window_us = int(window_us)
        self.slots = int(slots)
        self.slow_cap = int(slow_cap)
        # dispatchers only ENCODE + LAUNCH (readback rides the dedicated
        # readback thread), so a couple of threads saturate the C++ batch
        # queue; the in-flight window is the slot count, not this number
        self.dispatch_threads = int(dispatch_threads)
        self._mod = None
        self._snaps: Dict[int, _SnapRec] = {}
        self._next_snap_id = 1
        # kernel-cost observatory (ISSUE 16): XLA-modeled per-row cost per
        # snapshot generation; >=2x per-row regressions raise an advisory
        # cost-regression anomaly (the refresh swap is never blocked)
        self._cost_model = CostModel("native_frontend")
        self._running = False
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        # newest snapshot record — the slow lane registers verified-token
        # variants against it (GIL-atomic pointer read)
        self._cur_rec: Optional[_SnapRec] = None
        # duration/stage histogram drain cadence + accumulated stage counts
        self.hist_drain_s = 2.0
        self._last_hist_drain = 0.0
        self.stage_totals: Dict[str, Any] = {}
        # fe_stats() → Prometheus delta drain, owned by the periodic drain
        # thread (single owner: delta state is unsynchronized by design)
        self._stats_drain = metrics_mod.NativeStatsDrain()
        self._drain_wake = threading.Event()
        self._drain_lock = threading.Lock()
        # cached label children for the per-(pad,eff) warm-cache counters
        self._warm_children: Dict[Tuple[int, int, str], Any] = {}
        # live pre-warm/refresh helper threads (joined on stop); own lock —
        # trackers run both under _lock (refresh) and without it (notifier)
        self._prewarm_threads: List[threading.Thread] = []
        self._thread_lock = threading.Lock()
        # evaluators this instance registered _on_oidc_change on —
        # unregistered in stop() so a replaced frontend isn't kept alive
        # (and re-fired) by long-lived evaluators
        self._change_wired: set = set()
        # slow-lane responses buffer here; a dedicated completer thread
        # lands them in C++ in batches (per-response fe_complete_slow was
        # ~35µs of contended wall on the asyncio thread)
        from collections import deque as _deque

        self._done_buf = _deque()
        self._done_evt = threading.Event()
        # pipelined readback: dispatchers launch kernels WITHOUT blocking on
        # the device→host copy and park the in-flight batch here; a readback
        # thread completes each batch as its result arrives (is_ready), so
        # the in-flight window is bounded by the C++ slot count, not by how
        # many Python threads are captive in np.asarray
        self._rb_q = _deque()
        self._rb_evt = threading.Event()
        self._rb_lock = threading.Lock()
        self._rb_inflight = 0
        self.rb_inflight_peak = 0
        self._fe_stopped = False  # set just before fe_stop(): readback must
        # never complete a batch into the torn-down C++ server
        self._g_native_inflight = metrics_mod.inflight_batches.labels("native")
        # overload resilience (ISSUE 7): the C++ side already bounds its
        # queues (slots for the device lane, slow_cap for the slow lane);
        # the Python side adds (a) a CoDel admission state fed by the slow
        # lane's estimated queue wait, paced-rejecting slow requests typed
        # RESOURCE_EXHAUSTED while a standing queue persists, and (b)
        # host-lane brownout: with nearly every device slot in flight, a
        # small batch is answered by the SAME kernel on the CPU backend
        # (exact; docs/robustness.md "Overload & brownout")
        # the CoDel interval must exceed the wait-feed cadence (the drain
        # loop, hist_drain_s): with a shorter interval the idle-reset
        # would mistake the gap BETWEEN feeds for vanished load and flap
        # the OVERLOADED state under genuinely sustained saturation
        self.admission = AdmissionController(
            "native", target_s=admission_target_s,
            interval_s=max(1.0, 2 * self.hist_drain_s))
        self.brownout = bool(brownout)
        self.brownout_max_rows = max(1, int(brownout_max_rows))
        self._brownout_threshold = max(1, self.slots - 2)
        self._brownout_total = 0
        self._brownout_batches = 0
        # live brownout worker threads (under _rb_lock): stop()'s drain
        # must wait these out like in-flight device batches — a spill
        # mid-_host_eval completing into a torn-down C++ server would be
        # a native use-after-stop
        self._brownout_live = 0
        # lane selection (ISSUE 12, docs/performance.md "Lane selection"):
        # slot-level lane choice — a small gathered slot whose host-twin
        # cost beats the device round trip is answered on the CPU-backend
        # kernel even when the window is NOT saturated (brownout keeps its
        # distinct overload trigger and counters).  Speculative dual-
        # dispatch stays an engine-lane feature: a C++ slot completes via
        # fe_complete_batch exactly once, so racing two completions against
        # one slot has no safe first-wins seam here.
        self.lanes = LaneSelector(
            "native", enabled=lane_select,
            host_max_rows=min(int(lane_host_max_rows), self.max_batch),
            speculative=False, host_concurrency=2)
        # persistent workers for cost-model-selected host slots: this is
        # the LIGHT-LOAD latency path, so thread-per-slot churn (the
        # brownout pattern, fine under rare saturation spills) would eat
        # a measurable slice of the very p50 the lane buys down
        from concurrent.futures import ThreadPoolExecutor

        self._host_pool = ThreadPoolExecutor(
            max_workers=self.lanes.host_limit,
            thread_name_prefix="atpu-fe-lane-host")
        # slow-lane service-rate estimator state (owned by the drain loop)
        self._slow_last: Dict[str, float] = {"slow": 0.0, "t": 0.0}
        # decision observability (ISSUE 9): per-lane SLO burn-rate tracker
        # (--slo-ms; 0 = off — the native SLI is the batch's device round
        # trip, folded per batch) and the flight-recorder provider
        self.slo = None
        if slo_ms:
            from ..utils.slo import SloTracker

            self.slo = SloTracker("native", slo_ms)
        # tenant QoS (ISSUE 15): the native lane SHARES the engine's tenant
        # plane — the C++ gather owns its own slot cut (no Python-side
        # reorder seam), but every completed slot folds its tenant axis
        # (config_id rows) into the same per-tenant request/deny/SLO
        # counters the engine lane feeds (queue waits stay C++-clocked and
        # out of the per-tenant CoDel signal), so detection, weights and
        # the /debug/tenants view see one multi-lane truth; containment
        # ENFORCEMENT lands at the engine/slow-lane admission
        # (docs/tenancy.md names the fast-lane caveat).
        self.tenancy = getattr(engine, "tenancy", None)
        RECORDER.register_provider("native_frontend", self, "debug_vars")

    # ------------------------------------------------------------------
    def start(self) -> int:
        from ..native import load_library

        mod = load_library()
        if mod is None:
            raise RuntimeError("native library unavailable")
        self._mod = mod
        rc = mod.fe_start(self.port, self.max_batch, self.slots, self.window_us,
                          self.slow_cap, self._health_bytes(),
                          1 if self.bind_all else 0)
        if rc != 0:
            raise RuntimeError(f"native frontend failed to start (rc={rc}; "
                               "is libnghttp2 present?)")
        self._running = True
        self.bound_port = mod.fe_port()
        self._threads = [
            threading.Thread(target=self._dispatch_loop,
                             name=f"atpu-fe-dispatch-{i}", daemon=True)
            for i in range(self.dispatch_threads)
        ]
        self._threads.append(
            threading.Thread(target=self._readback_loop,
                             name="atpu-fe-readback", daemon=True))
        self._threads.append(
            threading.Thread(target=self._slow_loop, name="atpu-fe-slow", daemon=True))
        self._threads.append(
            threading.Thread(target=self._completer_loop,
                             name="atpu-fe-completer", daemon=True))
        self._threads.append(
            threading.Thread(target=self._metrics_drain_loop,
                             name="atpu-fe-metrics-drain", daemon=True))
        for t in self._threads:
            t.start()
        self.refresh()
        self.engine.add_swap_listener(self.refresh)
        return self.bound_port

    def stop(self, drain_s: float = 10.0) -> None:
        if self._mod is not None and self._running:
            # graceful: already-accepted slow-lane work flushes to the wire
            # while the listener is still alive — a cancelled handler would
            # leave its client hanging to the gRPC deadline.  Bounded:
            # steady incoming traffic degrades to the old abrupt stop.
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline:
                s = self._mod.fe_stats()
                if not s or (s.get("slow_pending", 0) == 0
                             and s.get("slow_queued", 0) == 0):
                    break
                time.sleep(0.05)
            # in-flight device batches AND live brownout spills must land
            # (fe_complete_batch) while the C++ server is still alive
            deadline = time.monotonic() + drain_s
            while ((self._rb_inflight or self._brownout_live)
                   and time.monotonic() < deadline):
                time.sleep(0.02)
        self._running = False
        self._rb_evt.set()
        if self._mod is not None:
            self.engine.remove_swap_listener(self.refresh)
        # unwire AFTER the swap listener is gone and under _lock, so a
        # concurrent refresh() can't re-register listeners mid-unwire
        with self._lock:
            for ev in self._change_wired:
                remove = getattr(ev, "remove_change_listener", None)
                if remove is not None:
                    remove(self._on_oidc_change)
            self._change_wired.clear()
        if self._mod is not None:
            try:
                self._fold_fc_counts()
                self.drain_histograms()  # final fold: short runs lose nothing
                self.drain_native_stats()
            except Exception:
                log.exception("final metric drain failed")
            self._fe_stopped = True
            self._mod.fe_stop()
        self._drain_wake.set()
        # host-lane pool: its tasks are counted in _brownout_live, which
        # the drain above already waited out — shutdown is bookkeeping
        try:
            self._host_pool.shutdown(wait=False)
        except Exception:
            pass
        for t in self._threads:
            t.join(timeout=5)
        # pre-warm compiles can't be interrupted mid-XLA; they bail between
        # variants (self._running) — wait them out so interpreter teardown
        # never force-unwinds a thread inside native code
        with self._thread_lock:
            helpers = list(self._prewarm_threads)
        for t in helpers:
            t.join(timeout=300)

    def stats(self) -> Dict[str, int]:
        """fe_stats() plus the Python-side verdict-cache counters.  The
        verdict cache's hit/miss/add traffic is FOLDED into the dyn_hit/
        dyn_miss/dyn_add keys (the credential cache's counters — one
        combined 'cached decision' story on /metrics), and additionally
        exported under its own vdict_* keys so the two caches stay
        distinguishable; the periodic drain turns every key into a
        labelled auth_server_native_frontend_events_total series."""
        s = dict(self._mod.fe_stats()) if self._mod else {}
        vc = self._verdict_cache
        if s and vc is not None:
            counts = vc.counts()
            s["dyn_hit"] = s.get("dyn_hit", 0) + counts["hits"]
            s["dyn_miss"] = s.get("dyn_miss", 0) + counts["misses"]
            s["dyn_add"] = s.get("dyn_add", 0) + counts["adds"]
            s["vdict_hit"] = counts["hits"]
            s["vdict_miss"] = counts["misses"]
            s["vdict_add"] = counts["adds"]
            s["vdict_evict"] = counts["evictions"]
        return s

    def drain_native_stats(self) -> None:
        """Fold the C++ fe_stats() counters into Prometheus as deltas
        (auth_server_native_frontend_events_total / _queue_depth).  Locked:
        the periodic drain thread, stop()'s final fold, and on-demand
        callers (bench, /debug scrapes) must not interleave delta reads."""
        with self._drain_lock:
            self._stats_drain.fold(self.stats())

    def _metrics_drain_loop(self) -> None:
        """Periodic telemetry drain: fe_stats() deltas → Prometheus on the
        histogram cadence, independent of traffic (the dispatch loop only
        drains when batch events wake it)."""
        while self._running:
            self._drain_wake.wait(self.hist_drain_s)
            if not self._running:
                return
            try:
                self.drain_native_stats()
                self._feed_admission()
            except Exception:
                log.exception("native stats drain failed")

    def _feed_admission(self) -> None:
        """Estimate the slow lane's standing queue wait from fe_stats()
        (Little's law: queued / observed completion rate) and feed it to
        the CoDel admission state.  The per-request waits live in C++; this
        coarse estimate on the drain cadence is what the Python side can
        see without putting itself back on the per-request path."""
        s = self.stats()
        if not s:
            return
        now = time.monotonic()
        last_t = self._slow_last["t"]
        done = float(s.get("slow", 0))
        queued = float(s.get("slow_queued", 0)) + float(s.get("slow_pending", 0))
        if last_t:
            dt = now - last_t
            delta = done - self._slow_last["slow"]
            if dt > 0 and delta >= 0:
                self.admission.observe_service(int(delta), now=now)
                rate = delta / dt
                est_wait = queued / max(rate, 1.0)
                self.admission.observe_waits((est_wait,), now=now)
        self._slow_last["slow"] = done
        self._slow_last["t"] = now

    def debug_vars(self) -> Dict[str, Any]:
        """JSON-safe live state for /debug/vars: raw fe_stats counters and
        backlog gauges, the serving snapshot id, its warmed jit grid, and
        the frontend's batching knobs."""
        rec = self._cur_rec
        out: Dict[str, Any] = {
            "running": self._running,
            "stats": {k: int(v) for k, v in self.stats().items()},
            "max_batch": self.max_batch,
            "window_us": self.window_us,
            "slots": self.slots,
            "dispatch_threads": self.dispatch_threads,
            "inflight_batches": self._rb_inflight,
            "inflight_peak": self.rb_inflight_peak,
            "trace_sample_n": self.trace_sample_n,
            "batch_dedup": self.batch_dedup,
            "strict_verify": self.strict_verify,
            "verdict_cache": (self._verdict_cache.counts()
                              if self._verdict_cache is not None else None),
            "breaker": self.breaker.to_json(),
            "device_timeout_s": self.device_timeout_s,
            "admission": self.admission.to_json(),
            "brownout": {
                "enabled": self.brownout,
                "max_rows": self.brownout_max_rows,
                "slot_threshold": self._brownout_threshold,
                "decisions": self._brownout_total,
                "batches": self._brownout_batches,
            },
            # lane selection (ISSUE 12): slot-level cost-model decisions,
            # rows served per lane, cost EWMAs, warmed host shapes
            # tuple() first: the pre-warm thread and host-eval workers
            # add() concurrently — iterating the live set can raise
            "lane_select": dict(
                self.lanes.to_json(),
                host_warm_shapes=(sorted(list(s)
                                         for s in tuple(rec.host_warm))
                                  if rec is not None else [])),
            "provenance": {
                "heat": (rec.heat.to_json()
                         if rec is not None and rec.heat is not None
                         else None),
            },
            # tenant QoS (ISSUE 15): the shared plane's view — the native
            # lane feeds the same per-tenant folds the engine lane reads
            "tenancy": (self.tenancy.to_json()
                        if self.tenancy is not None else None),
            "slo": self.slo.to_json() if self.slo is not None else None,
            # change-safety mirror (ISSUE 10): the native lane holds the
            # baseline through a canary window (refresh fires on
            # promotion/rollback only) — operators reading this lane's
            # vars see the same canary/quarantine state the engine owns
            "change_safety": (self.engine.change_safety_vars()
                              if hasattr(self.engine, "change_safety_vars")
                              else None),
            # kernel cost observatory (ISSUE 16): the process-wide ledger
            # plus this lane's modeled-cost lineage and the jit entry
            # points the serving snapshot can dispatch through
            "kernel_cost": {
                "ledger": LEDGER.to_json(),
                "modeled": self._cost_model.to_json(),
                "entry_points": kernel_cost_mod.entry_points(
                    policy=rec.policy if rec is not None else None,
                    sharded=rec.sharded if rec is not None else None),
            },
            "snapshot": None,
        }
        if rec is not None:
            out["snapshot"] = {
                "snap_id": rec.snap_id,
                "warm": sorted([list(pe) for pe in rec.warm]),
                "warm_done": rec.warm_done.is_set(),
                "fast_configs": len(rec.row_labels),
                "hybrid_configs": len(rec.hybrid_rows),
                "dyn_registrations": len(rec.dyn_regs),
            }
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def _health_bytes() -> bytes:
        from .. import protos

        return protos.health_pb2.HealthCheckResponse(
            status=protos.health_pb2.HealthCheckResponse.SERVING
        ).SerializeToString()

    @staticmethod
    def _result_bytes(result: AuthResult) -> bytes:
        from ..service.grpc_server import check_response_from_result

        return check_response_from_result(result).SerializeToString()

    @staticmethod
    def _static_deny(code: int, message: str, headers: List[Dict[str, str]],
                     deny: Optional[DenyWithValues],
                     doc: Optional[Dict[str, Any]] = None) -> AuthResult:
        """Constant mirror of pipeline._customize_deny_with
        (ref pkg/service/auth_pipeline.go:581-608): the denyWith values are
        pre-checked constant for ``doc`` (static, or auth-only against a
        const identity doc; the default empty doc serves identity-failure
        templates, where auth-only selectors are constantly missing)."""
        from ..authjson.value import stringify_json

        doc = doc or {}
        result = AuthResult(code=code, message=message, headers=headers)
        if deny is not None:
            if deny.code:
                result.status = deny.code
            if deny.message is not None:
                result.message = stringify_json(deny.message.resolve_for(doc))
            if deny.body is not None:
                result.body = stringify_json(deny.body.resolve_for(doc))
            if deny.headers:
                result.headers = [
                    {h.name: stringify_json(h.value.resolve_for(doc))}
                    for h in deny.headers
                ]
        return result

    def _deny_result(self, rt: RuntimeAuthConfig,
                     identity_obj: Any = None) -> AuthResult:
        """Authorization-failure template, optionally resolved against a
        constant identity (ref pkg/service/auth_pipeline.go:478-481)."""
        return self._static_deny(
            PERMISSION_DENIED, "Unauthorized", [], rt.deny_with.unauthorized,
            doc=_const_doc(identity_obj) if identity_obj is not None else None)

    def _unauth_result(self, rt: RuntimeAuthConfig, message: str) -> AuthResult:
        """Identity-failure template: UNAUTHENTICATED + WWW-Authenticate
        challenges + static denyWith.unauthenticated
        (ref pkg/service/auth_pipeline.go:468-472)."""
        return self._static_deny(
            UNAUTHENTICATED, message, rt.challenge_headers(),
            rt.deny_with.unauthenticated)

    def _ok_bytes_for(self, rt: RuntimeAuthConfig, identity_obj) -> bytes:
        """Success CheckResponse bytes for a CONSTANT identity outcome:
        response evaluators resolved bucket by bucket against the const
        doc — mirrors pipeline._evaluate_response (per-bucket _sync_auth:
        later buckets see earlier outputs under auth.response.*) +
        wrap_responses + the success assembly in _evaluate_phases
        (ref pkg/service/auth_pipeline.go:487-491)."""
        from ..evaluators.base import wrap_responses
        from ..evaluators.response import DynamicJSON

        doc = _const_doc(identity_obj)
        results: Dict[Any, Any] = {}
        grouped: Dict[int, list] = {}
        for c in rt.response:
            grouped.setdefault(c.priority, []).append(c)
        for bucket in (grouped[p] for p in sorted(grouped)):
            for conf in bucket:
                ev = conf.evaluator
                if isinstance(ev, DynamicJSON):
                    results[conf] = {p.name: p.value.resolve_for(doc)
                                     for p in ev.properties}
                else:
                    results[conf] = ev.value.resolve_for(doc)
            doc["auth"]["response"] = {c.name: o for c, o in results.items()}
        headers, metadata = wrap_responses(results)
        return self._result_bytes(
            AuthResult(code=OK, headers=[headers], metadata=metadata))

    def _unauth_templates(self, rt: RuntimeAuthConfig,
                          sources: List[SourceSpec]) -> List[bytes]:
        """All-sources-failed CheckResponse templates, indexed by the
        bitmask of which STATIC sources' credentials were present (present
        ⇒ key unknown; absent ⇒ missing; dyn sources hitting this path are
        always missing — extractable dyn credentials go to the slow lane).
        Byte-exact with the pipeline: one source returns its bare error,
        several return the sorted JSON error dict
        (pipeline._evaluate_identity, ref auth_pipeline.go:203-258)."""
        if not sources:
            return []
        import json as _json

        statics = [s for s in sources if not s.dyn]
        out: List[bytes] = []
        for mask in range(1 << len(statics)):
            if len(sources) == 1:
                s = sources[0]
                msg = s.invalid_msg if (not s.dyn and mask & 1) else s.missing_msg
            else:
                errors: Dict[str, str] = {}
                si = 0
                for s in sources:
                    if s.dyn:
                        errors[s.name] = s.missing_msg
                    else:
                        errors[s.name] = (s.invalid_msg if (mask >> si) & 1
                                          else s.missing_msg)
                        si += 1
                msg = _json.dumps(errors, separators=(",", ":"), sort_keys=True)
            out.append(self._result_bytes(self._unauth_result(rt, msg)))
        return out

    # ---- jit pre-warm (compiles must never land on live requests) ----

    def _bucket_grid(self, rec: _SnapRec) -> List[Tuple[int, int]]:
        """Every (batch_pad, byte_eff) jit variant _dispatch can produce,
        largest first (the largest combo is the universal round-up target)."""
        pads: List[int] = []
        p = min(bucket_pow2(self.max_batch), self.max_batch)
        while p >= 16:
            pads.append(p)
            p //= 2
        if not pads:  # max_batch < 16: one pad, or refresh would warm nothing
            pads.append(min(bucket_pow2(self.max_batch), self.max_batch))
        if rec.sharded is not None:
            has_dfa = rec.sharded.has_dfa
        else:
            has_dfa = rec.params is not None and rec.params["dfa_tables"] is not None
        effs: List[int] = [0]
        if has_dfa:
            effs = []
            e = 16
            while e < DFA_VALUE_BYTES:
                effs.append(e)
                e *= 2
            effs.append(DFA_VALUE_BYTES)
            effs.reverse()
        return [(p, e) for p in pads for e in effs]

    def _warm_one(self, rec: _SnapRec, pad: int, eff: int) -> None:
        """Compile (and cache) the jit variant for one bucket shape using
        throwaway zero operands."""
        import jax
        import jax.numpy as jnp

        from ..ops.pattern_eval import eval_bitpacked_jit

        if rec.sharded is not None:
            sh = rec.sharded
            p0 = sh.shards[0]
            S, A, M, K = sh.n_shards, p0.n_attrs, p0.n_member_attrs, p0.members_k
            C, NB = p0.n_cpu_leaves, max(p0.n_byte_attrs, 1)
            with sh.state.launch_lock:  # psum enqueue-order consistency
                out = sh._step(
                    sh.params,
                    jnp.asarray(np.zeros((pad, S, A), dtype=np.int32)),
                    jnp.asarray(np.full((pad, S, M, K), PAD, dtype=np.int32)),
                    jnp.asarray(np.zeros((pad, S, C), dtype=bool)),
                    jnp.asarray(np.zeros((pad, S, NB, eff), dtype=np.uint8))
                    if eff else None,
                    jnp.asarray(np.zeros((pad, S, NB), dtype=bool))
                    if eff else None,
                    jnp.asarray(np.zeros((pad,), dtype=np.int32)),
                    jnp.asarray(np.zeros((pad,), dtype=np.int32)),
                )
            jax.block_until_ready(out)
            rec.warm.add((pad, eff))
            return
        policy = rec.policy
        dt = wire_dtype(policy)
        A, M, K = policy.n_attrs, policy.n_member_attrs, policy.members_k
        C, NB = policy.n_cpu_leaves, max(policy.n_byte_attrs, 1)
        out = eval_bitpacked_jit(
            rec.params,
            jnp.asarray(np.zeros((pad, A), dtype=dt)),
            jnp.asarray(np.full((pad, M, K), PAD, dtype=dt)),
            jnp.asarray(np.zeros((pad, C), dtype=bool)),
            jnp.asarray(np.zeros((pad,), dtype=np.int32)),
            jnp.asarray(np.zeros((pad, NB, eff), dtype=np.uint8)) if eff else None,
            jnp.asarray(np.zeros((pad, NB), dtype=bool)) if eff else None,
        )
        jax.block_until_ready(out)
        # fused mega-kernel entry (ISSUE 17): the bitpacked warm above
        # compiles the routed compute, but the serving dispatch enters
        # through the one-launch per-operand fused entry — warm that
        # executable too so the first post-swap batch pays no Pallas
        # lowering (same (pad, eff) bucket, same operand signature as
        # _dispatch's fused branch)
        if rec.params is not None and rec.params.get("fused") is not None:
            from ..ops import fused_kernel as fused_mod

            out = fused_mod._fused_ops_jit(
                rec.params,
                jnp.asarray(np.zeros((pad, A), dtype=dt)),
                jnp.asarray(np.full((pad, M, K), PAD, dtype=dt)),
                jnp.asarray(np.zeros((pad, C), dtype=bool)),
                jnp.asarray(np.zeros((pad,), dtype=np.int32)),
                jnp.asarray(np.zeros((pad, NB, eff), dtype=np.uint8))
                if eff else None,
                jnp.asarray(np.zeros((pad, NB), dtype=bool))
                if eff else None,
                None, None, None, None,
                use_pallas=fused_mod.fused_kernel_supported(),
            )
            jax.block_until_ready(out)
        rec.warm.add((pad, eff))

    def _prewarm_rest(self, rec: _SnapRec, grid: List[Tuple[int, int]]) -> None:
        try:
            # host-lane jit first (ISSUE 12 satellite): with lane selection
            # on, the very next light-load slot after this swap will ride
            # the CPU-backend twin — its small pad shapes must be warm
            # before the long tail of device variants compiles (the same
            # latency-spike class as the brownout worker-thread fix)
            if self.lanes.enabled:
                try:
                    self._warm_host(rec)
                except Exception:
                    log.exception("host-lane jit pre-warm failed")
            for pad, eff in grid:
                # bail once superseded: a draining snapshot never sees new
                # shapes, and its compiles would contend with the successor's
                # swap-gate compile for the single core
                if (not self._running or rec.snap_id not in self._snaps
                        or rec.snap_id != self._next_snap_id - 1):
                    return
                if (pad, eff) in rec.warm:
                    continue
                self._warm_one(rec, pad, eff)
        except Exception:
            log.exception("jit pre-warm failed")
        finally:
            rec.warm_done.set()

    def _warm_host(self, rec: _SnapRec) -> None:
        """Compile the CPU-backend (host-lane) jit variants for the common
        SMALL pad shapes — the shapes cost-model-selected slots and the
        degrade path actually produce under light load.  Large pads stay
        cold on purpose: the cost model never routes a large cut host-side
        (R_BATCH), so warming them would burn reconcile-time CPU for
        shapes that only the saturated-brownout edge could ever hit."""
        if rec.sharded is not None or rec.policy is None:
            return
        has_dfa = rec.params is not None and rec.params["dfa_tables"] is not None
        effs = [DFA_VALUE_BYTES] if has_dfa else [0]
        for pad in (16, 32):
            if pad > self.max_batch:
                break
            for eff in effs:
                if (not self._running or rec.snap_id not in self._snaps
                        or rec.snap_id != self._next_snap_id - 1):
                    return
                if (pad, eff) not in rec.host_warm:
                    self._warm_host_one(rec, pad, eff)

    def _warm_host_one(self, rec: _SnapRec, pad: int, eff: int) -> None:
        """Compile (and cache) the CPU-backend jit variant for one bucket
        shape using throwaway zero operands — the host-lane mirror of
        _warm_one.  Also builds rec.host_params eagerly, so the first real
        host-lane slot pays neither the operand-pytree build nor the XLA
        compile."""
        import jax
        import jax.numpy as jnp

        from ..ops.pattern_eval import eval_bitpacked_jit, to_device

        if rec.host_params is None:
            rec.host_params = to_device(rec.policy, host=True)
        policy = rec.policy
        dt = wire_dtype(policy)
        A, M, K = policy.n_attrs, policy.n_member_attrs, policy.members_k
        C, NB = policy.n_cpu_leaves, max(policy.n_byte_attrs, 1)
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            out = eval_bitpacked_jit(
                rec.host_params,
                jnp.asarray(np.zeros((pad, A), dtype=dt)),
                jnp.asarray(np.full((pad, M, K), PAD, dtype=dt)),
                jnp.asarray(np.zeros((pad, C), dtype=bool)),
                jnp.asarray(np.zeros((pad,), dtype=np.int32)),
                jnp.asarray(np.zeros((pad, NB, eff), dtype=np.uint8))
                if eff else None,
                jnp.asarray(np.zeros((pad, NB), dtype=bool)) if eff else None,
            )
            jax.block_until_ready(out)
        rec.host_warm.add((pad, eff))

    def _pick_warm_shape(self, rec: _SnapRec, count: int, eff: int) -> Tuple[int, int]:
        """Smallest warmed (pad ≥ count, eff' ≥ eff); falls back to the
        exact bucket shape (inline compile) only when nothing fits — i.e.
        cold start before the first variant finished compiling.  Each
        consultation is counted per served (pad, eff) variant: hit = exact
        shape warm, rounded = a larger warm shape absorbed the batch,
        miss = inline XLA compile on a live batch."""
        pad = min(bucket_pow2(count), self.max_batch)
        if (pad, eff) in rec.warm:
            self._count_warm(pad, eff, "hit")
            return pad, eff
        best: Optional[Tuple[int, int]] = None
        for p, e in tuple(rec.warm):  # snapshot: the prewarm thread appends
            if p >= count and e >= eff and (best is None or (p, e) < best):
                best = (p, e)
        if best is not None:
            self._count_warm(best[0], best[1], "rounded")
            return best
        self._count_warm(pad, eff, "miss")
        return pad, eff

    def _count_warm(self, pad: int, eff: int, outcome: str) -> None:
        ch = self._warm_children.get((pad, eff, outcome))
        if ch is None:
            ch = self._warm_children[(pad, eff, outcome)] = (
                metrics_mod.jit_warm_cache.labels(str(pad), str(eff), outcome))
        ch.inc()

    def wait_warm(self, timeout_s: float = 600.0) -> bool:
        """Block until every jit bucket variant of the newest snapshot is
        compiled (bench/CLI call this after start() so no XLA compile lands
        on live traffic)."""
        with self._lock:
            rec = self._snaps.get(self._next_snap_id - 1)
        if rec is None:
            return True
        return rec.warm_done.wait(timeout_s)

    # ------------------------------------------------------------------
    def refresh(self) -> None:
        """Rebuild the C++ snapshot from the engine's current one (called
        after every engine.apply_snapshot — the reconcile-time swap).
        Serialized end-to-end under _lock: concurrent reconciles must not
        mint duplicate ids OR install their C++ snapshots out of order
        (fe_swap sets the serving snapshot unconditionally — a late older
        swap would leave a stale corpus serving).

        Change safety (ISSUE 10): during an engine canary window the swap
        listeners do not fire, and ``engine._snapshot`` IS the baseline —
        so this lane holds the previous generation until promotion (the
        C++ batcher gathers per-snapshot and cannot split one gathered
        batch across two compiled corpora; its canary evidence instead
        feeds the guard's baseline cohort via canary_observe_external).
        Promotion and rollback both fire the listeners, converging this
        lane in one atomic fe_swap."""
        with self._lock:
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        # a refresh already blocked on _lock when stop() ran would re-wire
        # change listeners (re-leaking this instance) and fe_swap onto a
        # torn-down module — the lock alone doesn't order it before stop()'s
        # unwire, so bail once stopped (start() sets _running before the
        # first refresh)
        if not self._running:
            return
        engine = self.engine
        snap = engine._snapshot
        policy = snap.policy if snap is not None else None
        sharded = snap.sharded if snap is not None else None
        mod = self._mod

        # ISSUE 14 lanes: the C++ encoder/kernel predate the numeric and
        # relation operands and the overflow assist — a corpus using them
        # must NOT become a C++ fast-lane snapshot (it would silently
        # mis-evaluate the new leaves).  The Python engine lane serves it
        # exactly; the native port is tracked work (docs/architecture.md).
        def _uses_new_lanes(p) -> bool:
            return (int(getattr(p, "n_num_attrs", 0) or 0) > 0
                    or int(getattr(p, "n_rel_slots", 0) or 0) > 0
                    or bool(getattr(p, "ovf_assist", False)))

        pols = ([policy] if policy is not None else
                list(getattr(sharded, "shards", None) or ()))
        if any(_uses_new_lanes(p) for p in pols):
            log.warning(
                "native fast lane DISABLED for this snapshot: the corpus "
                "uses numeric/relation/ovf-assist lanes the C++ encoder "
                "does not implement yet — the engine lane serves it")
            policy = None
            sharded = None

        if self.strict_verify and snap is not None and (
                policy is not None or sharded is not None) and not getattr(
                snap, "lint_ok", False):
            # lint_ok marks snapshots the engine's own strict-verify already
            # vetted at compile time: re-linting here (under _lock, per
            # refresh) would rebuild both lanes' operand pytrees for zero
            # added protection.  This path fires only when the frontend is
            # strict but the engine is not.
            from ..analysis.tensor_lint import lint_snapshot
            from ..analysis.translation_validate import (
                certify_snapshot,
                snapshot_policies,
            )

            findings = lint_snapshot(snap)
            if not findings:
                # lint-clean: certify the compiled artifacts decide like
                # the host oracle (same gate the engine's strict path
                # runs; the fingerprint cache makes repeats free)
                for pol in snapshot_policies(snap):
                    _, fails, _ = certify_snapshot(pol)
                    findings += fails
            if findings:
                # no snap_id minted, no fe_swap: the previous C++ snapshot
                # (and its credential variants) keeps serving untouched
                metrics_mod.snapshot_rejected.labels("native_frontend").inc()
                log.error(
                    "native snapshot REJECTED by tensor lint/translation "
                    "validation (snapshot %d keeps serving): %s",
                    self._cur_rec.snap_id if self._cur_rec else 0,
                    "; ".join(str(f) for f in findings[:5]))
                return

        snap_id = self._next_snap_id
        self._next_snap_id += 1

        spec: Dict[str, Any] = {
            "snap_id": snap_id,
            "policy": None,
            "A": 0, "M": 0, "K": 0, "C": 0, "NB": 0, "DVB": DFA_VALUE_BYTES,
            "elem16": 0,
            "fcs": [], "hosts": [], "slots": [],
            "attr_dfas": [],
            "dfa_R": 0, "dfa_S": 0,
            "invalid": self._result_bytes(
                AuthResult(code=INVALID_ARGUMENT, message="Invalid request")),
            "notfound": self._result_bytes(
                AuthResult(code=NOT_FOUND, message="Service not found")),
            "health": self._health_bytes(),
        }
        rec = _SnapRec(snap_id=snap_id, policy=policy, params=None, encoder=None)
        # attribution (ISSUE 9): reuse the engine snapshot's heat map when
        # it exists (same policy object → same rows), else build one
        try:
            rec.heat = getattr(snap, "heat", None) if snap is not None \
                else None
            if rec.heat is None:
                rec.heat = prov_mod.HeatMap.for_snapshot(policy, sharded)
        except Exception:
            log.exception("native heat-map build failed (refresh unaffected)")
            rec.heat = None

        entries = list(snap.by_id.values()) if snap is not None else []
        fcs: List[dict] = []
        # exact hosts AND "*.suffix" wildcard keys — the C++ side replicates
        # the index's wildcard walk-up, so misses resolve to NOT_FOUND
        # natively (ref pkg/index/index.go:153-174)
        hosts: List[Tuple[str, int]] = []
        ok_bytes = self._result_bytes(AuthResult(code=OK, headers=[{}]))

        # active span export needs a per-request Python span (W3C inject into
        # outbound calls + Check span export, ref pkg/trace/trace.go:20-27);
        # the fast lane never touches Python per request, so with tracing on
        # it head-samples: every Nth request takes the slow lane with full
        # spans, the rest stay native (counted in stats trace_sampled —
        # enabling observability must not cost ~8x throughput wholesale)
        spec["trace_every"] = (self.trace_sample_n
                               if tracing_mod.tracing_active() else 0)
        if spec["trace_every"] > 1 and not self._trace_mode_logged:
            self._trace_mode_logged = True
            log.info(
                "tracing active: head-sampling 1-in-%d requests to the slow "
                "lane for span export (the rest serve natively, untraced); "
                "set AUTHORINO_TPU_TRACE_ALL=1 for every-request tracing",
                spec["trace_every"])

        enc = None
        if policy is not None:
            from ..native.encoder import get_native_encoder
            from ..ops.pattern_eval import to_device

            enc = get_native_encoder(policy)
            if enc is not None:
                rec.encoder = enc
                rec.params = (snap.params if snap.params is not None
                              else to_device(policy, lane=self.kernel_lane))
                spec["policy"] = enc._handle
                dt = wire_dtype(policy)
                A, M, K = policy.n_attrs, policy.n_member_attrs, policy.members_k
                C, NB = policy.n_cpu_leaves, max(policy.n_byte_attrs, 1)
                spec.update(A=A, M=M, K=K, C=C, NB=NB,
                            elem16=1 if dt == np.int16 else 0)
                ams = np.ascontiguousarray(policy.member_attr_slot, dtype=np.int32)
                abs_v = np.ascontiguousarray(policy.attr_byte_slot, dtype=np.int32)
                rec.keepalive += [ams, abs_v]
                spec["attr_member_slot_addr"] = ams.ctypes.data
                spec["attr_byte_slot_addr"] = abs_v.ctypes.data
                rec.cacheable = policy.config_cacheable
                rec.cache_tokens = getattr(snap, "cache_tokens", None)
                if policy.n_byte_attrs > 0 and policy.dfa_tables.size:
                    # C++ indexes transition tables BY ROW: expand the
                    # compiler's deduped [T, S, 256] store through
                    # dfa_table_of_row for the native encoder
                    dt_tr = np.ascontiguousarray(policy.dfa_tables_by_row,
                                                 dtype=np.uint8)
                    dt_ac = np.ascontiguousarray(policy.dfa_accept_by_row,
                                                 dtype=np.uint8)
                    rec.keepalive += [dt_tr, dt_ac]
                    spec.update(dfa_R=int(dt_tr.shape[0]), dfa_S=int(dt_tr.shape[1]),
                                dfa_trans_addr=dt_tr.ctypes.data,
                                dfa_accept_addr=dt_ac.ctypes.data)
                # per-attr DFA leaves → (dfa row, dense cpu column)
                cpu_col = {int(l): i for i, l in enumerate(policy.cpu_leaf_list)}
                attr_dfas: List[List[Tuple[int, int]]] = [[] for _ in range(A)]
                for leaf in range(policy.n_leaves):
                    if int(policy.leaf_op[leaf]) == OP_REGEX_DFA and leaf in cpu_col:
                        attr_dfas[int(policy.leaf_attr[leaf])].append(
                            (int(policy.leaf_dfa_row[leaf]), cpu_col[leaf]))
                spec["attr_dfas"] = attr_dfas

                # batch slots (numpy-owned; freed on SNAP_RETIRED)
                B = self.max_batch
                for _ in range(self.slots):
                    a = {
                        "attrs_val": np.zeros((B, A), dtype=dt),
                        "members": np.full((B, M, K), PAD, dtype=dt),
                        "cpu_dense": np.zeros((B, C), dtype=np.uint8),
                        "config_id": np.zeros((B,), dtype=np.int32),
                        "attr_bytes": np.zeros((B, NB, DFA_VALUE_BYTES), dtype=np.uint8),
                        "byte_ovf": np.zeros((B, NB), dtype=np.uint8),
                    }
                    rec.arrays.append(a)
                    spec["slots"].append({k: v.ctypes.data for k, v in a.items()})

            else:
                policy = None  # no native encoder → kernel fast lane off
        elif sharded is not None:
            # mesh-sharded corpus: the shards share ONE interner and
            # ShapeTargets-unified operand shapes, so the C++ encoder writes
            # each request into its owning shard's [B, S, ...] slice and the
            # dispatcher feeds the shard_map step directly — multi-device
            # scaling and the native frontend compose (VERDICT r3 missing #2;
            # the reference's sharding composes with its full server,
            # ref controllers/label_selector.go:14-45)
            from ..native.encoder import get_native_encoder

            enc = get_native_encoder(sharded.shards[0])
            if enc is not None:
                rec.encoder = enc
                rec.sharded = sharded
                spec["policy"] = enc._handle
                p0 = sharded.shards[0]
                S_sh = sharded.n_shards
                A, M, K = p0.n_attrs, p0.n_member_attrs, p0.members_k
                C, NB = p0.n_cpu_leaves, max(p0.n_byte_attrs, 1)
                # the sharded step takes int32 operands (parallel/sharded_eval
                # encode contract), so elem16 stays off
                spec.update(A=A, M=M, K=K, C=C, NB=NB, S=S_sh, elem16=0)
                ams = np.ascontiguousarray(
                    np.stack([p.member_attr_slot for p in sharded.shards]),
                    dtype=np.int32)
                abs_v = np.ascontiguousarray(
                    np.stack([p.attr_byte_slot for p in sharded.shards]),
                    dtype=np.int32)
                rec.keepalive += [ams, abs_v]
                spec["attr_member_slot_addr"] = ams.ctypes.data
                spec["attr_byte_slot_addr"] = abs_v.ctypes.data
                # per-shard DFA tables stack on the row axis (targets unify
                # R and the state count); attr_dfas rows are globalized
                rec.cacheable = np.stack(
                    [p.config_cacheable for p in sharded.shards])
                attr_dfas: List[List[Tuple[int, int]]] = [
                    [] for _ in range(S_sh * A)]
                if p0.n_byte_attrs > 0 and p0.dfa_tables.size:
                    # per-row expansion of the deduped table store, stacked
                    # on the (shard-globalized) row axis for C++
                    R = int(p0.dfa_table_of_row.shape[0])
                    dt_tr = np.ascontiguousarray(
                        np.concatenate([p.dfa_tables_by_row
                                        for p in sharded.shards]),
                        dtype=np.uint8)
                    dt_ac = np.ascontiguousarray(
                        np.concatenate([p.dfa_accept_by_row
                                        for p in sharded.shards]),
                        dtype=np.uint8)
                    rec.keepalive += [dt_tr, dt_ac]
                    spec.update(dfa_R=int(dt_tr.shape[0]),
                                dfa_S=int(dt_tr.shape[1]),
                                dfa_trans_addr=dt_tr.ctypes.data,
                                dfa_accept_addr=dt_ac.ctypes.data)
                    for s, p in enumerate(sharded.shards):
                        cpu_col = {int(l): i
                                   for i, l in enumerate(p.cpu_leaf_list)}
                        for leaf in range(p.n_leaves):
                            if (int(p.leaf_op[leaf]) == OP_REGEX_DFA
                                    and leaf in cpu_col):
                                attr_dfas[s * A + int(p.leaf_attr[leaf])].append(
                                    (s * R + int(p.leaf_dfa_row[leaf]),
                                     cpu_col[leaf]))
                spec["attr_dfas"] = attr_dfas

                B = self.max_batch
                for _ in range(self.slots):
                    a = {
                        "attrs_val": np.zeros((B, S_sh, A), dtype=np.int32),
                        "members": np.full((B, S_sh, M, K), PAD, dtype=np.int32),
                        "cpu_dense": np.zeros((B, S_sh, C), dtype=np.uint8),
                        "config_id": np.zeros((B,), dtype=np.int32),
                        "shard_of": np.zeros((B,), dtype=np.int32),
                        "attr_bytes": np.zeros((B, S_sh, NB, DFA_VALUE_BYTES),
                                               dtype=np.uint8),
                        "byte_ovf": np.zeros((B, S_sh, NB), dtype=np.uint8),
                    }
                    rec.arrays.append(a)
                    spec["slots"].append({k: v.ctypes.data for k, v in a.items()})
            else:
                sharded = None  # no native encoder → kernel fast lane off

        fast_ids = set()
        fc_rows: List[int] = []
        for entry in entries:
            # each entry is judged against its OWN compile: the single
            # corpus, or its owning shard's sub-corpus on a mesh
            policy_for = policy
            if sharded is not None:
                policy_for = None
                if entry.rules is not None:
                    loc = sharded.locator.get(entry.rules.name)
                    if loc is not None:
                        policy_for = sharded.shards[loc[0]]
            spec_fl = fast_lane_eligible(entry, policy_for)
            if spec_fl is None:
                continue
            fast_ids.add(id(entry))
            fc_idx = len(fcs)
            # per-authconfig metric labels — EXACTLY the pipeline's
            # scheme (ref pkg/service/auth_pipeline.go:26-36; translate
            # injects namespace/name into runtime labels), so a
            # config's fast- and slow-lane traffic lands on one series
            lbl = entry.runtime.labels or {}
            ns_l, nm_l = lbl.get("namespace", ""), lbl.get("name", "")
            rt_e = entry.runtime
            # response/denyWith-template configs: OK and DENY bytes are per
            # identity outcome (anonymous at swap; per-key at swap; per-
            # credential at dyn registration) — empty bytes in a variant =
            # the config default
            fc_ok = (self._ok_bytes_for(rt_e, spec_fl.const_identity)
                     if rt_e.response and not spec_fl.sources
                     and not spec_fl.hybrid else ok_bytes)
            fc_deny = self._result_bytes(self._deny_result(
                rt_e,
                spec_fl.const_identity
                if spec_fl.deny_templated and not spec_fl.sources else None))
            fc = {
                "row": 0,
                "has_batch": 1 if spec_fl.has_batch else 0,
                "hybrid": 1 if spec_fl.hybrid else 0,
                "ok": fc_ok,
                "deny": fc_deny,
                "plans": spec_fl.plans,
                "sources": [
                    {
                        "cred_kind": s.cred_kind,
                        "cred_key": s.cred_key,
                        "dyn": 1 if s.dyn else 0,
                        "variants": [
                            (key, vplans,
                             self._ok_bytes_for(rt_e, ident_obj)
                             if ident_obj is not None and rt_e.response
                             and not spec_fl.hybrid
                             else b"",
                             self._result_bytes(
                                 self._deny_result(rt_e, ident_obj))
                             if ident_obj is not None
                             and spec_fl.deny_templated else b"")
                            for key, vplans, ident_obj in s.variants
                        ],
                    }
                    for s in spec_fl.sources
                ],
                "unauth_msgs": self._unauth_templates(rt_e, spec_fl.sources),
                "ns": ns_l,
                "name": nm_l,
            }
            dyn_map = {id(s.idc): (i, s.ttl_cap)
                       for i, s in enumerate(spec_fl.sources) if s.dyn}
            if dyn_map:
                rec.dyn_regs[entry.id] = (fc_idx, spec_fl.auth_attrs,
                                          policy_for, dyn_map,
                                          spec_fl.hybrid)
                # a JWKS rotation invalidates every cached token: swap
                # in a fresh snapshot (empty variant map) when the
                # provider's key set actually changes (add_change_listener
                # dedups, so re-wiring on every refresh is safe — and a
                # reconcile-minted evaluator gets wired the first time)
                for s in spec_fl.sources:
                    if not s.dyn:
                        continue
                    add_listener = getattr(s.idc.evaluator,
                                           "add_change_listener", None)
                    if add_listener is not None:
                        add_listener(self._on_oidc_change)
                        # unregistered in stop(): evaluators outlive
                        # frontend instances (reconcile re-creates the
                        # frontend, not the evaluator graph)
                        self._change_wired.add(s.idc.evaluator)
            if spec_fl.has_batch:
                if sharded is not None:
                    shard, row = sharded.locator[entry.rules.name]
                    fc["row"], fc["shard"] = int(row), int(shard)
                    row_key: Any = (int(shard), int(row))
                else:
                    row = policy.config_ids[entry.rules.name]
                    fc["row"] = int(row)
                    fc_rows.append(int(row))
                    row_key = int(row)
                rec.row_labels[row_key] = (ns_l, nm_l)
                if spec_fl.hybrid:
                    rec.hybrid_rows.add(row_key)
            fcs.append(fc)
            for host in entry.hosts:
                hosts.append((host, fc_idx))
        rec.fc_rows = np.asarray(fc_rows or [0], dtype=np.int64)

        # non-fast hosts route to the Python pipeline (slow lane)
        fast_hosts = {h for h, _ in hosts}
        for entry in entries:
            if id(entry) in fast_ids:
                continue
            for host in entry.hosts:
                if host not in fast_hosts:
                    hosts.append((host, -1))
        spec["fcs"] = fcs
        spec["hosts"] = hosts

        self._snaps[snap_id] = rec  # caller holds _lock
        self._cur_rec = rec
        grid: List[Tuple[int, int]] = []
        if (rec.params is not None or rec.sharded is not None) and rec.arrays:
            grid = self._bucket_grid(rec)
            try:
                # the largest combo compiles BEFORE the swap goes live: the
                # previous snapshot keeps serving meanwhile, and once this
                # one is current every batch shape can round up to it
                self._warm_one(rec, *grid[0])
            except Exception:
                log.exception("jit pre-warm (swap gate) failed")
        mod.fe_swap(spec)
        metrics_mod.snapshot_generation.labels("native_frontend").set(snap_id)
        try:
            # kernel-cost analysis (ISSUE 16) — advisory, after the swap is
            # live; the process-wide shape memo makes engine/native overlap
            # for the same snapshot essentially free
            self._cost_model.analyze(snap_id, policy=rec.policy,
                                     params=rec.params, sharded=rec.sharded,
                                     recorder=RECORDER)
        except Exception:
            log.exception("kernel cost analysis failed (swap unaffected)")
        if grid:
            # NON-daemon and tracked: a daemon thread mid-XLA-compile at
            # interpreter exit force-unwinds through native code and aborts
            # the process ("FATAL: exception not rethrown"); stop() joins
            # these, and _prewarm_rest bails between variants once stopped
            self._track_thread(threading.Thread(
                target=self._prewarm_rest, args=(rec, grid),
                name="atpu-fe-prewarm"))
        else:
            rec.warm_done.set()
        log.info("native frontend snapshot %d: %d fast configs, %d host keys",
                 snap_id, len(fcs), len(hosts))

    def _on_oidc_change(self) -> None:
        """JWKS rotation: rebuild the C++ snapshot (fresh, empty variant
        map) so tokens verified under retired keys stop being served fast.
        Runs on its own thread — the notifier is an asyncio worker and
        refresh() blocks on the swap-gate jit compile."""
        if not self._running:
            return
        self._track_thread(threading.Thread(target=self._refresh_if_running,
                                            name="atpu-fe-oidc-refresh"))

    def _refresh_if_running(self) -> None:
        if self._running:
            self.refresh()

    def _track_thread(self, t: threading.Thread) -> None:
        """Register-then-start a compile-bearing helper thread under its
        own lock (callers run both with and without _lock) — a dropped
        entry would escape stop()'s join and race interpreter teardown."""
        with self._thread_lock:
            self._prewarm_threads = [
                p for p in self._prewarm_threads if p.is_alive()] + [t]
        t.start()

    def _register_dyn(self, rec, entry, pipeline, model) -> None:
        """After a slow-lane pipeline run: if the config is dyn-eligible and
        identity resolved, cache this token's plan variant in C++ so the
        next request with it never touches Python (the fast-lane analog of
        the reference's per-evaluator TTL cache keyed by access token,
        ref pkg/evaluators/evaluator.go caching + opa.go:141 precompile).

        ``rec`` is the snapshot record captured BEFORE the pipeline ran: a
        JWKS rotation that rebuilds the snapshot mid-verification makes the
        registration land on the superseded (no longer serving) snapshot
        instead of re-caching a retired-key token into the fresh one."""
        if rec is None or rec is not self._cur_rec:
            return
        reg = rec.dyn_regs.get(entry.id)
        if reg is None:
            return
        fc_idx, auth_attrs, reg_policy, src_map, reg_hybrid = reg
        conf, obj = pipeline.resolved_identity()
        if obj is None:
            return
        reg_src = src_map.get(id(conf))
        if reg_src is None:
            return  # the winning identity is not a dyn source
        src_idx, ttl_cap = reg_src
        idc = conf
        import time as _time

        now = _time.time()
        ttl = self.dyn_ttl_s
        if ttl_cap is not None:
            # the opted-in window is anchored at the LAST REAL check: a
            # registration off a pipeline-cache hit must not restart the
            # clock (revocation would slip past cache.ttl otherwise)
            ttl = min(ttl, ttl_cap)
            if idc.cache is not None:
                try:
                    rem = idc.cache.remaining(idc.cache.resolve_key_for(
                        pipeline.authorization_json()))
                except Exception:
                    rem = None
                if rem is not None:
                    ttl = min(ttl, rem)
        deadline = now + ttl
        if isinstance(idc.evaluator, MTLS):
            # the raw forwarded PEM is the cache key (exactly the bytes the
            # C++ side extracts); the cert's own notAfter bounds the entry
            token = model.source.certificate or ""
            if not token:
                return
            try:
                import urllib.parse

                from cryptography import x509

                cert = x509.load_pem_x509_certificate(
                    urllib.parse.unquote(token).encode())
                deadline = min(deadline,
                               cert.not_valid_after_utc.timestamp())
            except Exception:
                return
        else:
            try:
                token = idc.evaluator.credentials.extract(model.http)
            except Exception:
                return
        exp = obj.get("exp") if isinstance(obj, dict) else None
        if isinstance(exp, (int, float)) and not isinstance(exp, bool):
            deadline = min(deadline, float(exp))
        if deadline <= now:
            return
        vplans: List[tuple] = []
        if auth_attrs:
            if reg_policy is None:
                return
            doc = _const_doc(obj)
            for attr in auth_attrs:
                p = _const_plan(reg_policy, attr, doc)
                if p is None:
                    return  # this token's values don't fit the compact payload
                vplans.append(p)
        rt_e = entry.runtime
        ok_bytes = b""
        deny_bytes = b""
        try:
            if rt_e.response and not reg_hybrid:
                # hybrid OKs are answered by the pipeline (response phase
                # runs there) — no per-credential OK bytes
                ok_bytes = self._ok_bytes_for(rt_e, obj)
            if rt_e.authorization and not _deny_with_static(
                    rt_e.deny_with.unauthorized):
                deny_bytes = self._result_bytes(self._deny_result(rt_e, obj))
        except Exception:
            return  # this credential's templates don't resolve: stay slow
        self._mod.fe_add_variant(rec.snap_id, fc_idx, src_idx,
                                 token.encode("utf-8"), vplans, ok_bytes,
                                 deny_bytes, int(deadline * 1e9))

    # ------------------------------------------------------------------
    def _fold_fc_counts(self) -> None:
        """Fold C++-side direct decisions (identity-only OKs, credential
        denials) into the same per-authconfig Prometheus series the pipeline
        bumps (ref pkg/service/auth_pipeline.go:26-36)."""
        for ns, name, ok, missing, invalid in self._mod.fe_drain_fc_counts():
            metrics_mod.authconfig_total.labels(ns, name).inc(ok + missing + invalid)
            if ok:
                metrics_mod.authconfig_response_status.labels(ns, name, "OK").inc(ok)
            if missing or invalid:
                metrics_mod.authconfig_response_status.labels(
                    ns, name, "UNAUTHENTICATED").inc(missing + invalid)
        # duration + stage histograms drain on a coarser cadence — each
        # drain walks every fc × bucket atomic, too wide for per-batch
        now = time.monotonic()
        if now - self._last_hist_drain >= self.hist_drain_s:
            self._last_hist_drain = now
            self.drain_histograms()

    def drain_histograms(self) -> None:
        """Fold the C++-recorded duration/stage histograms into Prometheus:
        auth_server_authconfig_duration_seconds per authconfig (metric
        parity with ref pkg/service/auth_pipeline.go:26-36 on the fast
        lane) and auth_server_frontend_stage_duration_seconds per on-box
        stage.  Also accumulates raw stage counts in self.stage_totals for
        the bench's on-box latency artifact."""
        for ns, name, buckets, sum_ns in self._mod.fe_drain_durations():
            metrics_mod.observe_bucketed(
                metrics_mod.authconfig_duration.labels(ns, name),
                buckets, sum_ns / 1e9)
        stages = self._mod.fe_stage_hist()
        if not stages:
            return  # server already stopped (fe_stop raced this drain)
        for stage in ("wait", "exec", "respond"):
            counts = stages[stage]
            acc = self.stage_totals.setdefault(stage, [0] * len(counts))
            for i, n in enumerate(counts):
                acc[i] += n
            # sum approximated from bucket midpoints: the stage series is
            # for shape/percentiles, not totals (bounds are µs-dense)
            bounds = stages["bounds_ns"]
            mids = [b / 2e9 if i == 0 else (bounds[i - 1] + b) / 2e9
                    for i, b in enumerate(bounds)] + [bounds[-1] / 1e9]
            est_sum = sum(n * mids[i] for i, n in enumerate(counts))
            metrics_mod.observe_bucketed(
                metrics_mod.frontend_stage_duration.labels(stage),
                counts, est_sum)
        self.stage_totals["bounds_ns"] = stages["bounds_ns"]

    def _dispatch_loop(self) -> None:
        mod = self._mod
        while self._running:
            kind, a, b, c = mod.fe_wait_batch(200)
            self._fold_fc_counts()
            if kind == EV_BATCH:
                try:
                    self._dispatch(int(a), int(b), int(c))
                except Exception as e:
                    log.exception("native batch dispatch failed")
                    # retry once, then degrade (CPU-backend kernel) — fail
                    # closed deny only when the degraded lane fails too
                    try:
                        self._native_batch_failed(int(a), int(b), int(c), 0, e)
                    except Exception:
                        log.exception("native batch failure handling failed")
            elif kind == EV_SNAP_RETIRED:
                # GIL-atomic pop, deliberately NOT under _lock: refresh holds
                # _lock across its swap-gate jit compile, and blocking here
                # would stall every batch completion queued behind this event
                self._snaps.pop(int(a), None)
            elif kind == EV_STOPPED:
                break

    def _dedup_plan(self, rec: _SnapRec, a: Dict[str, np.ndarray],
                    count: int, rows: np.ndarray,
                    shards_arr: Optional[np.ndarray]):
        """Cache-lookup + within-batch row collapse for one C++-encoded
        slot.  Keys are the raw encoded operand bytes of each row (exact:
        the kernel is a pure per-row function; the native path has no
        lossy host-fallback rows).  Single-corpus snapshots key the cache
        per config — (encoding epoch, config fingerprint, row bytes), so
        entries for configs a reconcile did not touch SURVIVE the swap
        (ISSUE 8); mesh corpora fall back to snap_id keying.  Returns
        (cache_keys, eligible [count] bool, cached {row: verdict},
        miss_rows, unique_rows, inverse, eligible_misses) — or None when
        both features are off."""
        cache = self._verdict_cache
        if not self.batch_dedup and cache is None:
            return None
        from ..compiler.pack import dedup_rows, row_key_bytes

        arrays = [a["config_id"], a["attrs_val"], a["members"],
                  a["cpu_dense"], a["attr_bytes"], a["byte_ovf"]]
        if shards_arr is not None:
            arrays.insert(0, a["shard_of"])
        keys = row_key_bytes(arrays, count)
        tok = rec.cache_tokens if shards_arr is None else None
        if tok is not None:
            ckeys = [(tok[rows[r]], keys[r]) for r in range(count)]
        else:
            snap_id = rec.snap_id
            ckeys = [(snap_id, keys[r]) for r in range(count)]
        if rec.cacheable is None:
            eligible = np.zeros((count,), dtype=bool)
        elif shards_arr is not None:
            eligible = rec.cacheable[shards_arr, rows]
        else:
            eligible = rec.cacheable[rows]
        cached: Dict[int, int] = {}
        elig_miss = 0
        if cache is not None:
            miss_rows: List[int] = []
            for r in range(count):
                if eligible[r]:
                    v = cache.get(ckeys[r])
                    if v is not None:
                        cached[r] = v
                        continue
                    elig_miss += 1
                miss_rows.append(r)
        else:
            miss_rows = list(range(count))
        if self.batch_dedup:
            unique_rows, inverse = dedup_rows(keys, miss_rows)
        else:
            unique_rows, inverse = miss_rows, np.arange(len(miss_rows))
        return ckeys, eligible, cached, miss_rows, unique_rows, inverse, elig_miss

    def _row_h2d_bytes(self, a: Dict[str, np.ndarray], eff: int,
                       has_dfa: bool, sharded: bool) -> int:
        """Per-row operand bytes one launch stages from this slot's
        arrays at byte-width ``eff`` (pure shape arithmetic — numpy basic
        indexing views, no copies): multiply by the pad bucket for the
        ledger's exact H2D count."""
        per = (a["attrs_val"][0].nbytes + a["members"][0].nbytes
               + a["cpu_dense"][0].nbytes + a["config_id"].dtype.itemsize)
        if has_dfa:
            per += (a["attr_bytes"][0][..., :eff].nbytes
                    + a["byte_ovf"][0].nbytes)
        if sharded:
            per += a["shard_of"].dtype.itemsize  # mesh routing row
        return int(per)

    def _dispatch(self, snap_id: int, slot: int, count: int,
                  attempt: int = 0, spill: bool = True) -> None:
        """Launch stage: non-blocking kernel dispatch for one C++-encoded
        slot, then park the in-flight batch on the readback queue.  The
        dispatcher thread is immediately free to launch the next slot, so
        the in-flight window is the C++ slot count — batches overlap on the
        link instead of serializing per thread.

        Before the launch, cached (snap_id, row-digest) verdicts resolve
        without the device and the remaining rows collapse to UNIQUE rows
        (ISSUE 3): the H2D payload carries only unique work, and the
        readback thread fans verdicts back out through the inverse map.
        The readback itself is the bit-packed u8 bitmask (8 verdicts/
        byte), so D2H bytes shrink ~8x on the RTT-bound link too.

        ``attempt`` is the retry generation (0 = first dispatch, 1 = the
        one retry after a device failure); an OPEN circuit breaker skips
        the device entirely and decides the slot on the CPU backend."""
        import jax.numpy as jnp

        from ..ops.pattern_eval import eval_bitpacked_jit

        rec = self._snaps[snap_id]
        allowed, probe = self.breaker.admit_device()
        if not allowed:
            self._degrade_slot(rec, snap_id, slot, count)
            return
        # a claimed half-open PROBE must reach the device: routing it
        # host-side (lane choice or brownout) would strand _probe_inflight
        # forever — no breaker verdict ever lands, every later slot skips
        # the device, and a transiently-sick device becomes a permanent
        # host-only degrade.  (The engine lane turns probes into
        # speculative dual-dispatch instead; this lane has no first-wins
        # seam, so the probe simply rides the device alone.)
        if (spill and not probe and self.lanes.enabled
                and rec.sharded is None and rec.policy is not None
                and count <= self.lanes.host_max_rows):
            # slot-level lane choice (ISSUE 12): a small gathered slot the
            # cost model says the CPU-backend twin answers FASTER than a
            # device round trip rides the host lane — light-load latency
            # stops paying the H2D/D2H trip.  Same worker-thread + live-
            # counter discipline as brownout (stop() waits these out), but
            # its own trigger and counters: this is a latency choice, not
            # an overload spill.
            which, why = self.lanes.decide(count, self._rb_inflight,
                                           self.slots)
            if which == L_HOST:
                taken = False
                with self._rb_lock:
                    if self.lanes.host_inflight < self.lanes.host_limit:
                        self.lanes.host_inflight += 1
                        self._brownout_live += 1
                        taken = True
                if taken:
                    self.lanes.count(L_HOST, why)
                    self._host_pool.submit(self._brownout_slot, rec,
                                           snap_id, slot, count, why=why)
                    return
                # a concurrent host worker filled the cap between decide()
                # and the under-lock re-check: the slot rides the device —
                # record THAT, or dispatched slots stop summing up
                which, why = L_DEVICE, "host-busy"
            self.lanes.count(L_DEVICE, why)
        if (spill and not probe and self.brownout
                and count <= self.brownout_max_rows
                and self._rb_inflight >= self._brownout_threshold
                and rec.sharded is None and rec.policy is not None):
            # device pipeline saturated (nearly every slot in flight) and
            # this batch is small: answer it on the CPU-backend kernel
            # instead of queueing it behind a full window — exact verdicts,
            # bounded latency (brownout, docs/robustness.md).  On its OWN
            # worker thread: the first CPU eval of a new (pad, eff) shape
            # jit-compiles, and that must never stall a dispatcher thread
            # mid-saturation (mirrors _fail_async — at most one live
            # thread per C++ slot, since a slot cannot re-fire until
            # fe_complete_batch refills it).  Counted in _brownout_live so
            # stop()'s drain waits the spill out before fe_stop.
            with self._rb_lock:
                self._brownout_live += 1
            threading.Thread(target=self._brownout_slot,
                             args=(rec, snap_id, slot, count),
                             name="atpu-fe-brownout", daemon=True).start()
            return
        a = rec.arrays[slot]
        # copy attribution rows BEFORE the slot can complete: once
        # fe_complete_batch runs, the C++ encoder may refill them
        rows = a["config_id"][:count].copy()
        shards_arr = (a["shard_of"][:count].copy()
                      if rec.sharded is not None else None)
        fan = self._dedup_plan(rec, a, count, rows, shards_arr)
        if fan is not None:
            unique_rows = fan[4]
            u = len(unique_rows)
        else:
            unique_rows, u = list(range(count)), count

        def sel(name):
            """Unique-row operand view: the slot arrays sliced [:pad] when
            nothing collapsed (stale pad rows discarded, as before), else
            fancy-indexed unique rows padded by repeating the first (a
            copy — the slot refills once the batch completes)."""
            return a[name][:pad] if u == count else a[name][idx]

        if rec.sharded is not None:
            # one shard_map dispatch per micro-batch: the C++ encoder
            # already laid each request into its owning shard's [B, S, ...]
            # slice (packed bit 0 = own verdict, psum-merged over 'mp')
            sh = rec.sharded
            has_dfa = sh.has_dfa
        else:
            has_dfa = rec.params["dfa_tables"] is not None
        cost_lane = "native" if rec.sharded is None else "mesh"
        if u == 0:
            # every row cache-resolved: complete through the readback queue
            # with no device work at all
            pad = eff = 0
            packed = np.zeros((0, 1), dtype=np.uint8)
            t0 = time.monotonic()
            t0_ns = time.time_ns()
            # structural cost fold (ISSUE 16): ZERO launches, zero bytes —
            # the parity the perf_guard tests pin exactly
            LEDGER.observe(
                cost_lane, rows=count,
                dedup_avoided_rows=(len(fan[3]) if fan is not None else 0),
                cache_avoided_rows=(len(fan[2]) if fan is not None else 0))
        else:
            eff_need = (_trim_bytes(a["attr_bytes"][:count] if u == count
                                    else a["attr_bytes"][unique_rows]
                                    ).shape[-1]
                        if has_dfa else 0)
            eff = eff_need
            # round the batch/byte buckets up to an already-compiled variant
            # so XLA compiles never land on live requests (rows past the
            # unique count carry stale/repeated operands; results discarded)
            pad, eff = self._pick_warm_shape(rec, u, eff)
            idx = (np.asarray(unique_rows + [unique_rows[0]] * (pad - u))
                   if u != count else None)
            t0 = time.monotonic()
            t0_ns = time.time_ns()
            if faults.ACTIVE:
                faults.FAULTS.check("h2d", "native")
                faults.FAULTS.check("kernel", "native")
            if rec.sharded is not None:
                with sh.state.launch_lock:  # psum enqueue-order consistency
                    packed = sh._step(
                        sh.params,
                        jnp.asarray(sel("attrs_val")),
                        jnp.asarray(sel("members")),
                        jnp.asarray(sel("cpu_dense").view(bool)),
                        jnp.asarray(np.ascontiguousarray(
                            sel("attr_bytes")[..., :eff]))
                        if has_dfa else None,
                        jnp.asarray(sel("byte_ovf").view(bool))
                        if has_dfa else None,
                        jnp.asarray(sel("shard_of")),
                        jnp.asarray(sel("config_id")),
                    )
            elif rec.params.get("fused") is not None:
                # fused lane (ISSUE 17): the ONE-launch mega-kernel entry
                # (operands are already separate arrays here, so the
                # per-operand variant stages them; compute + in-kernel
                # bitpack are a single executable either way)
                from ..ops import fused_kernel as fused_mod

                packed = fused_mod._fused_ops_jit(
                    rec.params,
                    jnp.asarray(sel("attrs_val")),
                    jnp.asarray(sel("members")),
                    jnp.asarray(sel("cpu_dense").view(bool)),
                    jnp.asarray(sel("config_id")),
                    jnp.asarray(np.ascontiguousarray(
                        sel("attr_bytes")[..., :eff]))
                    if has_dfa else None,
                    jnp.asarray(sel("byte_ovf").view(bool))
                    if has_dfa else None,
                    None, None, None, None,
                    use_pallas=fused_mod.fused_kernel_supported(),
                )
            else:
                packed = eval_bitpacked_jit(
                    rec.params,
                    jnp.asarray(sel("attrs_val")),
                    jnp.asarray(sel("members")),
                    jnp.asarray(sel("cpu_dense").view(bool)),
                    jnp.asarray(sel("config_id")),
                    jnp.asarray(np.ascontiguousarray(
                        sel("attr_bytes")[..., :eff]))
                    if has_dfa else None,
                    jnp.asarray(sel("byte_ovf").view(bool))
                    if has_dfa else None,
                )
            if faults.ACTIVE:
                packed = faults.FAULTS.wrap_handle(packed, "native")
            if rec.sharded is None:
                try:
                    from ..ops.pattern_eval import kernel_lane_of

                    metrics_mod.observe_kernel_lane(
                        kernel_lane_of(rec.params))
                except Exception:
                    pass  # metrics are advisory
            try:
                packed.copy_to_host_async()
            except Exception:
                pass
            # structural cost fold (ISSUE 16): ONE launch per slot, the
            # exact H2D operand bytes this (pad, eff) variant staged and
            # the bitpacked [pad, W] readback.  eff-column slack is the
            # warm-shape round-up (eff - eff_need); sharded slots count
            # their collective launch on the mesh lane instead (one per
            # shard-step — LEDGER.observe_launch fires in sh._step's
            # dispatch path only for dispatch_full, so count it here)
            h2d = pad * self._row_h2d_bytes(a, eff, has_dfa,
                                            rec.sharded is not None)
            d2h = int(packed.shape[0]) * int(packed.shape[1])
            if rec.sharded is not None:
                LEDGER.observe_launch("mesh", 1, h2d_bytes=h2d,
                                      d2h_bytes=d2h)
                LEDGER.observe(
                    "mesh", rows=count, device_rows=u, pad_rows=pad,
                    eff_slack_cols=eff - eff_need,
                    dedup_avoided_rows=(len(fan[3]) - u
                                        if fan is not None else 0),
                    cache_avoided_rows=(len(fan[2])
                                        if fan is not None else 0))
            else:
                LEDGER.observe(
                    "native", rows=count, device_rows=u, launches=1,
                    h2d_bytes=h2d, d2h_bytes=d2h, pad_rows=pad,
                    eff_slack_cols=eff - eff_need,
                    dedup_avoided_rows=(len(fan[3]) - u
                                        if fan is not None else 0),
                    cache_avoided_rows=(len(fan[2])
                                        if fan is not None else 0))
        with self._rb_lock:
            self._rb_inflight += 1
            if self._rb_inflight > self.rb_inflight_peak:
                self.rb_inflight_peak = self._rb_inflight
            inflight = self._rb_inflight
        self._g_native_inflight.set(inflight)
        self._rb_q.append((rec, snap_id, slot, count, pad, eff, rows,
                           shards_arr, packed, t0, t0_ns, fan, attempt))
        self._rb_evt.set()

    def _brownout_slot(self, rec: _SnapRec, snap_id: int, slot: int,
                       count: int, why: str = "brownout") -> None:
        """Answer one small slot on the CPU-backend kernel (worker thread —
        see _dispatch).  Two distinct triggers share this execution path:
        ``why="brownout"`` = the device window is saturated (overload
        spill, PR 7 counters); any other ``why`` = the ISSUE 12 cost model
        simply chose the host lane as FASTER (counted in
        auth_server_lane_decisions_total instead).  If the host eval
        itself fails, the slot falls back to a normal device dispatch
        (spill=False so it cannot loop back here).  Exactness: same
        kernel, same encoded operands — only the execution backend
        differs."""
        lane_sel = why != "brownout"
        try:
            t0 = time.monotonic()
            t0_ns = time.time_ns()
            rows = rec.arrays[slot]["config_id"][:count].copy()
            try:
                verdict, firing = self._host_eval(rec, slot, count)
            except Exception:
                log.exception("native host-lane eval failed; batch rides "
                              "the device instead")
                try:
                    self._dispatch(snap_id, slot, count, spill=False)
                except Exception as e:
                    log.exception("post-host-lane device dispatch failed")
                    try:
                        self._native_batch_failed(snap_id, slot, count, 0, e)
                    except Exception:
                        log.exception("native batch failure handling failed")
                return
            dur = time.monotonic() - t0
            self.lanes.cost.observe_host(dur, count)
            # kernel-cost ledger (ISSUE 16): a host-lane batch performs
            # ZERO device launches and moves zero device bytes — exactly
            LEDGER.observe("host", rows=count)
            if lane_sel:
                self.lanes.count_rows(L_HOST, count)
            else:
                metrics_mod.brownout_decisions.labels("native").inc(count)
                metrics_mod.brownout_batches.labels("native").inc()
                self._brownout_total += count
                self._brownout_batches += 1
            if not self._fe_stopped:
                self._mod.fe_complete_batch(snap_id, slot, verdict.ctypes.data)
            try:
                # pad/eff 0 + device_rows 0: per-authconfig counters stay
                # exact, while the device-occupancy series never sees a
                # batch that deliberately skipped the device
                self._post_complete_telemetry(rec, count, 0, 0, rows, None,
                                              verdict, dur, t0_ns,
                                              device_rows=0, device=False,
                                              firing=firing)
            except Exception:
                log.exception("host-lane telemetry failed")
        finally:
            with self._rb_lock:
                self._brownout_live -= 1
                if lane_sel:
                    self.lanes.host_inflight -= 1

    def _readback_loop(self) -> None:
        """Completion stage: finalize in-flight batches as their readbacks
        arrive (is_ready polling — a slow batch never convoys a fast one),
        completing each into C++ and folding its telemetry."""
        pending: List[tuple] = []
        while True:
            while self._rb_q:
                try:
                    pending.append(self._rb_q.popleft())
                except IndexError:
                    break
            if not pending:
                if not self._running:
                    return
                self._rb_evt.wait(0.2)
                self._rb_evt.clear()
                continue
            progressed = False
            for item in list(pending):
                is_ready = getattr(item[8], "is_ready", None)
                try:
                    ready = is_ready is None or bool(is_ready())
                except Exception:
                    ready = True  # surface the real error in completion
                if not ready:
                    t = self.device_timeout_s
                    if t and time.monotonic() - item[9] > t:
                        # watchdog: readback wedged past --device-timeout —
                        # abandon the handle, count a breaker failure, and
                        # feed the slot the retry/degrade path
                        pending.remove(item)
                        progressed = True
                        metrics_mod.watchdog_timeouts.labels("native").inc()
                        RECORDER.record("watchdog-timeout", lane="native",
                                        detail={"slot": item[2],
                                                "requests": item[3],
                                                "attempt": item[12]})
                        log.warning(
                            "native batch (slot %d, %d requests, attempt %d)"
                            " wedged past --device-timeout %.3fs",
                            item[2], item[3], item[12], t)
                        try:
                            self._fail_async(
                                item[1], item[2], item[3], item[12],
                                TimeoutError("device readback watchdog "
                                             "timeout"))
                        except Exception:
                            log.exception("native watchdog handling failed")
                        finally:
                            with self._rb_lock:
                                self._rb_inflight -= 1
                                inflight = self._rb_inflight
                            self._g_native_inflight.set(inflight)
                    continue
                pending.remove(item)
                progressed = True
                try:
                    self._complete_device_batch(*item)
                except Exception as e:
                    log.exception("native batch completion failed")
                    try:
                        # retry once, then degrade on the CPU backend (deny
                        # fail-closed only when that fails too; never into
                        # a stopped server — see _complete_device_batch)
                        self._fail_async(item[1], item[2], item[3],
                                         item[12], e)
                    except Exception:
                        log.exception("native batch failure handling failed")
                finally:
                    with self._rb_lock:
                        self._rb_inflight -= 1
                        inflight = self._rb_inflight
                    self._g_native_inflight.set(inflight)
            if not progressed:
                # sub-ms poll while results ride the link (noise vs RTT)
                self._rb_evt.wait(0.0005)
                self._rb_evt.clear()

    def _complete_device_batch(self, rec: _SnapRec, snap_id: int, slot: int,
                               count: int, pad: int, eff: int,
                               rows: np.ndarray,
                               shards_arr: Optional[np.ndarray],
                               packed, t0: float, t0_ns: int,
                               fan=None, attempt: int = 0) -> None:
        if self._fe_stopped:
            # stop()'s drain deadline expired with this batch still on the
            # wire and fe_stop has run: completing into the torn-down C++
            # server would be a native use-after-stop
            return
        if faults.ACTIVE:
            faults.FAULTS.check("readback", "native")
        packed = np.asarray(packed)
        if pad:
            # the device answered (cache-only batches with pad == 0 never
            # touched it): clear the breaker's consecutive-failure count
            self.breaker.record_success()
        else:
            # a cache-only batch proves nothing about the device — just
            # release a half-open probe slot it may have claimed
            self.breaker.release_probe()
        dispatch_s = time.monotonic() - t0
        # attribution (ISSUE 9): the packed readback already carries the
        # per-rule result/skip columns — ONE vectorized unpack per batch
        # recovers the firing column next to the verdict bit (zero
        # per-request Python, pinned by tests/test_provenance.py)
        from ..ops.pattern_eval import unpack_attribution

        heat = rec.heat
        E = heat.E if heat is not None else 0
        if fan is None:
            # dedup/cache off: packed is the bit-masked result of the full
            # slot; own verdict = bit 0 of byte 0
            if E:
                verdict, firing = unpack_attribution(packed[:count], E)
                verdict = np.ascontiguousarray(verdict)
            else:
                verdict = np.ascontiguousarray(
                    packed[:count, 0] & 1).astype(np.uint8)
                firing = None
            u = count
            cached_n = elig_miss_n = evict_d = 0
        else:
            keys, eligible, cached, miss_rows, unique_rows, inverse, \
                elig_miss_n = fan
            u = len(unique_rows)
            verdict = np.zeros((count,), dtype=np.uint8)
            firing = np.full((count,), -1, dtype=np.int32) if E else None
            if u:
                if E:
                    uniq_v, uniq_f = unpack_attribution(packed[:u], E)
                else:
                    uniq_v = (packed[:, 0] & 1).astype(np.uint8)
                    uniq_f = None
                mr = np.asarray(miss_rows)
                verdict[mr] = uniq_v[inverse]
                if firing is not None and uniq_f is not None:
                    firing[mr] = uniq_f[inverse]
            for r, v in cached.items():
                # cached value = (verdict, firing): a cache hit attributes
                # identically to the device evaluation it memoized
                verdict[r] = v[0]
                if firing is not None:
                    firing[r] = v[1]
            verdict = np.ascontiguousarray(verdict)
            cached_n = len(cached)
            evict_d = 0
        self._mod.fe_complete_batch(snap_id, slot, verdict.ctypes.data)
        # the slot is COMPLETED from here on: an exception below must not
        # propagate to the readback loop's fail-closed deny, which would
        # fe_complete_batch the same slot twice — by then possibly refilled
        # with a fresh live batch
        try:
            cache = self._verdict_cache
            if fan is not None and cache is not None:
                evict0 = cache.evictions
                for r in fan[4]:  # unique rows: freshly evaluated
                    if fan[1][r]:
                        # fan[0] carries the FULL cache key (per-config
                        # token or snap_id already folded in — captured
                        # from the batch's pinned snapshot at dispatch)
                        cache.put(fan[0][r], (
                            int(verdict[r]),
                            int(firing[r]) if firing is not None else -1))
                evict_d = cache.evictions - evict0
            metrics_mod.observe_dedup("native", count, u, cached_n,
                                      elig_miss_n, evict_d)
            self._post_complete_telemetry(rec, count, pad, eff, rows,
                                          shards_arr, verdict, dispatch_s,
                                          t0_ns, device_rows=u,
                                          firing=firing)
        except Exception:
            log.exception("post-completion telemetry failed")

    def _fail_async(self, snap_id: int, slot: int, count: int,
                    attempt: int, exc: Exception) -> None:
        """Hand a failed batch to its own worker thread: the retry dispatch
        and the CPU-backend degrade (whose first use jit-compiles) must not
        stall the single readback thread that completes every other
        in-flight batch.  Bounded: at most one live thread per C++ slot —
        a slot cannot fail again until fe_complete_batch refills it."""
        threading.Thread(target=self._native_batch_failed,
                         args=(snap_id, slot, count, attempt, exc),
                         name="atpu-fe-degrade", daemon=True).start()

    def _native_batch_failed(self, snap_id: int, slot: int, count: int,
                             attempt: int, exc: Exception) -> None:
        """One device batch failed (launch, readback, or watchdog): count a
        breaker failure, retry ONCE on a fresh dispatch from the same slot
        (the C++ operands are intact until fe_complete_batch), then decide
        the slot on the degraded lane — the native mirror of the engine's
        _batch_failed."""
        self.breaker.record_failure()
        rec = self._snaps.get(snap_id)
        if attempt == 0 and rec is not None:
            metrics_mod.batch_retries.labels("native").inc()
            log.warning("native batch (slot %d, %d requests) failed (%r): "
                        "retrying once on a fresh dispatch", slot, count, exc)
            try:
                self._dispatch(snap_id, slot, count, attempt=1)
                return
            except Exception as e2:
                log.exception("native batch retry dispatch failed")
                self.breaker.record_failure()
                exc = e2
        self._degrade_slot(rec, snap_id, slot, count, exc=exc)

    def _degrade_slot(self, rec: Optional[_SnapRec], snap_id: int, slot: int,
                      count: int, exc: Optional[Exception] = None) -> None:
        """Degraded lane: evaluate the slot's already-encoded operands with
        the SAME kernel on the CPU backend (exactness preserved — the
        kernel is a pure per-row function; only the execution device
        changes).  Deny fail-closed ONLY when the degraded evaluation
        itself is impossible (no CPU backend, mesh-sharded corpus, retired
        snapshot)."""
        if rec is None:
            # the C++ side already retired this snapshot (EV_SNAP_RETIRED
            # raced the failure): its slots are gone — completing into it
            # would be a native use-after-retire, so drop the batch
            log.warning("native batch failure for retired snapshot %d "
                        "(slot %d, %d requests): dropped", snap_id, slot,
                        count)
            return
        verdict: Optional[np.ndarray] = None
        firing: Optional[np.ndarray] = None
        rows: Optional[np.ndarray] = None
        if rec.sharded is None and rec.policy is not None:
            try:
                # attribution rows copied BEFORE completion: the C++
                # encoder may refill the slot once fe_complete_batch runs
                rows = rec.arrays[slot]["config_id"][:count].copy()
                t0 = time.monotonic()
                verdict, firing = self._host_eval(rec, slot, count)
                # degraded host evals teach the cost model too (ISSUE 12):
                # a frontend that spent its warm-up degrading must not
                # enter lane selection on the cold-start estimate
                self.lanes.cost.observe_host(time.monotonic() - t0, count)
                # kernel-cost ledger (ISSUE 16): degrade = host lane, zero
                # device launches
                LEDGER.observe("host", rows=count)
            except Exception:
                log.exception("native host degrade failed (fail-closed deny)")
        if verdict is not None:
            metrics_mod.degraded_decisions.labels("native").inc(count)
            if exc is not None:
                log.warning("native batch (slot %d, %d requests) decided on "
                            "the CPU backend after device failure (%r)",
                            slot, count, exc)
        else:
            verdict = np.zeros(count, dtype=np.uint8)
        if not self._fe_stopped:
            self._mod.fe_complete_batch(snap_id, slot, verdict.ctypes.data)
        if firing is not None and rows is not None and rec.heat is not None:
            try:
                # degraded decisions attribute like the device decisions
                # they replaced (same kernel, CPU backend) — heat fold +
                # head sample only; the per-authconfig counters keep their
                # established healthy-path-only semantics
                prov_mod.fold_and_sample(rec.heat, rows, firing, count,
                                         lane="native",
                                         generation=rec.snap_id)
                # tenant parity (ISSUE 15 satellite): degraded slots used
                # to bypass per-tenant accounting entirely — a contained
                # or degraded tenant's traffic must still burn ITS
                # requests/denies, not vanish from the tenant plane
                ten = self.tenancy
                if ten is not None and ten.enabled:
                    ten.fold(rec.heat, rows, firing=firing, lane="native")
            except Exception:
                log.exception("degrade provenance fold failed")

    def _host_eval(self, rec: _SnapRec, slot: int,
                   count: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """CPU-backend kernel evaluation of one C++-encoded slot → (own
        verdicts [count] uint8, firing columns [count] int32 or None) —
        the SAME packed columns the device returns, so degraded/brownout
        decisions attribute identically.  The host operand pytree is built
        lazily once per snapshot; each (pad, eff) shape compiles on first
        use — a degraded-mode cost, never on the healthy path."""
        import jax
        import jax.numpy as jnp

        from ..ops.pattern_eval import (
            eval_bitpacked_jit,
            to_device,
            unpack_attribution,
        )

        a = rec.arrays[slot]
        if rec.host_params is None:
            rec.host_params = to_device(rec.policy, host=True)
        has_dfa = rec.host_params["dfa_tables"] is not None
        pad = min(bucket_pow2(count), self.max_batch)
        eff = (_trim_bytes(a["attr_bytes"][:count]).shape[-1]
               if has_dfa else 0)
        # round up into an already-warmed CPU variant (ISSUE 12 satellite:
        # the pre-warm thread compiles the small shapes at snapshot swap,
        # so a live host-lane slot pays no inline XLA compile; rows past
        # the count carry stale operands and their results are discarded —
        # the same discipline as the device lane's _pick_warm_shape)
        if (pad, eff) not in rec.host_warm:
            best = None
            for p, e in tuple(rec.host_warm):
                if p >= count and e >= eff and (best is None or (p, e) < best):
                    best = (p, e)
            if best is not None:
                pad, eff = best
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            packed = eval_bitpacked_jit(
                rec.host_params,
                jnp.asarray(a["attrs_val"][:pad]),
                jnp.asarray(a["members"][:pad]),
                jnp.asarray(a["cpu_dense"][:pad].view(bool)),
                jnp.asarray(a["config_id"][:pad]),
                jnp.asarray(np.ascontiguousarray(
                    a["attr_bytes"][:pad, :, :eff])) if has_dfa else None,
                jnp.asarray(a["byte_ovf"][:pad].view(bool))
                if has_dfa else None,
            )
            out = np.asarray(packed)
        rec.host_warm.add((pad, eff))  # compiled now, warm from here on
        E = rec.heat.E if rec.heat is not None else 0
        if E:
            verdict, firing = unpack_attribution(out[:count], E)
            return np.ascontiguousarray(verdict), firing
        return (np.ascontiguousarray(out[:count, 0] & 1).astype(np.uint8),
                None)

    def _post_complete_telemetry(self, rec: _SnapRec, count: int, pad: int,
                                 eff: int, rows: np.ndarray,
                                 shards_arr: Optional[np.ndarray],
                                 verdict: np.ndarray, dispatch_s: float,
                                 t0_ns: int,
                                 device_rows: Optional[int] = None,
                                 device: bool = True,
                                 firing: Optional[np.ndarray] = None) -> None:
        # per-batch telemetry AFTER completion: responses are already on
        # their way to the wire (queue wait is C++-clocked — stage hists).
        # ``device=False`` (brownout spill) keeps the per-authconfig
        # counters but stays out of the device-lane batch/RTT series — a
        # sub-ms host eval must not read as a fast device round trip.
        # which-rule-fired attribution (ISSUE 9): one composite-key
        # bincount per batch into the rule heat map + at most one
        # head-sampled decision record — never per-request Python
        heat = rec.heat
        if heat is not None and firing is not None and count:
            prov_mod.fold_and_sample(heat, rows, firing, count,
                                     lane="native", shards=shards_arr,
                                     latency_ms=dispatch_s * 1e3,
                                     generation=rec.snap_id)
        # tenant axis (ISSUE 15): every completed slot — device, lane-
        # selected host AND brownout spill alike (device=False paths
        # included) — folds per-tenant requests/denies/SLO into the shared
        # plane, so fast-lane traffic is never invisible to the
        # noisy-neighbor detector or the per-tenant burn trackers
        ten = self.tenancy
        if ten is not None and ten.enabled and heat is not None and count:
            try:
                # waits=None: the native lane's per-request queue waits
                # are C++-clocked — feeding the batch ROUND TRIP as a
                # "queue wait" would latch every tenant overloaded on
                # normal device latency.  The SLO bad mask keeps the
                # lane's established SLI (the batch's on-box round trip,
                # shared by every member).
                slo_s = self.slo.slo_s if self.slo is not None else 0.0
                ten.fold(heat, rows, firing=firing, shards=shards_arr,
                         bad_mask=(np.full(count, dispatch_s > slo_s)
                                   if slo_s else None),
                         denied_mask=(np.asarray(verdict) == 0)
                         if firing is None else None,
                         lane="native")
            except Exception:
                log.exception("tenant fold failed (telemetry only)")
            # change safety (ISSUE 10): during an engine canary the native
            # fast lane serves the BASELINE (its C++ snapshot only
            # rebuilds on promotion — swap listeners are deferred), so its
            # attribution strengthens the guard's baseline cohort
            if getattr(self.engine, "_canary", None) is not None:
                self.engine.canary_observe_external(rows, firing, heat,
                                                    shards=shards_arr)
        if self.slo is not None and count:
            # the native SLI is the batch's on-box round trip (per-request
            # waits are C++-clocked): every member shares the batch verdict
            n_bad = count if dispatch_s > self.slo.slo_s else 0
            self.slo.observe(count, n_bad)
            # per-lane burn bias feed (ISSUE 12): selection leans toward
            # the lane that is not burning budget
            self.lanes.cost.observe_slo(L_DEVICE if device else L_HOST,
                                        count, n_bad)
        if device:
            if device_rows is None or device_rows > 0:
                # lane-selection cost model: every device completion feeds
                # the RTT/occupancy EWMAs the next slot decision compares
                # against (cache-only batches skip it — they never touched
                # the link, and their sub-ms turnaround would read as a
                # fast device)
                self.lanes.cost.observe_device(dispatch_s, count, 0,
                                               self._rb_inflight, self.slots)
            self.lanes.count_rows(L_DEVICE, count)
            metrics_mod.observe_batch("native", count, pad, None, dispatch_s,
                                      device_rows=device_rows)
            metrics_mod.observe_pipeline_stage("native", "device", dispatch_s)
        if device and tracing_mod.tracing_active():
            # fast-lane requests have no Python spans to link (only sampled
            # slow-lane ones do) — the DeviceBatch span still carries the
            # launch's batch_size/pad/eff for pad-waste attribution
            tracing_mod.export_device_batch_span(count, pad, eff, [],
                                                 t0_ns, dispatch_s)
        # per-authconfig request metrics, same counters + labels the
        # pipeline bumps (ref pkg/service/auth_pipeline.go:26-36)
        if shards_arr is not None:
            from ..parallel.sharded_eval import flat_config_rows

            G = rec.sharded.configs_per_shard
            flat = flat_config_rows(shards_arr, rows, G)
            n_per = np.bincount(flat)
            ok_per = np.bincount(flat, weights=verdict).astype(np.int64)
            keys = [(int(f // G), int(f % G)) for f in np.nonzero(n_per)[0]]
            idxs = np.nonzero(n_per)[0]
        else:
            n_per = np.bincount(rows)
            ok_per = np.bincount(rows, weights=verdict).astype(np.int64)
            idxs = np.nonzero(n_per)[0]
            keys = [int(f) for f in idxs]
        for f, key in zip(idxs, keys):
            n, n_ok = int(n_per[f]), int(ok_per[f])
            ns, name = rec.row_labels.get(key, ("", ""))
            if key in rec.hybrid_rows:
                # kernel-allowed hybrid requests continue into the
                # pipeline, which observes them itself — only the native
                # denials are final here
                n = n - n_ok
                n_ok = 0
                if not n:
                    continue
            metrics_mod.authconfig_total.labels(ns, name).inc(n)
            if n_ok:
                metrics_mod.authconfig_response_status.labels(ns, name, "OK").inc(n_ok)
            if n - n_ok:
                metrics_mod.authconfig_response_status.labels(
                    ns, name, "PERMISSION_DENIED").inc(n - n_ok)

    # ------------------------------------------------------------------
    def _completer_loop(self) -> None:
        """Drain buffered slow-lane responses into C++ in batches: two lock
        rounds + at most one epoll wake per batch instead of per response.
        Runs until stop() AND the buffer is flushed (stop()'s drain loop
        waits for slow_pending to clear, which needs these flushes)."""
        mod = self._mod
        buf = self._done_buf
        evt = self._done_evt
        while True:
            if not buf:
                # only sleep when the buffer is empty: a burst past the
                # batch cap must flush immediately, not after the timeout
                evt.wait(0.2)
                evt.clear()
            items = []
            while buf and len(items) < 1024:
                try:
                    items.append(buf.popleft())
                except IndexError:
                    break
            if items:
                try:
                    mod.fe_complete_slow_many(items)
                except Exception:
                    log.exception("batch completion failed")
            elif not self._running:
                return

    # ------------------------------------------------------------------
    def _slow_loop(self) -> None:
        import asyncio

        from .. import protos
        from ..service.grpc_server import (
            check_response_from_result,
            request_model_from_proto,
        )

        mod = self._mod
        engine = self.engine
        external_auth_pb2 = protos.external_auth_pb2

        from ..utils.tracing import RequestSpan

        done_buf = self._done_buf
        done_evt = self._done_evt

        def complete(req_id: int, payload: bytes, status: int) -> None:
            done_buf.append((req_id, payload, status))
            done_evt.set()

        from ..utils.rpc import RESOURCE_EXHAUSTED

        overload_bytes = check_response_from_result(AuthResult(
            code=RESOURCE_EXHAUSTED,
            message="server overloaded: slow lane shedding",
        )).SerializeToString()

        async def handle(req_id: int, raw: bytes) -> None:
            # CoDel admission (ISSUE 7): while the slow lane's estimated
            # standing wait has stayed above target for a full interval,
            # paced arrivals are answered typed RESOURCE_EXHAUSTED before
            # any parse/pipeline work — the C++ slow_cap bounds the queue,
            # this bounds the WAIT of what the queue holds
            if self.admission.drop_now():
                self.admission.count_reject("overload")
                complete(req_id, overload_bytes, 0)
                return
            try:
                req = external_auth_pb2.CheckRequest.FromString(raw)
                model = request_model_from_proto(req)
                if model is None:
                    result = AuthResult(code=INVALID_ARGUMENT, message="Invalid request")
                else:
                    # same flow as engine.check (host lookup + pipeline),
                    # inlined so the pipeline object is reachable for
                    # verified-token registration; same span lifecycle as
                    # the Python gRPC server (service/grpc_server.py check)
                    span = RequestSpan.from_headers(model.http.headers, model.http.id)
                    try:
                        entry = engine.lookup(model.host())
                        if entry is None:
                            result = AuthResult(code=NOT_FOUND,
                                                message="Service not found")
                        else:
                            # snapshot BEFORE verification: registration is
                            # dropped when a JWKS rotation swaps it mid-run
                            rec = self._cur_rec
                            pipeline = AuthPipeline(model, entry.runtime,
                                                    timeout=engine.timeout_s,
                                                    span=span)
                            result = await pipeline.evaluate()
                            # register BEFORE completing: once the client
                            # sees this response, a repeat of the same
                            # token must already be servable fast
                            self._register_dyn(rec, entry, pipeline, model)
                    finally:
                        span.end()
                complete(req_id,
                         check_response_from_result(result).SerializeToString(), 0)
            except Exception:
                log.exception("slow-lane request failed")
                complete(req_id, b"", 13)  # INTERNAL

        async def main() -> None:
            # continuous admission, NOT batch-gather convoys: a straggler
            # (an OIDC discovery fetch, a slow metadata backend) must not
            # block unrelated requests queued behind it — each completion
            # frees an admission slot immediately (the asyncio analog of the
            # reference's per-request goroutines, ref main.go:437-488)
            loop = asyncio.get_running_loop()
            # deep enough to hide the device link RTT under the slow lane's
            # own micro-batches (in-flight ≈ throughput × RTT)
            sem = asyncio.Semaphore(2048)
            # strong refs: asyncio holds tasks weakly — an unreferenced
            # task can be garbage-collected mid-execution
            tasks: set = set()

            def _done(t):
                tasks.discard(t)
                sem.release()

            while self._running:
                batch = await loop.run_in_executor(None, mod.fe_take_slow, 200, 256)
                for i, raw in batch:
                    await sem.acquire()
                    t = loop.create_task(handle(i, raw))
                    tasks.add(t)
                    t.add_done_callback(_done)
            # drain in-flight work before the loop closes: every request
            # taken from the C++ queue MUST complete (asyncio.run would
            # otherwise cancel these tasks and their clients would hang
            # until their gRPC deadlines)
            if tasks:
                await asyncio.gather(*tuple(tasks), return_exceptions=True)

        asyncio.run(main())
