"""Change safety (ISSUE 10): canary snapshot swaps, guard-breach
auto-rollback, and poison-config quarantine.

Strict-verify and translation validation (PRs 4/6) certify that a compiled
snapshot matches the host oracle — but a *semantically valid yet wrong*
AuthConfig (an operator typo that constant-denies a hot host) passes every
compile-time gate and, in the reference reconciler's hot-swap model, serves
100% of traffic the instant the swap lands.  This module is the runtime
side of the blast-radius control the serving stack was missing:

- **canary cohort**: a deterministic hash-fraction of requests
  (``--canary-fraction``) routes to the NEW snapshot generation while the
  rest keeps serving the previous one.  The hash is over stable request
  identity (host|path|method), so a request lands in the same cohort on
  every retry and on every replica — no per-request randomness, no sticky
  state;
- **guards** (:class:`CanaryGuard`): per-cohort deny rates (overall and
  per-authconfig — fed from the PR 9 which-rule-fired attribution fold),
  typed-error rates, and SLO bad-fractions, compared canary vs baseline.
  A breach inside the ``--canary-window`` triggers automatic rollback; a
  clean window promotes to 100%;
- **quarantine** (driven by the engine): on breach, the PR 8 fingerprint
  diff names the configs the reconcile changed and the guard's per-config
  deltas pin the deny spike on specific ones; the reconcile is re-applied
  with only those poison configs reverted to their prior compiled
  artifacts — the rest of the change still lands.

Everything here is per-BATCH work (the same fold cadence as the heat map),
never per-request Python; the state machine itself lives in
``runtime/engine.py``.  See docs/robustness.md "Change safety"."""

from __future__ import annotations

import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import metrics as metrics_mod

__all__ = ["COHORT_BUCKETS", "cohort_bucket", "in_canary_cohort",
           "GuardThresholds", "CanaryGuard", "CanaryPhase",
           "guard_self_test"]

# cohort hash resolution: fraction granularity is 1/10000 (0.01%)
COHORT_BUCKETS = 10000


def cohort_bucket(doc: Any) -> int:
    """Deterministic cohort bucket of one authorization JSON: crc32 over
    the request's stable identity (host|path|method).  The same request —
    retried, re-dispatched, or hitting another replica — always lands in
    the same bucket, so a canary never flaps a client between generations
    mid-session."""
    try:
        req = doc.get("request") or {}
        key = "%s|%s|%s" % (req.get("host", ""),
                            req.get("path") or req.get("url_path", ""),
                            req.get("method", ""))
    except Exception:
        key = ""
    return zlib.crc32(key.encode("utf-8", "replace")) % COHORT_BUCKETS


def in_canary_cohort(doc: Any, fraction: float) -> bool:
    if fraction <= 0.0:
        return False
    if fraction >= 1.0:
        return True
    return cohort_bucket(doc) < int(fraction * COHORT_BUCKETS)


@dataclass
class GuardThresholds:
    """Breach thresholds for the canary guards.  Deltas are ABSOLUTE rate
    differences (canary − baseline): a poison constant-deny pushes a hot
    config's deny-rate delta toward 1.0, far above any honest policy
    change; transient noise on a handful of requests stays below the
    minimum sample counts and can never breach."""

    deny_delta: float = 0.25          # overall deny-rate delta
    config_deny_delta: float = 0.5    # per-authconfig deny-rate delta
    error_delta: float = 0.10         # typed serving-error rate delta
    slo_delta: float = 0.25           # SLO bad-fraction delta
    min_requests: int = 32            # per cohort, for the overall guards
    min_config_requests: int = 16     # per (cohort, authconfig)
    # allow-collapse guard: a config whose canary cohort keeps LESS than
    # this fraction of its baseline allow rate breaches even when the
    # baseline deny rate was already high (where an absolute deny delta
    # saturates — a constant-deny on a 70%-deny config only moves the
    # delta 0.3).  Requires at least min_config_allows baseline allows so
    # an always-denying config can never trip it.
    allow_collapse_ratio: float = 0.5
    min_config_allows: int = 8
    # per-tenant rejection guard (ISSUE 15): a canaried change that pushes
    # its OWN tenant's traffic into tenant-scoped rejections (quota /
    # containment / tenant-aware doomed shedding) at an elevated rate vs
    # the baseline cohort breaches — the per-config deny deltas above see
    # only DECIDED requests, so a change that turns a tenant's traffic
    # into admission rejections would otherwise promote blind.
    tenant_reject_delta: float = 0.25
    min_tenant_attempts: int = 16


class _CohortStats:
    __slots__ = ("total", "denies", "errors", "slo_total", "slo_bad",
                 "configs", "tenant_rejects")

    def __init__(self):
        self.total = 0
        self.denies = 0
        self.errors = 0
        self.slo_total = 0
        self.slo_bad = 0
        # authconfig name -> [requests, denies]
        self.configs: Dict[str, List[int]] = {}
        # tenant (== authconfig) -> tenant-scoped admission rejections
        # (ISSUE 15: quota / containment / tenant-aware doomed shedding)
        self.tenant_rejects: Dict[str, int] = {}

    def to_json(self) -> Dict[str, Any]:
        return {
            "requests": self.total,
            "denies": self.denies,
            "errors": self.errors,
            "slo_observed": self.slo_total,
            "slo_bad": self.slo_bad,
            "configs_seen": len(self.configs),
            "tenant_rejections": sum(self.tenant_rejects.values()),
        }


class CanaryGuard:
    """Per-cohort decision statistics + the breach decision.

    ``observe_batch`` is the hot entry point — one ``np.unique`` fold per
    micro-batch over the SAME (rows, firing) arrays the PR 9 heat-map fold
    already consumes, so attribution and guarding read identical evidence.
    ``breach()`` is rate-limited (at most every ``check_interval_s``) and
    sticky: once breached, it stays breached — the engine's rollback is
    the only exit."""

    def __init__(self, thresholds: Optional[GuardThresholds] = None,
                 check_interval_s: float = 0.1,
                 changed: Optional[set] = None):
        """``changed`` restricts the per-config guards to the configs the
        reconcile actually touched (the PR 8 fingerprint diff's recompile
        set): only a changed config can be poison — its siblings share the
        baseline's literal artifacts — and the cohort hash partitions the
        REQUEST space, so with few distinct requests per config the two
        cohorts sample different fixed doc subsets and an unchanged
        config's rates can differ persistently (selection bias).  None =
        no restriction (the overall guards are never restricted)."""
        self.thresholds = thresholds or GuardThresholds()
        self.changed = set(changed) if changed is not None else None
        self.check_interval_s = float(check_interval_s)
        self._lock = threading.Lock()
        self._baseline = _CohortStats()
        self._canary = _CohortStats()
        self._breach: Optional[Dict[str, Any]] = None
        self._last_check = 0.0
        self._closed = False
        self._g_delta = {
            g: metrics_mod.canary_guard_delta.labels(g)
            for g in ("deny-rate", "config-deny-rate", "error-rate",
                      "slo-bad-rate", "tenant-rejection-rate")}

    def _side(self, canary: bool) -> _CohortStats:
        return self._canary if canary else self._baseline

    # -- feeding (per batch, both lanes) ------------------------------------

    def observe_batch(self, canary: bool, rows, firing, heat,
                      shards=None) -> None:
        """Fold one batch's attribution into the cohort's stats: ``rows``
        are kernel config rows, ``firing`` the per-request firing column
        (−1 = allowed), ``heat`` the snapshot's HeatMap (row → authconfig
        name; both cohorts' corpora name configs identically)."""
        if heat is None or firing is None:
            return
        rows = np.asarray(rows, dtype=np.int64)
        firing = np.asarray(firing, dtype=np.int64)
        if rows.size == 0:
            return
        if shards is not None and getattr(heat, "configs_per_shard", None):
            rows = np.asarray(shards, dtype=np.int64) * \
                heat.configs_per_shard + rows
        denied = firing >= 0
        uniq, inv = np.unique(rows, return_inverse=True)
        tot = np.bincount(inv, minlength=len(uniq))
        den = np.bincount(inv[denied], minlength=len(uniq)) if \
            denied.any() else np.zeros(len(uniq), dtype=np.int64)
        side = self._side(canary)
        with self._lock:
            side.total += int(rows.size)
            side.denies += int(np.count_nonzero(denied))
            for u, t, d in zip(uniq, tot, den):
                name = heat.name(int(u))
                if not name:
                    continue
                st = side.configs.setdefault(name, [0, 0])
                st[0] += int(t)
                st[1] += int(d)

    def observe_counts(self, canary: bool, total: int = 0, denies: int = 0,
                       errors: int = 0, slo_total: int = 0,
                       slo_bad: int = 0, configs=None,
                       tenant_rejects=None) -> None:
        """Count-level cohort feed (ISSUE 18): fold pre-aggregated deltas
        into one cohort's stats.  The fleet aggregator replays each
        replica's published fold deltas through this — the canary
        replica's counts land on the canary side, the rest of the fleet's
        on the baseline side — so ``breach()`` judges GLOBAL deny/error/
        SLO deltas with the exact thresholds, minimum-sample gates, and
        changed-set restriction the in-process canary uses.  ``configs``
        maps authconfig name → (requests, denies); ``tenant_rejects``
        maps tenant → tenant-scoped rejection count."""
        side = self._side(canary)
        with self._lock:
            side.total += max(0, int(total))
            side.denies += max(0, int(denies))
            side.errors += max(0, int(errors))
            side.slo_total += max(0, int(slo_total))
            side.slo_bad += max(0, int(slo_bad))
            for name, td in (configs or {}).items():
                st = side.configs.setdefault(str(name), [0, 0])
                st[0] += max(0, int(td[0]))
                st[1] += max(0, int(td[1]))
            for name, n in (tenant_rejects or {}).items():
                if n > 0:
                    side.tenant_rejects[str(name)] = \
                        side.tenant_rejects.get(str(name), 0) + int(n)

    def observe_errors(self, canary: bool, n: int) -> None:
        """Typed serving errors (UNAVAILABLE-class — deadline sheds and
        overload rejections are the protection mechanism working and stay
        out of the guard, mirroring the SLO tracker's semantics)."""
        if n <= 0:
            return
        side = self._side(canary)
        with self._lock:
            side.errors += int(n)

    def observe_slo(self, canary: bool, n: int, n_bad: int) -> None:
        if n <= 0:
            return
        side = self._side(canary)
        with self._lock:
            side.slo_total += int(n)
            side.slo_bad += int(n_bad)

    def observe_tenant_rejection(self, canary: bool, tenant: str,
                                 n: int = 1) -> None:
        """Tenant-scoped admission rejections (ISSUE 15) — per-tenant
        guard evidence: the changed tenant's cohort must not start eating
        quota/containment rejections the baseline cohort does not."""
        if n <= 0:
            return
        side = self._side(canary)
        with self._lock:
            side.tenant_rejects[tenant] = \
                side.tenant_rejects.get(tenant, 0) + int(n)

    # -- deciding ------------------------------------------------------------

    def breach(self, now: Optional[float] = None,
               force: bool = False) -> Optional[Dict[str, Any]]:
        """The guard verdict: a dict naming the breached guard(s), the
        deltas, and the suspect configs — or None.  Sticky once breached;
        rate-limited between evaluations (the per-config scan is bounded
        by configs SEEN by the cohorts, evaluated on the check cadence,
        never per batch).  ``force`` bypasses the rate limit — the
        window-expiry conclusion must never skip its final evaluation
        just because a per-batch check ran moments earlier."""
        if self._breach is not None:
            return self._breach
        now = time.monotonic() if now is None else now
        if not force and now - self._last_check < self.check_interval_s:
            return None
        self._last_check = now
        th = self.thresholds
        with self._lock:
            b, c = self._baseline, self._canary
            deltas: Dict[str, float] = {}
            breached: List[str] = []
            suspects: List[Tuple[str, float]] = []
            overall_ok = (c.total >= th.min_requests
                          and b.total >= th.min_requests)
            b_rate = (b.denies / b.total) if b.total else 0.0
            if overall_ok:
                deltas["deny-rate"] = c.denies / c.total - b_rate
                if deltas["deny-rate"] > th.deny_delta:
                    breached.append("deny-rate")
            # the error guard counts ATTEMPTED requests (decided +
            # errored), not decided ones: a canary whose batches ALL fail
            # never accumulates decided samples — exactly the generation
            # that must not ride the min-sample gate to a blind promote
            ce_n, be_n = c.total + c.errors, b.total + b.errors
            if ce_n >= th.min_requests and be_n >= th.min_requests:
                deltas["error-rate"] = c.errors / ce_n - b.errors / be_n
                if deltas["error-rate"] > th.error_delta:
                    breached.append("error-rate")
            if (c.slo_total >= th.min_requests
                    and b.slo_total >= th.min_requests):
                deltas["slo-bad-rate"] = (c.slo_bad / c.slo_total
                                          - b.slo_bad / b.slo_total)
                if deltas["slo-bad-rate"] > th.slo_delta:
                    breached.append("slo-bad-rate")
            # per-authconfig guards: the quarantine's attribution
            # evidence, restricted to the CHANGED configs (see __init__).
            # Two criteria: an absolute deny-rate delta, and an
            # allow-collapse ratio (constant-deny on an already-denying
            # config saturates the absolute delta).  The baseline rate
            # falls back to the cohort-wide baseline when the specific
            # config lacks baseline samples — never to 0, so an
            # always-denying config cannot false-breach.
            for name, (ct, cd) in c.configs.items():
                if self.changed is not None and name not in self.changed:
                    continue
                if ct < th.min_config_requests:
                    continue
                bt, bd = b.configs.get(name, (0, 0))
                if bt >= th.min_config_requests:
                    base = bd / bt
                elif b.total >= th.min_requests:
                    bt, bd = b.total, b.denies
                    base = b_rate
                else:
                    continue
                delta = cd / ct - base
                collapsed = (bt - bd >= th.min_config_allows
                             and (ct - cd) / ct
                             < th.allow_collapse_ratio * (bt - bd) / bt)
                if delta > th.config_deny_delta or collapsed:
                    suspects.append((name, delta))
            if suspects:
                breached.append("config-deny-rate")
                deltas["config-deny-rate"] = max(d for _, d in suspects)
            # per-TENANT rejection guard (ISSUE 15): the changed tenant's
            # cohort specifically — PR 10's per-config deny deltas see only
            # decided requests; a change that converts its tenant's traffic
            # into tenant-scoped admission rejections (quota, containment,
            # tenant-aware doomed shedding) must breach here instead of
            # promoting blind.  Attempts = decided + rejected per tenant.
            t_suspects: List[Tuple[str, float]] = []
            for name in set(c.tenant_rejects) | set(b.tenant_rejects):
                if self.changed is not None and name not in self.changed:
                    continue
                ct, _cd = c.configs.get(name, (0, 0))
                bt, _bd = b.configs.get(name, (0, 0))
                cr = c.tenant_rejects.get(name, 0)
                br = b.tenant_rejects.get(name, 0)
                c_att, b_att = ct + cr, bt + br
                if (c_att < th.min_tenant_attempts
                        or b_att < th.min_tenant_attempts):
                    continue
                t_delta = cr / c_att - br / b_att
                if t_delta > th.tenant_reject_delta:
                    t_suspects.append((name, t_delta))
            if t_suspects:
                breached.append("tenant-rejection-rate")
                deltas["tenant-rejection-rate"] = max(
                    d for _, d in t_suspects)
                suspects.extend(t_suspects)
        if not self._closed:
            for g, child in self._g_delta.items():
                if g in deltas:
                    child.set(deltas[g])
        if not breached:
            return None
        suspects.sort(key=lambda x: -x[1])
        self._breach = {
            "guards": breached,
            "deltas": {k: round(v, 4) for k, v in deltas.items()},
            "suspects": [name for name, _ in suspects],
            "suspect_deltas": {name: round(d, 4) for name, d in suspects},
            "baseline": self._baseline.to_json(),
            "canary": self._canary.to_json(),
        }
        return self._breach

    def close(self) -> None:
        """Canary concluded (promote or rollback): zero the live delta
        gauges — they are documented as the deltas of the canary IN
        PROGRESS, and a breach-level value lingering after the rollback
        already handled it keeps dashboards and alerts firing."""
        self._closed = True
        for child in self._g_delta.values():
            child.set(0.0)

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "thresholds": {
                    "deny_delta": self.thresholds.deny_delta,
                    "config_deny_delta": self.thresholds.config_deny_delta,
                    "error_delta": self.thresholds.error_delta,
                    "slo_delta": self.thresholds.slo_delta,
                    "min_requests": self.thresholds.min_requests,
                    "min_config_requests":
                        self.thresholds.min_config_requests,
                    "allow_collapse_ratio":
                        self.thresholds.allow_collapse_ratio,
                    "min_config_allows": self.thresholds.min_config_allows,
                },
                "changed_watched": (sorted(self.changed)[:32]
                                    if self.changed is not None else None),
                "baseline": self._baseline.to_json(),
                "canary": self._canary.to_json(),
            }
        out["breach"] = self._breach
        return out


class CanaryPhase:
    """One in-progress canary swap: the candidate snapshot, the baseline it
    canaries against (both pinned — rollback is a pointer swap), the
    reconcile's entries (the quarantine re-apply input), both host indexes,
    and the guard.  Transitions (promote / rollback) are owned by the
    engine under its swap lock; this object only carries state + the
    window timer."""

    def __init__(self, snap, baseline, entries, index, baseline_index,
                 fraction: float, window_s: float,
                 guard: Optional[CanaryGuard] = None,
                 preflight: Optional[Dict[str, Any]] = None):
        self.snap = snap
        self.baseline = baseline
        self.entries = list(entries)
        self.index = index
        self.baseline_index = baseline_index
        self.fraction = float(fraction)
        self.window_s = float(window_s)
        self.guard = guard or CanaryGuard()
        # replay preflight summary (ISSUE 13): a candidate that survived
        # the pregate carries the evidence here — /debug/canary shows it,
        # and the engine tightened this phase's guard thresholds when the
        # preflighted diff was clean
        self.preflight = preflight
        # kernel cost stamp (ISSUE 16): the reconcile's modeled-cost
        # record — a canaried swap whose per-row cost regressed >=2x
        # carries the evidence on /debug/canary and the bench artifact
        self.kernel_cost: Optional[Dict[str, Any]] = None
        self.t_start = time.monotonic()
        self.started_unix = time.time()
        self._timer: Optional[threading.Timer] = None

    def in_cohort(self, doc: Any) -> bool:
        return in_canary_cohort(doc, self.fraction)

    def expired(self) -> bool:
        return time.monotonic() - self.t_start >= self.window_s

    def start_timer(self, conclude) -> None:
        """Arm the window-expiry timer: promotion must not wait for
        traffic (an idle canary with no breach evidence promotes at the
        window end, like a clean one)."""
        t = threading.Timer(self.window_s, conclude)
        t.daemon = True
        t.name = "atpu-canary-window"
        self._timer = t
        t.start()

    def cancel_timer(self) -> None:
        t = self._timer
        if t is not None:
            t.cancel()
            self._timer = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "generation": getattr(self.snap, "generation", None),
            "baseline_generation": getattr(self.baseline, "generation",
                                           None),
            "fraction": self.fraction,
            "window_s": self.window_s,
            "age_s": round(time.monotonic() - self.t_start, 3),
            "started_unix": self.started_unix,
            "guard": self.guard.to_json(),
            "preflight": self.preflight,
            "kernel_cost": self.kernel_cost,
        }


# ---------------------------------------------------------------------------
# self-test (analysis --verify-fixtures + tier-1): a blind guard is itself
# a failure — a planted constant-deny poison MUST breach, a clean churn
# MUST stay clean (and therefore promote at the window end)
# ---------------------------------------------------------------------------


class _StubHeat:
    configs_per_shard = None

    def __init__(self, names):
        self._names = list(names)

    def name(self, row: int, shard=None) -> str:
        return self._names[row] if 0 <= row < len(self._names) else ""


def _feed(guard: CanaryGuard, canary: bool, heat, row: int, n: int,
          deny_frac: float) -> None:
    rows = np.full(n, row, dtype=np.int64)
    firing = np.full(n, -1, dtype=np.int64)
    firing[: int(n * deny_frac)] = 0
    guard.observe_batch(canary, rows, firing, heat)


def guard_self_test() -> List[str]:
    """Prove the guard can still see: (a) a planted constant-deny poison
    on one config breaches with that config named as the suspect; (b) an
    identical-rate clean churn does NOT breach (it would promote).  Run by
    ``python -m authorino_tpu.analysis --verify-fixtures`` and pinned by
    tier-1 — a blind or trigger-happy guard fails both."""
    errors: List[str] = []
    heat = _StubHeat(["cfg-clean", "cfg-poison"])

    clean = CanaryGuard(check_interval_s=0.0)
    for _ in range(4):
        _feed(clean, False, heat, 0, 64, 0.10)
        _feed(clean, True, heat, 0, 64, 0.10)
        _feed(clean, False, heat, 1, 64, 0.05)
        _feed(clean, True, heat, 1, 64, 0.05)
    if clean.breach() is not None:
        errors.append("guard breached on a CLEAN churn (identical deny "
                      f"rates both cohorts): {clean.breach()}")

    poisoned = CanaryGuard(check_interval_s=0.0)
    for _ in range(4):
        _feed(poisoned, False, heat, 0, 64, 0.10)
        _feed(poisoned, True, heat, 0, 64, 0.10)
        _feed(poisoned, False, heat, 1, 64, 0.05)
        _feed(poisoned, True, heat, 1, 64, 1.00)  # constant-deny poison
    b = poisoned.breach()
    if b is None:
        errors.append("guard BLIND: a planted constant-deny poison config "
                      "did not breach inside the window")
    elif "cfg-poison" not in b.get("suspects", []):
        errors.append("guard failed to pin the deny spike on the poison "
                      f"config (suspects={b.get('suspects')})")
    elif "cfg-clean" in b.get("suspects", []):
        errors.append("guard mis-attributed the poison to a clean config")

    # allow-collapse: a constant-deny on a config whose baseline ALREADY
    # denied 70% moves the absolute delta only 0.3 — the collapse ratio
    # (canary kept <50% of the baseline allow rate) must still breach
    collapse = CanaryGuard(check_interval_s=0.0)
    for _ in range(4):
        _feed(collapse, False, heat, 1, 64, 0.70)
        _feed(collapse, True, heat, 1, 64, 1.00)
    bc = collapse.breach()
    if bc is None or "cfg-poison" not in bc.get("suspects", []):
        errors.append("guard BLIND to constant-deny on a high-baseline-"
                      f"deny config (allow collapse): {bc}")

    # changed-set restriction: cohort selection bias on an UNCHANGED
    # config (the cohorts sample different fixed request subsets) must
    # not breach when the guard knows what the reconcile touched
    biased = CanaryGuard(check_interval_s=0.0, changed={"cfg-poison"})
    for _ in range(8):  # bulk balanced traffic on the changed config
        _feed(biased, False, heat, 1, 64, 0.10)
        _feed(biased, True, heat, 1, 64, 0.10)
    _feed(biased, False, heat, 0, 64, 0.10)
    _feed(biased, True, heat, 0, 64, 0.90)  # unchanged + cohort-biased
    if biased.breach() is not None:
        errors.append("guard breached on an UNCHANGED config (the changed-"
                      "set restriction is not applied): "
                      f"{biased.breach()}")

    # determinism of the cohort hash: same doc, same cohort, always
    doc = {"request": {"host": "h", "path": "/a", "method": "GET"}}
    if cohort_bucket(doc) != cohort_bucket(dict(doc)):
        errors.append("cohort hash is not deterministic over equal docs")
    if in_canary_cohort(doc, 1.0) is not True or \
            in_canary_cohort(doc, 0.0) is not False:
        errors.append("cohort fraction bounds broken (0.0 must exclude, "
                      "1.0 must include)")
    return errors
