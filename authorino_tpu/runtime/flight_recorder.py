"""Black-box flight recorder (ISSUE 9): a bounded process-wide ring of
serving-lifecycle events — circuit-breaker transitions, watchdog fires,
snapshot swaps/rejections, admission state flips, reconcile phases, drain —
that auto-dumps a diagnostic bundle when an anomaly fires.

The aviation model: the ring records continuously at negligible cost (one
deque append per event; events are per-incident, never per-request), and an
anomaly trigger — breaker OPEN, watchdog timeout, snapshot rejection,
admission OVERLOADED — freezes the evidence by writing a bundle containing
the event trail, every registered component's /debug/vars snapshot, and the
full Prometheus exposition, to ``--flight-dir``.  Incident forensics then
start from the bundle (``python -m authorino_tpu.analysis --flight-dump``),
not from whatever the process happened to log.

Recording hooks live in runtime/breaker.py, runtime/admission.py,
runtime/engine.py and runtime/native_frontend.py; everything here is
fail-safe — a recorder bug must never take down the serving path, so every
public entry point swallows its own exceptions after logging.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..utils import metrics as metrics_mod
from ..utils.atomicio import atomic_write_json

__all__ = ["FlightRecorder", "RECORDER", "BUNDLE_SCHEMA"]

log = logging.getLogger("authorino_tpu.flight_recorder")

# bundle schema version: bumped whenever the bundle layout changes, so the
# analysis CLI can refuse bundles it does not understand
BUNDLE_SCHEMA = 1

# event kinds that trigger an auto-dump (the anomaly set); every other kind
# only rides the ring as context
ANOMALY_KINDS = frozenset({
    "breaker-open", "watchdog-timeout", "snapshot-rejected",
    "admission-overloaded", "snapshot-rollback",
    # ISSUE 13: a reconcile whose replay preflight breached — the bundle
    # freezes the top-N verdict-diff rows (attributed flips) as evidence
    "replay-pregate-breach",
    # ISSUE 19: a reconcile whose CORPUS preflight breached — same evidence
    # shape, but the flips may be synthetic-origin rows (a rule no live
    # traffic ever exercised), which is exactly the zero-exposure catch
    "corpus-pregate-breach",
    # ISSUE 15: the noisy-neighbor detector CONTAINED a tenant (tenant-
    # scoped brownout/shed) — the bundle freezes the per-tenant shares,
    # weights and wait state that justified the clamp.  The auto-release
    # (kind `tenant-released`) rides the ring as context only.
    "tenant-contained",
    # ISSUE 16: a reconcile whose XLA-modeled per-row kernel cost regressed
    # >=2x vs the previous generation (advisory — the swap still lands; the
    # bundle freezes the modeled flops/bytes diff per entry point)
    "cost-regression",
    # ISSUE 20: a warm restart served the state-dir snapshot past its
    # --max-snapshot-age bound — fail-static by design (old verdicts beat
    # no verdicts), but the bundle freezes the age/generation evidence and
    # /readyz degrades until a live control-plane snapshot lands
    "stale-snapshot",
})


class FlightRecorder:
    """Bounded event ring + anomaly-triggered bundle dumps.

    ``record()`` is the hot entry point: deque append + one counter inc,
    safe from any thread (including under the breaker's lock).  Dumps run
    on their own daemon thread and are rate-limited (``min_dump_interval_s``
    between bundles) so a flapping breaker cannot turn the recorder into a
    disk-filling amplifier."""

    def __init__(self, capacity: int = 512, dump_dir: Optional[str] = None,
                 min_dump_interval_s: float = 30.0, enabled: bool = True,
                 keep: int = 16):
        self.capacity = max(16, int(capacity))
        # on-disk bundle retention (ISSUE 10 satellite): --flight-dir used
        # to grow without limit across anomalies — a flapping lane on a
        # long-lived pod would slowly fill the disk with bundles nobody
        # read.  Only the newest ``keep`` bundles survive each dump.
        self.keep = max(1, int(keep))
        self._ring: deque = deque(maxlen=self.capacity)
        # guards ring append vs snapshot: record() fires from any thread
        # (breaker/admission hooks) while the dump thread lists the ring —
        # an unguarded list(deque) under concurrent appends raises, and a
        # swallowed raise there silently loses the incident's bundle
        self._ring_lock = threading.Lock()
        self.enabled = bool(enabled)
        self.dump_dir = dump_dir or os.environ.get(
            "AUTHORINO_TPU_FLIGHT_DIR",
            os.path.join(tempfile.gettempdir(), "authorino-tpu-flight"))
        self.min_dump_interval_s = float(min_dump_interval_s)
        self._last_dump = 0.0
        self._dump_lock = threading.Lock()
        # registered context providers: name -> weakref'd zero-arg callable
        # returning a JSON-safe dict (engine.debug_vars, fe.debug_vars).
        # Weak by owner: engines are created freely in tests/reconciles and
        # a strong ref here would leak every one of them.
        self._providers: Dict[str, Any] = {}
        self._provider_lock = threading.Lock()
        self.events_total = 0
        self.dumps: List[str] = []  # bundle paths written this process

    # -- configuration -----------------------------------------------------

    def configure(self, dump_dir: Optional[str] = None,
                  capacity: Optional[int] = None,
                  min_dump_interval_s: Optional[float] = None,
                  enabled: Optional[bool] = None,
                  keep: Optional[int] = None) -> None:
        if dump_dir is not None:
            self.dump_dir = dump_dir
        if capacity is not None and int(capacity) != self.capacity:
            self.capacity = max(16, int(capacity))
            with self._ring_lock:
                self._ring = deque(self._ring, maxlen=self.capacity)
        if min_dump_interval_s is not None:
            self.min_dump_interval_s = float(min_dump_interval_s)
        if enabled is not None:
            self.enabled = bool(enabled)
        if keep is not None:
            self.keep = max(1, int(keep))

    def register_provider(self, name: str, owner: Any,
                          method: str = "debug_vars") -> None:
        """Register ``owner.<method>()`` as a context provider for bundles.
        Held weakly; a later registration under the same name wins (the
        latest engine is the serving one)."""
        with self._provider_lock:
            self._providers[name] = (weakref.ref(owner), method)

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, lane: str = "", detail: Any = None,
               anomaly: Optional[bool] = None) -> None:
        """Append one lifecycle event; auto-dump when it is an anomaly
        (``kind in ANOMALY_KINDS``, overridable via ``anomaly=``)."""
        if not self.enabled:
            return
        try:
            with self._ring_lock:
                self._ring.append({
                    "t": time.time(), "kind": kind, "lane": lane,
                    "detail": detail,
                })
                self.events_total += 1
            metrics_mod.flight_events.labels(kind).inc()
            if anomaly if anomaly is not None else kind in ANOMALY_KINDS:
                self._schedule_dump(kind)
        except Exception:
            log.exception("flight-recorder record failed (serving unaffected)")

    # -- dumping -----------------------------------------------------------

    def _schedule_dump(self, trigger: str) -> None:
        now = time.monotonic()
        with self._dump_lock:
            if now - self._last_dump < self.min_dump_interval_s:
                return
            self._last_dump = now
        t = threading.Thread(target=self._dump_safe, args=(trigger,),
                             name="atpu-flight-dump", daemon=True)
        t.start()

    def _dump_safe(self, trigger: str) -> None:
        try:
            self.dump(trigger)
        except Exception:
            log.exception("flight-recorder dump failed (serving unaffected)")

    def _gather_vars(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        with self._provider_lock:
            items = list(self._providers.items())
        dead = []
        for name, (ref, method) in items:
            owner = ref()
            if owner is None:
                dead.append(name)
                continue
            try:
                out[name] = getattr(owner, method)()
            except Exception as e:
                out[name] = {"error": repr(e)}
        if dead:
            with self._provider_lock:
                for name in dead:
                    self._providers.pop(name, None)
        return out

    def bundle(self, trigger: str) -> Dict[str, Any]:
        """The diagnostic bundle as a dict: the event trail, every live
        provider's debug-vars snapshot, and the Prometheus exposition."""
        try:
            from prometheus_client import generate_latest

            metrics_text = generate_latest().decode("utf-8", "replace")
        except Exception:
            metrics_text = ""
        with self._ring_lock:
            events = list(self._ring)
        return {
            "schema": BUNDLE_SCHEMA,
            "kind": "authorino-tpu-flight-bundle",
            "trigger": trigger,
            "t": time.time(),
            "pid": os.getpid(),
            "events": events,
            "vars": self._gather_vars(),
            "metrics": metrics_text,
        }

    def dump(self, trigger: str) -> str:
        """Write one bundle to ``dump_dir`` and return its path (also
        counted in auth_server_flight_recorder_dumps_total{trigger})."""
        bundle = self.bundle(trigger)
        os.makedirs(self.dump_dir, exist_ok=True)
        fname = "flight-%d-%s-%d.json" % (
            int(bundle["t"]), trigger.replace("/", "_"), os.getpid())
        path = os.path.join(self.dump_dir, fname)
        # shared atomic writer (ISSUE 20): the old inline tmp+replace here
        # skipped fsync, so a crash could surface a zero-length bundle
        atomic_write_json(path, bundle, artifact="flight", default=str)
        metrics_mod.flight_dumps.labels(trigger).inc()
        self.dumps.append(path)
        del self.dumps[:-32]
        self._prune_disk()
        log.warning("flight recorder dumped diagnostic bundle (%s): %s",
                    trigger, path)
        return path

    def _prune_disk(self) -> None:
        """Bounded on-disk retention: keep only the newest ``keep``
        bundles in dump_dir (by mtime).  Best-effort — a prune failure
        must never lose the bundle that was just written."""
        try:
            names = [n for n in os.listdir(self.dump_dir)
                     if n.startswith("flight-") and n.endswith(".json")]
            if len(names) <= self.keep:
                return
            names.sort(key=lambda n: os.path.getmtime(
                os.path.join(self.dump_dir, n)))
            for n in names[:-self.keep]:
                try:
                    os.unlink(os.path.join(self.dump_dir, n))
                except OSError:
                    pass
        except OSError:
            pass

    # -- introspection -----------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        with self._ring_lock:
            depth, tail = len(self._ring), list(self._ring)[-16:]
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "events_recorded": self.events_total,
            "ring_depth": depth,
            "dump_dir": self.dump_dir,
            "keep": self.keep,
            "min_dump_interval_s": self.min_dump_interval_s,
            "dumps": list(self.dumps),
            "tail": tail,
        }


# the process-wide recorder every hook reports into (one black box per
# process, like one breaker trail per lane)
def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


RECORDER = FlightRecorder(
    enabled=os.environ.get("AUTHORINO_TPU_FLIGHT_RECORDER", "1").lower()
    not in ("0", "false", "no"),
    keep=_env_int("AUTHORINO_TPU_FLIGHT_KEEP", 16))
