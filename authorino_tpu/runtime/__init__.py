"""Serving runtime: compiled-corpus engine + micro-batching dispatch."""

from .engine import EngineEntry, PolicyEngine  # noqa: F401
