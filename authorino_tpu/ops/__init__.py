"""TPU kernels: batched policy evaluation (pure JAX/XLA; Pallas variants live
in ops/pallas_kernels.py as they land)."""

from .pattern_eval import eval_batch_jit, eval_verdicts, to_device  # noqa: F401
