"""Fused mega-kernel: the whole hot path in ONE launch (ISSUE 17).

The serving snapshot's batch today crosses several jitted calls on the
unfused path (leaf compares, the DFA byte scan, the value lanes, the
circuit, the bitpack) and the DFA lane gathers through the compile-order
row map.  This module is the paper's "one vmapped (requests x rules)
kernel" taken seriously:

  - ``_eval_verdicts_fused`` is the gather lane re-plumbed onto the fused
    operand layout: op codes travel int8 (all codes < 2^7, see
    compiler/compile.py OP_*), and the DFA transition arrays are re-keyed
    by ``CompiledPolicy.dfa_row_perm`` — rows grouped by owning table
    (``dfa_table_of_row`` nondecreasing after the permutation) so per-byte
    transition gathers walk the deduped table axis sequentially instead of
    hopping through compile order.
  - ``_fused_packed`` finishes the batch IN-KERNEL: own-config selection,
    the [B, 1+2E] attribution concat, and the little-endian bitpack are
    inlined (no separate ``_bitpack_rows`` launch) so the kernel's only
    output is the [B, W] uint8 readback.
  - ``dispatch_megakernel`` wraps the whole thing in ONE launch: a Pallas
    kernel on a real TPU backend, ``pl.pallas_call(..., interpret=True)``
    on this CPU image (bit-exact, so tier-1 pins parity), and a single-jit
    lax fallback when Pallas is unavailable.  Either way the PR 16 ledger
    sees ``launches_per_batch == 1.0``.
  - ``dispatch_staged`` is the honest UNFUSED baseline: the same math cut
    into per-stage jits (leaves / DFA / value lanes / circuit / bitpack),
    each its own launch, bit-exact with the fused result — what
    ``bench_micro --kernel-cost-grid``'s fused-vs-unfused column and the
    perf_guard launch-count proof compare against.
  - ``occupancy_pad`` shapes the mesh batch pad from per-shard occupancy
    (the PR 11 grid's dp replication) instead of the global cut size.

Lane selection: ``to_device(..., lane="fused")`` or the
``AUTHORINO_TPU_KERNEL_LANE`` env mirror of ``--kernel-lane``; ``auto``
arms fused only on a real TPU backend (interpret-mode Pallas is an
emulation, correct but slow — docs/performance.md "Fused mega-kernel").
"""

from __future__ import annotations

import os
from functools import partial
from types import SimpleNamespace
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import pattern_eval as pe
from ..compiler.compile import DFA_VALUE_BYTES, CompiledPolicy

__all__ = [
    "fused_operands", "eval_fused_kernel", "dispatch_megakernel",
    "dispatch_staged", "staged_launches", "fused_kernel_supported",
    "prewarm_fused", "occupancy_pad",
]


def _kernel_lane() -> str:
    """Env mirror of ``--kernel-lane`` (cli.py): fused|gather|matmul|auto."""
    return os.environ.get("AUTHORINO_TPU_KERNEL_LANE", "auto")


# ---------------------------------------------------------------------------
# fused operand layout (int8 ops, table-grouped DFA rows)
# ---------------------------------------------------------------------------


def fused_operands(policy: CompiledPolicy, dfa_byte_slot: np.ndarray) -> dict:
    """The ``params["fused"]`` subtree, host-side numpy (``to_device``
    applies its own ``put``).  Grouped arrays are the gather lane's DFA
    operands composed with ``policy.dfa_row_perm``; ``leaf_dfa_pos`` is the
    leaf's row position AFTER grouping (inverse permutation composed with
    ``leaf_dfa_row``) so leaf gathers land on the re-keyed axis."""
    fz = {"leaf_op_i8": np.asarray(policy.leaf_op_i8)}
    if policy.n_byte_attrs:
        perm = np.asarray(policy.dfa_row_perm)
        inv = np.empty_like(perm)
        inv[perm] = np.arange(perm.shape[0], dtype=np.int32)
        fz["dfa_table_of_row_g"] = policy.dfa_table_of_row[perm]
        fz["dfa_byte_slot_g"] = dfa_byte_slot.astype(np.int32)[perm]
        fz["leaf_dfa_pos"] = inv[policy.leaf_dfa_row].astype(np.int32)
    return fz


def _eval_verdicts_fused(params, attrs_val, members_c, cpu_dense,
                         attr_bytes=None, byte_ovf=None, attrs_num=None,
                         num_valid=None, rel_rows=None, member_ovf=None):
    """Gather-lane semantics on the fused layout.  Differences from
    ``pe._eval_verdicts_gather`` are exactly the layout: int8 op codes
    (upcast once on device), table-grouped DFA row arrays, and a
    ``fori_loop`` byte scan (the loop form Pallas kernels lower best)."""
    fz = params["fused"]
    if attrs_val.dtype != jnp.int32:
        attrs_val = attrs_val.astype(jnp.int32)
    if members_c.dtype != jnp.int32:
        members_c = members_c.astype(jnp.int32)
    leaf_op = fz["leaf_op_i8"].astype(jnp.int32)
    leaf_const = params["leaf_const"]
    B = attrs_val.shape[0]

    val = jnp.take(attrs_val, params["leaf_attr"], axis=1)          # [B, L]
    eq = val == leaf_const[None, :]
    memb = jnp.take(members_c, params["member_slot_of_leaf"], axis=1)
    incl = jnp.any(memb == leaf_const[None, :, None], axis=-1)
    cpu_lane = pe._cpu_full(params, cpu_dense)

    if params["dfa_tables"] is not None and attr_bytes is not None:
        tables = params["dfa_tables"]            # [T, S, 256] uint8 (deduped)
        # grouped layout: tab_idx nondecreasing, so each scan step's table
        # gathers are sequential along the deduped table axis
        tab_idx = fz["dfa_table_of_row_g"][None, :]                  # [1, R]
        row_bytes = jnp.take(attr_bytes, fz["dfa_byte_slot_g"], axis=1)
        LB = row_bytes.shape[2]
        # init derived from a varying input (zero-multiplied) so its
        # manual-mesh "varying" type matches inside shard_map
        init = (row_bytes[:, :, 0] * 0).astype(jnp.int32)

        def dfa_step(i, states):
            byte_col = jax.lax.dynamic_index_in_dim(
                row_bytes, i, axis=2, keepdims=False)
            return tables[tab_idx, states, byte_col.astype(jnp.int32)].astype(
                jnp.int32)

        final = jax.lax.fori_loop(0, LB, dfa_step, init)
        dfa_row_res = params["dfa_accept"][tab_idx, final]           # [B, R]
        leaf_dfa = jnp.take(dfa_row_res, fz["leaf_dfa_pos"], axis=1)
        leaf_slot = jnp.take(fz["dfa_byte_slot_g"], fz["leaf_dfa_pos"])
        leaf_bovf = jnp.take(byte_ovf, leaf_slot, axis=1)
        dfa_leaf_val = jnp.where(leaf_bovf, cpu_lane, leaf_dfa)
    else:
        dfa_leaf_val = cpu_lane  # regexes ride the CPU lane entirely

    num_cmp = None
    if params.get("leaf_num_slot") is not None and attrs_num is not None:
        lv = jnp.take(attrs_num, params["leaf_num_slot"], axis=1)
        lok = jnp.take(num_valid, params["leaf_num_slot"], axis=1)
        ic = leaf_const[None, :]
        num_cmp = (lok & (lv > ic), lok & (lv >= ic),
                   lok & (lv < ic), lok & (lv <= ic))

    rel_res = None
    if params.get("rel_bits") is not None and rel_rows is not None:
        rows_l = jnp.take(rel_rows, params["leaf_rel_slot"], axis=1)
        col = params["leaf_rel_col"]
        byte = params["rel_bits"][rows_l, (col >> 3)[None, :]].astype(
            jnp.int32)
        rel_res = ((byte >> (col & 7)[None, :]) & 1) != 0

    leaf_movf = None
    if member_ovf is not None:
        leaf_movf = jnp.take(member_ovf, params["member_slot_of_leaf"],
                             axis=1)

    res = pe._leaf_op_cascade(leaf_op, eq, incl, dfa_leaf_val, cpu_lane,
                              num_cmp, rel_res, leaf_movf)

    true_col = jnp.ones((B, 1), dtype=bool)
    false_col = jnp.zeros((B, 1), dtype=bool)
    buffer = jnp.concatenate([true_col, false_col, res], axis=1)
    for children, is_and in params["levels"]:
        ch = jnp.take(buffer, children.reshape(-1), axis=1)
        ch = ch.reshape(B, children.shape[0], children.shape[1])
        node = jnp.where(is_and[None, :], jnp.all(ch, axis=-1),
                         jnp.any(ch, axis=-1))
        buffer = jnp.concatenate([buffer, node], axis=1)

    cond = jnp.take(buffer, params["eval_cond"].reshape(-1), axis=1)
    rule = jnp.take(buffer, params["eval_rule"].reshape(-1), axis=1)
    G, E = params["eval_rule"].shape
    return pe._verdict_from_tables(
        params, cond.reshape(B, G, E), rule.reshape(B, G, E))


def _fused_packed(params, ops: dict):
    """The whole batch in one traced body: verdicts + attribution + the
    IN-KERNEL bitpack.  ``ops`` is the operand dict a ``pe._defuse`` (or
    the per-operand staging) produces; absent lanes are absent keys."""
    verdict, (rule, skipped) = _eval_verdicts_fused(
        params, ops["attrs_val"], ops["members_c"], ops["cpu_dense"],
        ops.get("attr_bytes"), ops.get("byte_ovf"), ops.get("attrs_num"),
        ops.get("num_valid"), ops.get("rel_rows"), ops.get("member_ovf"))
    own_mask = pe._select_own(ops["config_id"], verdict.shape[1])
    own = jnp.any(verdict & own_mask, axis=1)
    own_rule = jnp.any(rule & own_mask[:, :, None], axis=1)
    own_skipped = jnp.any(skipped & own_mask[:, :, None], axis=1)
    cols = jnp.concatenate([own[:, None], own_rule, own_skipped], axis=1)
    # inline little-endian bitpack — same contract as pe._bitpack_rows, but
    # produced inside the one launch so the kernel's only output is the
    # [B, W] uint8 readback (W == CompiledPolicy.fused_pack_w)
    B, C = cols.shape
    W = pe.packed_width(C)
    padded = jnp.zeros((B, W * 8), dtype=bool).at[:, :C].set(cols)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, None, :]
    return (padded.reshape(B, W, 8).astype(jnp.int32) * weights).sum(
        axis=-1).astype(jnp.uint8)


# ---------------------------------------------------------------------------
# the one launch: Pallas kernel (interpret on CPU) / single-jit lax fallback
# ---------------------------------------------------------------------------


def _pallas_wrap(params, ops: dict, extra_flat=None, defuse_layout=None):
    """Run ``_fused_packed`` as ONE ``pl.pallas_call``.  Params + operands
    tree-flatten into the kernel's refs (bool leaves cross as uint8 — Pallas
    I/O is numeric — and are restored inside); with ``defuse_layout`` the
    LAST input is the fused staging buffer and the operand decode happens
    inside the kernel too, so the launch consumes the raw H2D bytes."""
    from jax.experimental import pallas as pl

    tree = (params, ops)
    flat, treedef = jax.tree_util.tree_flatten(tree)
    bool_ix = frozenset(
        i for i, a in enumerate(flat)
        if np.dtype(a.dtype) == np.dtype(bool))
    cast = [a.astype(jnp.uint8) if i in bool_ix else a
            for i, a in enumerate(flat)]
    tail = list(extra_flat) if extra_flat is not None else []
    if defuse_layout is not None:
        B = next(s[0] for n, d, s, o, z in defuse_layout if n == "attrs_val")
    else:
        B = ops["attrs_val"].shape[0]
    W = pe.packed_width(1 + 2 * params["eval_rule"].shape[1])

    def kernel(*refs):
        *in_refs, out_ref = refs
        vals = [r[...] for r in in_refs]
        leaves = [(v != 0) if i in bool_ix else v
                  for i, v in enumerate(vals[:len(flat)])]
        p, o = jax.tree_util.tree_unflatten(treedef, leaves)
        if defuse_layout is not None:
            o = pe._defuse(vals[-1], defuse_layout)
        out_ref[...] = _fused_packed(p, o)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B, W), jnp.uint8),
        interpret=jax.default_backend() != "tpu",
    )(*cast, *tail)


_PALLAS_OK: Optional[bool] = None


def fused_kernel_supported() -> bool:
    """One-time probe that a tiny Pallas kernel (interpret-mode off-TPU)
    round-trips on this backend; the dispatcher degrades to the single-jit
    lax fallback — never to more launches — when it does not."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            from jax.experimental import pallas as pl

            def k(x_ref, o_ref):
                o_ref[...] = x_ref[...] + 1

            got = pl.pallas_call(
                k, out_shape=jax.ShapeDtypeStruct((4,), jnp.int32),
                interpret=jax.default_backend() != "tpu",
            )(jnp.arange(4, dtype=jnp.int32))
            _PALLAS_OK = bool(
                np.array_equal(np.asarray(got), np.arange(4) + 1))
        except Exception:
            _PALLAS_OK = False
    return _PALLAS_OK


@partial(jax.jit, static_argnames=("layout", "use_pallas"))
def _fused_buf_jit(params, buf, layout, use_pallas):
    """ONE launch over the fused H2D staging buffer: operand decode, every
    lane, the circuit, and the bitpack in a single executable."""
    if use_pallas:
        return _pallas_wrap(params, {}, extra_flat=(buf,),
                            defuse_layout=layout)
    return _fused_packed(params, pe._defuse(buf, layout))


@partial(jax.jit, static_argnames=("use_pallas",))
def _fused_ops_jit(params, attrs_val, members_c, cpu_dense, config_id,
                   attr_bytes, byte_ovf, attrs_num, num_valid, rel_rows,
                   member_ovf, use_pallas):
    """Per-operand-transfer variant of the one launch (big-endian hosts
    where the fused H2D bitcast probe fails, and the zero-operand warm)."""
    ops = {"attrs_val": attrs_val, "members_c": members_c,
           "cpu_dense": cpu_dense, "config_id": config_id}
    for name, a in (("attr_bytes", attr_bytes), ("byte_ovf", byte_ovf),
                    ("attrs_num", attrs_num), ("num_valid", num_valid),
                    ("rel_rows", rel_rows), ("member_ovf", member_ovf)):
        if a is not None:
            ops[name] = a
    if use_pallas:
        return _pallas_wrap(params, ops)
    return _fused_packed(params, ops)


def eval_fused_kernel(params, db) -> "jax.Array":
    """One compact batch through the mega-kernel; returns the on-device
    [B, W] uint8 bitpacked readback (decode with ``pe.unpack_verdicts``)."""
    use_pallas = fused_kernel_supported()
    if pe.fused_h2d_supported():
        buf, layout = pe.fuse_batch(db)
        return _fused_buf_jit(params, jnp.asarray(buf), layout, use_pallas)
    has_dfa = params["dfa_tables"] is not None
    return _fused_ops_jit(
        params,
        jnp.asarray(db.attrs_val),
        jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense),
        jnp.asarray(db.config_id),
        jnp.asarray(db.attr_bytes) if has_dfa else None,
        jnp.asarray(db.byte_ovf) if has_dfa else None,
        *pe._extra_operands(db),
        use_pallas=use_pallas,
    )


def dispatch_megakernel(params, db) -> "jax.Array":
    """Non-blocking mega-kernel launch (the fused-lane twin of
    ``pe.dispatch_fused``'s unfused body): eager D2H copy start, one launch
    on the ledger either way."""
    out = eval_fused_kernel(params, db)
    try:
        out.copy_to_host_async()
    except Exception:
        pass  # readback degrades to a blocking copy at np.asarray time
    return out


# ---------------------------------------------------------------------------
# staged baseline: the same math cut into per-stage launches
# ---------------------------------------------------------------------------
#
# The honest pre-fusion shape of the hot path for the ledger/bench
# comparison: each stage is its own jit (its own launch + inter-stage
# device round trips stay on device, but the LAUNCH count is real).
# Bit-exact with the fused result — tests pin it.


@jax.jit
def _stage_leaves(params, attrs_val, members_c, cpu_dense):
    if attrs_val.dtype != jnp.int32:
        attrs_val = attrs_val.astype(jnp.int32)
    if members_c.dtype != jnp.int32:
        members_c = members_c.astype(jnp.int32)
    val = jnp.take(attrs_val, params["leaf_attr"], axis=1)
    eq = val == params["leaf_const"][None, :]
    memb = jnp.take(members_c, params["member_slot_of_leaf"], axis=1)
    incl = jnp.any(memb == params["leaf_const"][None, :, None], axis=-1)
    return eq, incl, pe._cpu_full(params, cpu_dense)


@jax.jit
def _stage_dfa(params, attr_bytes, byte_ovf, cpu_lane):
    # the UNgrouped compile-order gather layout — the pre-fusion hot path
    tables = params["dfa_tables"]
    tab_idx = params["dfa_table_of_row"][None, :]
    row_bytes = jnp.take(attr_bytes, params["dfa_byte_slot"], axis=1)

    def dfa_step(states, byte_col):
        nxt = tables[tab_idx, states, byte_col.astype(jnp.int32)]
        return nxt.astype(jnp.int32), None

    init = (row_bytes[:, :, 0] * 0).astype(jnp.int32)
    final, _ = jax.lax.scan(dfa_step, init,
                            jnp.transpose(row_bytes, (2, 0, 1)))
    dfa_row_res = params["dfa_accept"][tab_idx, final]
    leaf_dfa = jnp.take(dfa_row_res, params["leaf_dfa_row"], axis=1)
    leaf_slot = jnp.take(params["dfa_byte_slot"], params["leaf_dfa_row"])
    leaf_bovf = jnp.take(byte_ovf, leaf_slot, axis=1)
    return jnp.where(leaf_bovf, cpu_lane, leaf_dfa)


@jax.jit
def _stage_value_lanes(params, attrs_num, num_valid, rel_rows, member_ovf):
    num_cmp = None
    if params.get("leaf_num_slot") is not None and attrs_num is not None:
        lv = jnp.take(attrs_num, params["leaf_num_slot"], axis=1)
        lok = jnp.take(num_valid, params["leaf_num_slot"], axis=1)
        ic = params["leaf_const"][None, :]
        num_cmp = (lok & (lv > ic), lok & (lv >= ic),
                   lok & (lv < ic), lok & (lv <= ic))
    rel_res = None
    if params.get("rel_bits") is not None and rel_rows is not None:
        rows_l = jnp.take(rel_rows, params["leaf_rel_slot"], axis=1)
        col = params["leaf_rel_col"]
        byte = params["rel_bits"][rows_l, (col >> 3)[None, :]].astype(
            jnp.int32)
        rel_res = ((byte >> (col & 7)[None, :]) & 1) != 0
    leaf_movf = None
    if member_ovf is not None:
        leaf_movf = jnp.take(member_ovf, params["member_slot_of_leaf"],
                             axis=1)
    return num_cmp, rel_res, leaf_movf


@jax.jit
def _stage_circuit(params, config_id, eq, incl, dfa_leaf_val, cpu_lane,
                   num_cmp, rel_res, leaf_movf):
    res = pe._leaf_op_cascade(params["leaf_op"], eq, incl, dfa_leaf_val,
                              cpu_lane, num_cmp, rel_res, leaf_movf)
    B = res.shape[0]
    buffer = jnp.concatenate(
        [jnp.ones((B, 1), dtype=bool), jnp.zeros((B, 1), dtype=bool), res],
        axis=1)
    for children, is_and in params["levels"]:
        ch = jnp.take(buffer, children.reshape(-1), axis=1)
        ch = ch.reshape(B, children.shape[0], children.shape[1])
        node = jnp.where(is_and[None, :], jnp.all(ch, axis=-1),
                         jnp.any(ch, axis=-1))
        buffer = jnp.concatenate([buffer, node], axis=1)
    cond = jnp.take(buffer, params["eval_cond"].reshape(-1), axis=1)
    rule = jnp.take(buffer, params["eval_rule"].reshape(-1), axis=1)
    G, E = params["eval_rule"].shape
    verdict, (rule_r, skipped) = pe._verdict_from_tables(
        params, cond.reshape(B, G, E), rule.reshape(B, G, E))
    own_mask = pe._select_own(config_id, verdict.shape[1])
    own = jnp.any(verdict & own_mask, axis=1)
    own_rule = jnp.any(rule_r & own_mask[:, :, None], axis=1)
    own_skipped = jnp.any(skipped & own_mask[:, :, None], axis=1)
    return jnp.concatenate([own[:, None], own_rule, own_skipped], axis=1)


_stage_pack = jax.jit(pe._bitpack_rows)


def staged_launches(params, db) -> int:
    """How many launches ``dispatch_staged`` will make for this batch —
    pure structure arithmetic (leaves + circuit + pack, plus DFA and
    value-lane stages when those operands ride)."""
    n = 3
    if params["dfa_tables"] is not None and db.attr_bytes is not None:
        n += 1
    if any(a is not None
           for a in (db.attrs_num, db.num_valid, db.rel_rows,
                     db.member_ovf)):
        n += 1
    return n


def dispatch_staged(params, db, ledger_lane: Optional[str] = None):
    """The unfused baseline: same batch, same bit-exact [B, W] uint8
    readback, one launch PER STAGE (recorded on the PR 16 ledger when
    ``ledger_lane`` is given).  Intermediate arrays stay on device."""
    def obs():
        if ledger_lane is not None:
            from ..runtime.kernel_cost import LEDGER
            LEDGER.observe_launch(ledger_lane)

    eq, incl, cpu_lane = _stage_leaves(
        params, jnp.asarray(db.attrs_val), jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense))
    obs()
    if params["dfa_tables"] is not None and db.attr_bytes is not None:
        dfa_leaf_val = _stage_dfa(params, jnp.asarray(db.attr_bytes),
                                  jnp.asarray(db.byte_ovf), cpu_lane)
        obs()
    else:
        dfa_leaf_val = cpu_lane
    extras = pe._extra_operands(db)
    if any(a is not None for a in extras):
        num_cmp, rel_res, leaf_movf = _stage_value_lanes(params, *extras)
        obs()
    else:
        num_cmp = rel_res = leaf_movf = None
    cols = _stage_circuit(params, jnp.asarray(db.config_id), eq, incl,
                          dfa_leaf_val, cpu_lane, num_cmp, rel_res,
                          leaf_movf)
    obs()
    out = _stage_pack(cols)
    obs()
    return out


# ---------------------------------------------------------------------------
# pre-warm + mesh occupancy shaping
# ---------------------------------------------------------------------------


def _zero_db(policy: CompiledPolicy, pad: int, eff: int):
    """Throwaway zero DeviceBatch-shaped namespace at one (pad, eff)
    bucket — the fused twin of kernel_cost._bitpacked_zero_args, carrying
    the PR 14 operand tail so the warmed executable matches serving."""
    from ..compiler.intern import PAD
    from ..compiler.pack import wire_dtype

    dt = wire_dtype(policy)
    A, M, K = policy.n_attrs, policy.n_member_attrs, policy.members_k
    C, NB = policy.n_cpu_leaves, max(policy.n_byte_attrs, 1)
    NN = getattr(policy, "n_num_attrs", 0)
    NR = getattr(policy, "n_rel_slots", 0)
    return SimpleNamespace(
        attrs_val=np.zeros((pad, A), dtype=dt),
        members_c=np.full((pad, M, K), PAD, dtype=dt),
        cpu_dense=np.zeros((pad, C), dtype=bool),
        config_id=np.zeros((pad,), dtype=np.int32),
        attr_bytes=np.zeros((pad, NB, eff), dtype=np.uint8) if eff else None,
        byte_ovf=np.zeros((pad, NB), dtype=bool) if eff else None,
        attrs_num=np.zeros((pad, NN), dtype=np.int32) if NN else None,
        num_valid=np.zeros((pad, NN), dtype=bool) if NN else None,
        rel_rows=np.zeros((pad, NR), dtype=np.int32) if NR else None,
        member_ovf=np.zeros((pad, M), dtype=bool)
        if getattr(policy, "ovf_assist", False) else None,
    )


def prewarm_fused(policy: CompiledPolicy, params, pad: int = 16,
                  eff: Optional[int] = None) -> bool:
    """Compile the mega-kernel entry at one warm-grid (pad, eff) bucket so
    the first post-reconcile batch pays no XLA (or Pallas lowering) compile.
    No-op (False) unless the snapshot's params carry the fused subtree."""
    if params is None or params.get("fused") is None:
        return False
    if eff is None:
        eff = DFA_VALUE_BYTES if policy.n_byte_attrs else 0
    out = eval_fused_kernel(params, _zero_db(policy, pad, eff))
    jax.block_until_ready(out)
    return True


def occupancy_pad(shard_counts, dp: int, n_rows: int,
                  floor: int = 16, cap: Optional[int] = None) -> int:
    """Per-shard occupancy-shaped batch pad for the mesh lane (ISSUE 17):
    the stacked [B, S, ...] operands pad to the pow2 bucket of the BUSIEST
    shard's row count replicated across the PR 11 grid's dp axis — a batch
    concentrated on one shard pads to that shard's occupancy, never below
    the real row count, snapped to the same pow2 grid as the single-corpus
    warm buckets (so it adds no jit variants beyond that grid)."""
    occ = max((int(c) for c in shard_counts), default=0)
    need = max(int(n_rows), occ * max(int(dp), 1), 1)
    pad = max(int(floor), 1)
    while pad < need:
        pad *= 2
    if cap is not None:
        pad = min(pad, max(int(cap), need))
    return pad
