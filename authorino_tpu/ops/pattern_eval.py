"""Batched policy-evaluation kernel (pure JAX/XLA; static shapes, no
data-dependent control flow).

One call evaluates a micro-batch of requests against the *entire* compiled
rule corpus and returns per-request per-config allow verdicts.  This replaces
the reference's per-request goroutine fan-out + per-pattern gjson walk
(ref: pkg/service/auth_pipeline.go:150-182, pkg/jsonexp/expressions.go:59):
equal-priority rules across all configs fuse into one kernel launch
(SURVEY.md §2 P1/P2 mapping).

Inputs are the *compact* device payload (compiler/pack.py): [B, A] attr ids,
[B, M, K] membership rows for incl/excl attrs only, a [B, C] dense CPU lane
(C = true-CPU + DFA leaves, not the full leaf axis), and the DFA byte
tensors.  Host↔device transfer is the real bottleneck (HBM/PCIe — or a
network tunnel on this image), so the wire format carries only what the
kernel reads and results return as one packed bool matrix.

Two lanes:

  - ``matmul`` (default): gathers are pathological on TPU (they lower to
    scalar-unit loops), so every gather is reformulated as a one-hot matmul
    on the MXU:
      * leaf operand selection rides ``attrs @ attr_onehot`` with
        ``Precision.HIGHEST`` — XLA's 3-pass bf16 decomposition makes the
        f32 product exact, and selecting through an exact 0/1 one-hot
        reassembles interner ids < 2^24 bit-exactly;
      * the boolean circuit becomes per-level *count* matmuls over the
        result buffer (AND ≡ count==width since And-padding children point
        at the constant-TRUE slot; OR ≡ count>0 with FALSE-slot padding);
      * per-config rule/condition extraction and own-config selection are
        one-hot matmuls/masked reductions;
      * the regex-DFA byte scan keeps its ``lax.scan`` skeleton but each
        step's transition lookup becomes a batched
        (byte-one-hot × transition-table) matmul — values ≤ 255 are exact
        in bf16.
  - ``gather``: the direct jnp.take formulation — the semantic reference
    for differential tests, and the automatic fallback when the interner
    outgrows exact-f32 range (ids ≥ 2^24).

Lane dispatch is structural: ``to_device`` builds the matmul operands (or
not), and ``eval_verdicts`` branches on their presence at trace time, so the
two lanes jit-cache independently.

Membership overflow (arrays longer than K) and DFA byte overflow cannot be
answered from the compact payload per-leaf; overflowed *requests* are flagged
host_fallback by pack_batch and re-decided on host by the expression oracle
(models/policy_model.py) — the kernel result for those rows is ignored.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.compile import (
    FALSE_SLOT,
    NUMERIC_OPS,
    OP_CPU,
    OP_EQ,
    OP_ERROR,
    OP_EXCL,
    OP_INCL,
    OP_NEQ,
    OP_NUM_GE,
    OP_NUM_GT,
    OP_NUM_LE,
    OP_NUM_LT,
    OP_REGEX_DFA,
    OP_RELATION,
    OP_TREE_CPU,
    TRUE_SLOT,
    CompiledPolicy,
)

__all__ = ["DevicePolicy", "to_device", "eval_verdicts", "eval_batch_jit",
           "fuse_batch", "eval_fused_jit", "dispatch_fused",
           "fused_h2d_supported", "eval_bitpacked_jit", "unpack_verdicts",
           "packed_width", "firing_columns", "unpack_attribution",
           "kernel_lane_of", "auto_lane", "last_auto_decision"]

# exact integer range of f32 accumulation — larger interners must use the
# gather lane
_F32_EXACT = 1 << 24

_HIGH = jax.lax.Precision.HIGHEST


def _eval_lane() -> str:
    return os.environ.get("AUTHORINO_TPU_EVAL_LANE", "matmul")


def _kernel_lane() -> str:
    """Env mirror of ``--kernel-lane``: ``fused`` arms the ISSUE 17
    mega-kernel, ``gather``/``matmul`` force those lanes, ``auto``
    (default) picks fused only on a real TPU backend — off-TPU the Pallas
    kernel runs in interpret mode, which is bit-exact but an emulation
    (docs/performance.md "Fused mega-kernel")."""
    return os.environ.get("AUTHORINO_TPU_KERNEL_LANE", "auto")


# last `--kernel-lane auto` resolution (ISSUE 18 satellite): what got
# armed, over which device platforms, surfaced on /debug/vars
# kernel_cost.entry_points so an operator can see WHY fused is (not) on
_AUTO_DECISION: dict = {}


def auto_lane(device=None) -> str:
    """Resolve ``--kernel-lane auto`` for one operand upload: fused iff
    EVERY device the operands can land on is a real TPU.
    ``jax.default_backend()`` alone is the wrong oracle — it names the
    highest-priority platform, so a single TPU in a mixed device set used
    to arm the Pallas kernel mesh-wide and run it in interpret mode on
    every non-TPU shard.  The consulted set is the explicit target device
    when one is given, else the FULL visible device set (``mesh="auto"``
    shards over exactly that set, so all-TPU here implies all-TPU on the
    mesh)."""
    devices = [device] if device is not None else list(jax.devices())
    platforms = sorted({str(getattr(d, "platform", "unknown"))
                        for d in devices})
    lane = "fused" if platforms == ["tpu"] else _eval_lane()
    _AUTO_DECISION.clear()
    _AUTO_DECISION.update({
        "requested": "auto", "lane": lane,
        "devices": len(devices), "platforms": platforms,
    })
    return lane


def last_auto_decision() -> Optional[dict]:
    """The most recent auto-lane resolution, or None before any auto
    upload (explicit --kernel-lane values never consult this path)."""
    return dict(_AUTO_DECISION) if _AUTO_DECISION else None


def kernel_lane_of(params) -> str:
    """Which kernel lane a params pytree dispatches through — structural,
    mirroring eval_verdicts' trace-time branch order."""
    if params.get("fused") is not None:
        return "fused"
    if params.get("matmul") is not None:
        return "matmul"
    return "gather"


def _mm_dtype(device=None):
    """bf16 on MXU-bearing backends; f32 on CPU (whose dot kernels lack
    BF16×BF16→F32 — and where f32 one-hot matmuls are exact natively).
    Derived from the *target* device's platform when one is given."""
    platform = device.platform if device is not None else jax.default_backend()
    return jnp.float32 if platform == "cpu" else jnp.bfloat16


def _matmul_operands(policy: CompiledPolicy, row_slot: np.ndarray, device=None) -> dict:
    """One-hot / count matrices for the MXU lane (see module doc).
    ``row_slot`` is the per-DFA-row byte-tensor slot (shared with the gather
    lane's ``dfa_byte_slot`` so the two lanes can never disagree on which
    byte tensor a row scans)."""
    L = policy.n_leaves
    A = policy.n_attrs
    M = policy.n_member_attrs
    C = policy.n_cpu_leaves
    cdt = _mm_dtype(device)
    attr_onehot = np.zeros((A, L), dtype=np.float32)
    attr_onehot[policy.leaf_attr, np.arange(L)] = 1.0

    # compact-membership one-hot: member slot of each incl/excl leaf's attr
    memb_onehot = np.zeros((M, L), dtype=np.float32)
    is_memb = policy.leaf_is_membership
    if is_memb.any():
        slots = policy.member_attr_slot[policy.leaf_attr[is_memb]]
        memb_onehot[slots, np.nonzero(is_memb)[0]] = 1.0

    # dense CPU lane spread: [C] columns → [L] leaf axis
    cpu_oh = np.zeros((C, L), dtype=np.float32)
    cl = policy.cpu_leaf_list
    if cl.shape[0]:
        cpu_oh[np.arange(cl.shape[0]), cl] = 1.0

    # per-level count matrices over the buffer prefix visible to that level
    # (the count threshold — the level's child width — is recovered at trace
    # time from params["levels"]; keeping it out of the operands leaves the
    # pytree all-array, so the sharded model can stack it on a mesh axis)
    level_mats = []
    cursor = 2 + L  # TRUE/FALSE slots + leaf block
    for children, is_and in policy.levels:
        rows, width = children.shape
        m = np.zeros((rows, cursor), dtype=np.float32)
        np.add.at(m, (np.repeat(np.arange(rows), width), children.reshape(-1)), 1.0)
        level_mats.append(m.astype(cdt))
        cursor += rows

    # eval-table one-hots over the full buffer
    G, E = policy.eval_rule.shape
    rule_m = np.zeros((G * E, cursor), dtype=np.float32)
    rule_m[np.arange(G * E), policy.eval_rule.reshape(-1)] = 1.0
    cond_m = np.zeros((G * E, cursor), dtype=np.float32)
    cond_m[np.arange(G * E), policy.eval_cond.reshape(-1)] = 1.0

    out = {
        "attr_onehot": attr_onehot,  # f32: exact selection via HIGHEST
        "memb_onehot": memb_onehot,  # f32: exact selection via HIGHEST
        "cpu_oh": cpu_oh.astype(cdt),
        "level_mats": tuple(level_mats),
        "rule_m": rule_m.astype(cdt),
        "cond_m": cond_m.astype(cdt),
    }

    # numeric lane (ISSUE 14): int32 compares happen slot-wise (exact, no
    # f32 round-trip for the values); this bool mask spreads each slot's
    # verdict onto its leaves via a masked any-reduce — gather-free
    if getattr(policy, "n_num_attrs", 0):
        NN = policy.n_num_attrs
        num_mask = np.zeros((NN, L), dtype=bool)
        is_num = np.isin(policy.leaf_op, NUMERIC_OPS)
        if is_num.any():
            slots = np.maximum(
                policy.num_attr_slot[policy.leaf_attr[is_num]], 0)
            num_mask[slots, np.nonzero(is_num)[0]] = True
        out["num_slot_leaf_mask"] = num_mask

    # relation lane (ISSUE 14): per (entity row, leaf) bit matrix — each
    # relation leaf's column unpacked onto its leaf slot, selected by an
    # exact one-hot over the row axis (0/1 products: exact in bf16)
    if getattr(policy, "n_rel_slots", 0):
        Rp = int(policy.rel_bits.shape[0])
        NR = policy.n_rel_slots
        is_rel = policy.leaf_op == OP_RELATION
        rel_leaf_mat = np.zeros((Rp, L), dtype=np.float32)
        rel_slot_leaf = np.zeros((NR, L), dtype=np.float32)
        for l in np.nonzero(is_rel)[0]:
            c = int(policy.leaf_rel_col[l])
            rel_leaf_mat[:, l] = (policy.rel_bits[:, c >> 3]
                                  >> np.uint8(c & 7)) & 1
            rel_slot_leaf[int(policy.leaf_rel_slot[l]), l] = 1.0
        out["rel_leaf_mat"] = rel_leaf_mat.astype(cdt)
        out["rel_slot_leaf_oh"] = rel_slot_leaf.astype(cdt)

    # device regex lane: matmul-form transition tables + spread one-hots.
    # The compiled tables are table-deduped ([T, S, 256] + row→table map);
    # the matmul lane's einsum contracts over the row axis, so the tables
    # expand back to per-row here (host-side — the one-hot spread matrices
    # dominate this lane's operand footprint anyway)
    if policy.n_byte_attrs:
        R = policy.dfa_table_of_row.shape[0]
        NB = policy.n_byte_attrs
        slot_row_oh = np.zeros((NB, R), dtype=np.float32)
        slot_row_oh[row_slot, np.arange(R)] = 1.0
        is_dfa_leaf = policy.leaf_op == OP_REGEX_DFA
        row_leaf_oh = np.zeros((R, L), dtype=np.float32)
        row_leaf_oh[policy.leaf_dfa_row[is_dfa_leaf], np.nonzero(is_dfa_leaf)[0]] = 1.0
        slot_leaf_oh = np.zeros((NB, L), dtype=np.float32)
        leaf_slot = row_slot[policy.leaf_dfa_row[is_dfa_leaf]]
        slot_leaf_oh[leaf_slot, np.nonzero(is_dfa_leaf)[0]] = 1.0
        out.update(
            {
                # next-state values ≤ 255 and state count ≤ 256: exact in bf16
                "dfa_tables_f": policy.dfa_tables_by_row.astype(cdt),
                "dfa_accept_f": policy.dfa_accept_by_row.astype(cdt),
                "slot_row_oh": slot_row_oh.astype(cdt),
                "row_leaf_oh": row_leaf_oh.astype(cdt),
                "slot_leaf_oh": slot_leaf_oh.astype(cdt),
            }
        )
    return out


def to_device(policy: CompiledPolicy, device=None, lane: Optional[str] = None,
              host: bool = False) -> dict:
    """Upload a compiled corpus's operands as a pytree of device arrays.
    The engine double-buffers these and swaps atomically on reconcile
    (SURVEY.md §3.4: rule-tensor compile + device upload on index Set).
    ``lane`` overrides the env-var lane selection; ``host=True`` keeps the
    operands as host numpy arrays — the sharded model stacks per-shard
    pytrees host-side and transfers each shard's slice exactly once via a
    mesh-sharded device_put, instead of staging everything on device 0."""
    if host:
        put = np.asarray
    else:
        put = partial(jax.device_put, device=device) if device is not None else jax.device_put
    if lane is None:
        kl = _kernel_lane()
        if kl in ("fused", "gather", "matmul"):
            lane = kl
        else:  # auto: fused iff every target device is a real TPU
            lane = auto_lane(device)
    if lane == "matmul" and len(policy.interner) + 4 >= _F32_EXACT:
        lane = "gather"  # ids no longer exact in f32 accumulation
    # per-dfa-row byte-tensor slot (attr → slot mapping folded in here);
    # shared by all lanes
    dfa_byte_slot = np.maximum(policy.attr_byte_slot[policy.dfa_leaf_attr], 0)
    mm = (
        jax.tree.map(put, _matmul_operands(policy, dfa_byte_slot, device=device))
        if lane == "matmul"
        else None
    )
    if lane == "fused":
        from . import fused_kernel as _fk  # lazy: fused_kernel imports us

        fz = jax.tree.map(put, _fk.fused_operands(policy, dfa_byte_slot))
    else:
        fz = None
    # gather-lane helpers for the compact payload
    L = policy.n_leaves
    member_slot_of_leaf = np.maximum(
        policy.member_attr_slot[policy.leaf_attr], 0
    ).astype(np.int32)
    # scatter targets: dense CPU cols → leaf axis; padding cols land in a
    # dump slot at L (sliced off) so they can never clobber a real leaf
    C = policy.n_cpu_leaves
    cpu_scatter_idx = np.full((C,), L, dtype=np.int32)
    cpu_scatter_idx[: policy.cpu_leaf_list.shape[0]] = policy.cpu_leaf_list
    # operands are numpy throughout: `put` is the ONLY device transfer (or a
    # no-op for host=True), so nothing ever stages on the default device
    return {
        "matmul": mm,
        # fused mega-kernel subtree (ISSUE 17): int8 op codes + the
        # table-grouped DFA row layout; None (structural) on other lanes
        "fused": fz,
        "leaf_op": put(policy.leaf_op),
        "leaf_attr": put(policy.leaf_attr),
        "leaf_const": put(policy.leaf_const),
        "member_slot_of_leaf": put(member_slot_of_leaf),
        "cpu_scatter_idx": put(cpu_scatter_idx),
        "levels": tuple(
            (put(children), put(is_and))
            for children, is_and in policy.levels
        ),
        "eval_cond": put(policy.eval_cond),
        "eval_rule": put(policy.eval_rule),
        "eval_has_cond": put(policy.eval_has_cond),
        # device regex lane; None (a static pytree node, not a traced leaf)
        # when the corpus has no DFA-compilable regexes, so the kernel's
        # python-level `is None` check specializes at trace time.  Tables
        # travel DEDUPED ([T, S, 256] + dfa_table_of_row): the gather lane
        # indexes through the row→table map on device, so identical regexes
        # across AuthConfigs upload exactly one transition table.
        "dfa_tables": put(policy.dfa_tables) if policy.n_byte_attrs else None,
        "dfa_accept": put(policy.dfa_accept) if policy.n_byte_attrs else None,
        "dfa_table_of_row": put(policy.dfa_table_of_row)
        if policy.n_byte_attrs else None,
        "dfa_byte_slot": put(dfa_byte_slot.astype(np.int32)) if policy.n_byte_attrs else None,
        "leaf_dfa_row": put(policy.leaf_dfa_row) if policy.n_byte_attrs else None,
        # numeric comparator lane (ISSUE 14): leaf → compact value slot;
        # the constants ride leaf_const (folded int32 at compile time)
        "leaf_num_slot": put(np.maximum(
            policy.num_attr_slot[policy.leaf_attr], 0).astype(np.int32))
        if getattr(policy, "n_num_attrs", 0) else None,
        # relation lane (ISSUE 14): the per-snapshot closure bitmatrix +
        # leaf → (entity-row slot, group column) bindings
        "rel_bits": put(policy.rel_bits)
        if getattr(policy, "n_rel_slots", 0) else None,
        "leaf_rel_slot": put(policy.leaf_rel_slot)
        if getattr(policy, "n_rel_slots", 0) else None,
        "leaf_rel_col": put(policy.leaf_rel_col)
        if getattr(policy, "n_rel_slots", 0) else None,
    }


DevicePolicy = dict


def _cpu_full(params, cpu_dense):
    """Spread the dense [B, C] CPU lane onto the [B, L] leaf axis."""
    B = cpu_dense.shape[0]
    L = params["leaf_op"].shape[0]
    buf = jnp.zeros((B, L + 1), dtype=bool)
    buf = buf.at[:, params["cpu_scatter_idx"]].set(cpu_dense)
    return buf[:, :L]


def _leaf_op_cascade(leaf_op, eq, incl, dfa_leaf_val, cpu_lane,
                     num_cmp=None, rel_res=None, leaf_movf=None):
    """Shared op-code dispatch: per-leaf boolean results from the lane's
    primitive comparisons (identical semantics in both lanes).

    ``num_cmp`` is the numeric lane's (gt, ge, lt, le) [B, L] quadruple
    (None: no numeric leaves); ``rel_res`` the relation lane's [B, L]
    bitmask-gather result; ``leaf_movf`` the membership-overflow mask
    spread to the leaf axis (ovf_assist): overflowed incl/excl leaves read
    their exact precomputed answer from the dense CPU columns — note the
    EXCL branch reads ``cpu_lane`` directly (the encoder stores the final
    excl answer, not the membership bit)."""
    op = leaf_op[None, :]
    if leaf_movf is None:
        incl_eff, excl_eff = incl, ~incl
    else:
        incl_eff = jnp.where(leaf_movf, cpu_lane, incl)
        excl_eff = jnp.where(leaf_movf, cpu_lane, ~incl)
    if num_cmp is None:
        num_res = False
    else:
        gt, ge, lt, le = num_cmp
        num_res = jnp.where(
            op == OP_NUM_GT, gt,
            jnp.where(op == OP_NUM_GE, ge,
                      jnp.where(op == OP_NUM_LT, lt, le)))
    tail = jnp.where(
        (op == OP_CPU) | (op == OP_TREE_CPU), cpu_lane,
        jnp.where(op >= OP_NUM_GT,
                  jnp.where(op == OP_RELATION,
                            rel_res if rel_res is not None else False,
                            num_res),
                  False))  # OP_ERROR → False
    return jnp.where(
        op == OP_EQ, eq,
        jnp.where(
            op == OP_NEQ, ~eq,
            jnp.where(
                op == OP_INCL, incl_eff,
                jnp.where(
                    op == OP_EXCL, excl_eff,
                    jnp.where(op == OP_REGEX_DFA, dfa_leaf_val, tail),
                ),
            ),
        ),
    )


def _verdict_from_tables(params, cond, rule):
    """Shared tail: per-config verdicts ∧ over evaluators of (¬cond ∨ rule)."""
    skipped = params["eval_has_cond"][None, :, :] & ~cond
    contrib = jnp.where(skipped, True, rule)
    verdict = jnp.all(contrib, axis=-1)  # [B, G]
    return verdict, (rule, skipped)


# ---------------------------------------------------------------------------
# matmul lane (MXU)
# ---------------------------------------------------------------------------


def _eval_verdicts_matmul(params, attrs_val, members_c, cpu_dense,
                          attr_bytes, byte_ovf, attrs_num=None,
                          num_valid=None, rel_rows=None, member_ovf=None):
    mm = params["matmul"]
    f32 = jnp.float32
    cdt = mm["rule_m"].dtype
    B = attrs_val.shape[0]
    attr_oh = mm["attr_onehot"]                              # [A, L] f32
    const = params["leaf_const"].astype(f32)                 # [L]

    # ---- leaf selection: one-hot matmuls, exact in f32 -------------------
    val = jnp.matmul(attrs_val.astype(f32), attr_oh, precision=_HIGH)  # [B, L]
    eq = val == const[None, :]
    memb = jnp.einsum(
        "bmk,ml->bkl", members_c.astype(f32), mm["memb_onehot"], precision=_HIGH
    )                                                        # [B, K, L]
    incl = jnp.any(memb == const[None, None, :], axis=1)     # [B, L]

    # ---- dense CPU lane spread onto the leaf axis ------------------------
    cpu_lane = jnp.matmul(
        cpu_dense.astype(cdt), mm["cpu_oh"], preferred_element_type=f32
    ) > 0.5                                                  # [B, L]

    # ---- device regex lane: DFA scan, transitions as batched matmuls -----
    if params["dfa_tables"] is not None and attr_bytes is not None:
        tables = mm["dfa_tables_f"]                          # [R, S, 256] bf16
        R, S = tables.shape[0], tables.shape[1]
        # spread each row's attr bytes from its slot: [B, NB, LB] → [B, R, LB]
        row_bytes = jnp.einsum(
            "bnl,nr->brl", attr_bytes.astype(cdt), mm["slot_row_oh"],
            preferred_element_type=f32,
        )
        iota_s = jnp.arange(S, dtype=f32)
        iota_c = jnp.arange(256, dtype=f32)

        def dfa_step(state, byte_col):  # state [B,R] f32; byte_col [B,R] f32
            byte_oh = (byte_col[..., None] == iota_c).astype(cdt)   # [B,R,256]
            # per-state next-state given this byte: [R,S,256] × [B,R,256]
            nxt_by_state = jnp.einsum(
                "rsc,brc->brs", tables, byte_oh, preferred_element_type=f32
            )
            st_oh = (state[..., None] == iota_s).astype(f32)
            nxt = jnp.sum(st_oh * nxt_by_state, axis=-1)
            return nxt, None

        # derive the scan's init carry from a varying input (zero-multiplied)
        # so its manual-mesh "varying" type matches inside shard_map
        init = row_bytes[:, :, 0] * 0.0
        final, _ = jax.lax.scan(dfa_step, init, jnp.transpose(row_bytes, (2, 0, 1)))
        final_oh = (final[..., None] == iota_s).astype(cdt)
        dfa_row_res = jnp.einsum(
            "brs,rs->br", final_oh, mm["dfa_accept_f"], preferred_element_type=f32
        ) > 0.5                                              # [B, R]
        leaf_dfa = jnp.einsum(
            "br,rl->bl", dfa_row_res.astype(cdt), mm["row_leaf_oh"],
            preferred_element_type=f32,
        ) > 0.5
        leaf_bovf = jnp.einsum(
            "bn,nl->bl", byte_ovf.astype(cdt), mm["slot_leaf_oh"],
            preferred_element_type=f32,
        ) > 0.5
        # overflowed values: exact answer precomputed into the CPU lane
        dfa_leaf_val = jnp.where(leaf_bovf, cpu_lane, leaf_dfa)
    else:
        dfa_leaf_val = cpu_lane  # regexes ride the CPU lane entirely

    # ---- numeric lane: slot-wise int32 compares, mask-spread (no gather,
    # no f32 round-trip of the values — exactness by construction) --------
    num_cmp = None
    if params.get("leaf_num_slot") is not None and attrs_num is not None:
        num_mask = mm["num_slot_leaf_mask"]                  # [NN, L] bool
        iconst = params["leaf_const"][None, None, :]         # [1, 1, L] i32
        v = attrs_num[:, :, None]                            # [B, NN, 1]
        lane_ok = num_valid[:, :, None] & num_mask[None]     # [B, NN, L]
        num_cmp = (
            jnp.any(lane_ok & (v > iconst), axis=1),
            jnp.any(lane_ok & (v >= iconst), axis=1),
            jnp.any(lane_ok & (v < iconst), axis=1),
            jnp.any(lane_ok & (v <= iconst), axis=1),
        )

    # ---- relation lane: exact one-hot row selection per slot over the
    # unpacked per-leaf column matrix (0/1 products: exact) ---------------
    rel_res = None
    if params.get("rel_bits") is not None and rel_rows is not None:
        rel_mat = mm["rel_leaf_mat"]                         # [Rp, L]
        Rp = rel_mat.shape[0]
        iota_r = jnp.arange(Rp, dtype=f32)
        acc = jnp.zeros((B, rel_mat.shape[1]), dtype=f32)
        for n_i in range(mm["rel_slot_leaf_oh"].shape[0]):   # static, small
            oh = (rel_rows[:, n_i].astype(f32)[:, None]
                  == iota_r[None, :]).astype(cdt)            # [B, Rp]
            vals = jnp.matmul(oh, rel_mat, preferred_element_type=f32)
            acc = acc + vals * mm["rel_slot_leaf_oh"][n_i][None, :].astype(f32)
        rel_res = acc > 0.5

    # ---- membership-overflow assist: spread the [B, M] mask to leaves ---
    leaf_movf = None
    if member_ovf is not None:
        leaf_movf = jnp.matmul(
            member_ovf.astype(cdt), mm["memb_onehot"].astype(cdt),
            preferred_element_type=f32) > 0.5                # [B, L]

    res = _leaf_op_cascade(params["leaf_op"], eq, incl, dfa_leaf_val,
                           cpu_lane, num_cmp, rel_res, leaf_movf)

    # ---- boolean circuit: per-level count matmuls ------------------------
    true_col = jnp.ones((B, 1), dtype=bool)
    false_col = jnp.zeros((B, 1), dtype=bool)
    buffer = jnp.concatenate([true_col, false_col, res], axis=1)
    for m, (children, is_and) in zip(mm["level_mats"], params["levels"]):
        width = children.shape[1]  # static: the level's padded child count
        counts = jnp.matmul(
            buffer.astype(cdt), m.T, preferred_element_type=f32
        )                                                    # [B, rows]
        # And-padding children point at TRUE (count includes them); Or-padding
        # at FALSE (adds 0) — so count==width ≡ all, count>0 ≡ any
        node = jnp.where(is_and[None, :], counts >= width - 0.5, counts > 0.5)
        buffer = jnp.concatenate([buffer, node], axis=1)

    # ---- per-config rule/cond extraction: one-hot matmuls ----------------
    buf16 = buffer.astype(cdt)
    G, E = params["eval_rule"].shape
    rule = (jnp.matmul(buf16, mm["rule_m"].T, preferred_element_type=f32) > 0.5)
    cond = (jnp.matmul(buf16, mm["cond_m"].T, preferred_element_type=f32) > 0.5)
    return _verdict_from_tables(params, cond.reshape(B, G, E), rule.reshape(B, G, E))


# ---------------------------------------------------------------------------
# gather lane (semantic reference / large-interner fallback)
# ---------------------------------------------------------------------------


def _eval_verdicts_gather(params, attrs_val, members_c, cpu_dense,
                          attr_bytes, byte_ovf, attrs_num=None,
                          num_valid=None, rel_rows=None, member_ovf=None):
    leaf_op = params["leaf_op"]          # [L]
    leaf_attr = params["leaf_attr"]      # [L]
    leaf_const = params["leaf_const"]    # [L]

    B = attrs_val.shape[0]

    # ---- leaf evaluation -------------------------------------------------
    val = jnp.take(attrs_val, leaf_attr, axis=1)            # [B, L]
    eq = val == leaf_const[None, :]
    memb = jnp.take(members_c, params["member_slot_of_leaf"], axis=1)  # [B, L, K]
    incl = jnp.any(memb == leaf_const[None, :, None], axis=-1)

    cpu_lane = _cpu_full(params, cpu_dense)                 # [B, L]

    # ---- device regex lane: DFA scan over value bytes --------------------
    if params["dfa_tables"] is not None and attr_bytes is not None:
        tables = params["dfa_tables"]          # [T, S, 256] uint8 (deduped)
        # per-row table index: rows sharing an automaton share one table
        tab_idx = params["dfa_table_of_row"][None, :]        # [1, R]
        row_bytes = jnp.take(attr_bytes, params["dfa_byte_slot"], axis=1)  # [B, R, LB]

        def dfa_step(states, byte_col):  # states [B,R] i32, byte_col [B,R] u8
            nxt = tables[tab_idx, states, byte_col.astype(jnp.int32)]
            return nxt.astype(jnp.int32), None

        # init carry derived from a varying input (zero-multiplied) so its
        # manual-mesh "varying" type matches inside shard_map
        init = (row_bytes[:, :, 0] * 0).astype(jnp.int32)
        final, _ = jax.lax.scan(dfa_step, init, jnp.transpose(row_bytes, (2, 0, 1)))
        dfa_row_res = params["dfa_accept"][tab_idx, final]   # [B, R]
        leaf_dfa = jnp.take(dfa_row_res, params["leaf_dfa_row"], axis=1)  # [B, L]
        leaf_slot = jnp.take(params["dfa_byte_slot"], params["leaf_dfa_row"])
        leaf_bovf = jnp.take(byte_ovf, leaf_slot, axis=1)    # [B, L]
        dfa_leaf_val = jnp.where(leaf_bovf, cpu_lane, leaf_dfa)
    else:
        dfa_leaf_val = cpu_lane  # regexes ride the CPU lane entirely

    # ---- numeric lane: gather each leaf's slot value, compare int32 ------
    num_cmp = None
    if params.get("leaf_num_slot") is not None and attrs_num is not None:
        lv = jnp.take(attrs_num, params["leaf_num_slot"], axis=1)    # [B, L]
        lok = jnp.take(num_valid, params["leaf_num_slot"], axis=1)
        ic = leaf_const[None, :]
        num_cmp = (lok & (lv > ic), lok & (lv >= ic),
                   lok & (lv < ic), lok & (lv <= ic))

    # ---- relation lane: bitmask gather through (entity row, group col) ---
    rel_res = None
    if params.get("rel_bits") is not None and rel_rows is not None:
        rows_l = jnp.take(rel_rows, params["leaf_rel_slot"], axis=1)  # [B, L]
        col = params["leaf_rel_col"]                                  # [L]
        byte = params["rel_bits"][rows_l, (col >> 3)[None, :]].astype(
            jnp.int32)                                                # [B, L]
        rel_res = ((byte >> (col & 7)[None, :]) & 1) != 0

    # ---- membership-overflow assist ---------------------------------------
    leaf_movf = None
    if member_ovf is not None:
        leaf_movf = jnp.take(member_ovf, params["member_slot_of_leaf"],
                             axis=1)                                  # [B, L]

    res = _leaf_op_cascade(leaf_op, eq, incl, dfa_leaf_val, cpu_lane,
                           num_cmp, rel_res, leaf_movf)

    # ---- boolean-circuit reduction, level by level -----------------------
    true_col = jnp.ones((B, 1), dtype=bool)
    false_col = jnp.zeros((B, 1), dtype=bool)
    buffer = jnp.concatenate([true_col, false_col, res], axis=1)
    for children, is_and in params["levels"]:
        ch = jnp.take(buffer, children.reshape(-1), axis=1)
        ch = ch.reshape(B, children.shape[0], children.shape[1])
        node = jnp.where(is_and[None, :], jnp.all(ch, axis=-1), jnp.any(ch, axis=-1))
        buffer = jnp.concatenate([buffer, node], axis=1)

    # ---- per-config verdicts ---------------------------------------------
    cond = jnp.take(buffer, params["eval_cond"].reshape(-1), axis=1)
    rule = jnp.take(buffer, params["eval_rule"].reshape(-1), axis=1)
    G, E = params["eval_rule"].shape
    return _verdict_from_tables(
        params, cond.reshape(B, G, E), rule.reshape(B, G, E)
    )


def eval_verdicts(
    params: DevicePolicy,
    attrs_val: jnp.ndarray,      # [B, A] int32
    members_c: jnp.ndarray,      # [B, M, K] int32 (compact membership)
    cpu_dense: jnp.ndarray,      # [B, C] bool (dense CPU lane)
    attr_bytes: Optional[jnp.ndarray] = None,  # [B, NB, LB] uint8
    byte_ovf: Optional[jnp.ndarray] = None,    # [B, NB] bool
    attrs_num: Optional[jnp.ndarray] = None,   # [B, NN] int32 (numeric lane)
    num_valid: Optional[jnp.ndarray] = None,   # [B, NN] bool
    rel_rows: Optional[jnp.ndarray] = None,    # [B, NR] int32 (relation lane)
    member_ovf: Optional[jnp.ndarray] = None,  # [B, M] bool (ovf_assist)
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Returns (verdict [B, G] bool, (rule_results [B, G, E], skipped [B, G, E]))."""
    # ids travel as int16 when the interner fits (compiler/pack.py
    # wire_dtype); upcast on device AFTER the transfer
    if attrs_val.dtype != jnp.int32:
        attrs_val = attrs_val.astype(jnp.int32)
    if members_c.dtype != jnp.int32:
        members_c = members_c.astype(jnp.int32)
    if params.get("fused") is not None:
        from . import fused_kernel as _fk  # lazy: fused_kernel imports us

        return _fk._eval_verdicts_fused(
            params, attrs_val, members_c, cpu_dense, attr_bytes, byte_ovf,
            attrs_num, num_valid, rel_rows, member_ovf
        )
    if params.get("matmul") is not None:
        return _eval_verdicts_matmul(
            params, attrs_val, members_c, cpu_dense, attr_bytes, byte_ovf,
            attrs_num, num_valid, rel_rows, member_ovf
        )
    return _eval_verdicts_gather(
        params, attrs_val, members_c, cpu_dense, attr_bytes, byte_ovf,
        attrs_num, num_valid, rel_rows, member_ovf
    )


def _select_own(config_id: jnp.ndarray, n_configs: int) -> jnp.ndarray:
    """[B, G] one-hot row mask of each request's own config (mask-reduce
    instead of take_along_axis: gathers serialize on TPU)."""
    return config_id[:, None] == jnp.arange(n_configs, dtype=config_id.dtype)[None, :]


def forward(params, attrs_val, members_c, cpu_dense, config_id,
            attr_bytes=None, byte_ovf=None, attrs_num=None, num_valid=None,
            rel_rows=None, member_ovf=None):
    """Canonical forward step: encoded micro-batch → (own verdicts [B],
    full verdict matrix [B, G]).  The single source of truth for
    verdict-selection logic (PolicyModel and the engine both use it)."""
    verdict, _ = eval_verdicts(
        params, attrs_val, members_c, cpu_dense, attr_bytes, byte_ovf,
        attrs_num, num_valid, rel_rows, member_ovf
    )
    own_mask = _select_own(config_id, verdict.shape[1])
    own = jnp.any(verdict & own_mask, axis=1)
    return own, verdict


_eval_jit = jax.jit(forward)


@partial(jax.jit, static_argnames=())
def eval_full_jit(params, attrs_val, members_c, cpu_dense, config_id,
                  attr_bytes=None, byte_ovf=None, attrs_num=None,
                  num_valid=None, rel_rows=None, member_ovf=None):
    """Like _eval_jit but also returns each request's own per-evaluator rule
    results + skipped flags [B, E] — what the pipeline's batched
    pattern-matching evaluators consume (runtime/engine.py)."""
    verdict, (rule, skipped) = eval_verdicts(
        params, attrs_val, members_c, cpu_dense, attr_bytes, byte_ovf,
        attrs_num, num_valid, rel_rows, member_ovf
    )
    own_mask = _select_own(config_id, verdict.shape[1])
    own = jnp.any(verdict & own_mask, axis=1)
    own_rule = jnp.any(rule & own_mask[:, :, None], axis=1)
    own_skipped = jnp.any(skipped & own_mask[:, :, None], axis=1)
    return own, own_rule, own_skipped


@partial(jax.jit, static_argnames=())
def eval_packed_jit(params, attrs_val, members_c, cpu_dense, config_id,
                    attr_bytes=None, byte_ovf=None, attrs_num=None,
                    num_valid=None, rel_rows=None, member_ovf=None):
    """Hot-path variant: one packed [B, 1+2E] bool result (own verdict,
    own rule results, own skipped) so the device→host read is a single
    small transfer — the link's round-trip latency dominates the batch
    budget, so one readback per batch is the contract."""
    own, own_rule, own_skipped = eval_full_jit(
        params, attrs_val, members_c, cpu_dense, config_id, attr_bytes,
        byte_ovf, attrs_num, num_valid, rel_rows, member_ovf
    )
    return jnp.concatenate([own[:, None], own_rule, own_skipped], axis=1)


# ---------------------------------------------------------------------------
# packed u8 bitmask readback: 8 verdicts per byte on the D2H link
# ---------------------------------------------------------------------------
#
# The packed [B, 1+2E] bool result still crosses the link as one byte per
# element (JAX bools are 1-byte).  On the RTT-bound tunnel the readback
# bytes are pure overhead, so the serving dispatchers read back a [B, W]
# uint8 bitmask instead (W = ceil((1+2E)/8)): ~8x fewer D2H bytes per
# batch.  Bit order is LITTLE (bit j of byte k = column k*8+j), matching
# np.unpackbits(bitorder="little") for the host-side decode — round-trip
# bit-exactness is pinned by tests/test_eval_lanes.py.

def packed_width(n_cols: int) -> int:
    """Bitmask bytes per row for an ``n_cols``-wide packed bool result."""
    return (n_cols + 7) // 8


def _bitpack_rows(mat):
    """Traced [B, C] bool → [B, ceil(C/8)] uint8 (little bit order)."""
    B, C = mat.shape
    W = packed_width(C)
    padded = jnp.zeros((B, W * 8), dtype=bool).at[:, :C].set(mat)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, None, :]
    return (padded.reshape(B, W, 8).astype(jnp.int32) * weights).sum(
        axis=-1).astype(jnp.uint8)


def unpack_verdicts(arr, n_cols: int) -> np.ndarray:
    """Host-side decode of a [B, W] uint8 bitmask readback back to the
    [B, n_cols] bool matrix eval_packed_jit would have returned."""
    a = np.asarray(arr)
    return np.unpackbits(a, axis=1, bitorder="little")[:, :n_cols].astype(bool)


def firing_columns(own_rule: np.ndarray, own_skipped: np.ndarray) -> np.ndarray:
    """Which-rule-fired attribution (ISSUE 9): the FIRST evaluator column
    that evaluated false and was not condition-skipped, per row — the same
    short-circuit order the reference pipeline denies in — or -1 for
    allowed rows (verdict ≡ all(skipped | rule), so a row is denied iff a
    firing column exists).  Pure vectorized numpy: one call per BATCH, the
    zero-per-request-Python contract of the native fast lane.

    Padded evaluator columns read TRUE_SLOT (rule=True) and can never
    fire.  Host-fallback rows past the fallback cap are denied fail-closed
    with rule[:]=False — they attribute to column 0, a synthetic denial
    documented in docs/observability.md."""
    fired = ~np.asarray(own_skipped, dtype=bool) & ~np.asarray(
        own_rule, dtype=bool)                                  # [B, E]
    first = fired.argmax(axis=1).astype(np.int32)              # [B]
    first[~fired.any(axis=1)] = -1
    return first


def unpack_attribution(packed, n_evaluators: int):
    """Per-batch decode of a bitpacked [B, W] uint8 readback into
    (verdict [B] uint8, firing [B] int32) — the native lane's one-shot
    column fold (bit 0 = own verdict, bits 1..E = rule results,
    E+1..2E = skipped)."""
    E = n_evaluators
    cols = unpack_verdicts(packed, 1 + 2 * E)
    verdict = cols[:, 0].astype(np.uint8)
    firing = firing_columns(cols[:, 1:1 + E], cols[:, 1 + E:1 + 2 * E])
    return verdict, firing


@partial(jax.jit, static_argnames=())
def eval_bitpacked_jit(params, attrs_val, members_c, cpu_dense, config_id,
                       attr_bytes=None, byte_ovf=None, attrs_num=None,
                       num_valid=None, rel_rows=None, member_ovf=None):
    """eval_packed_jit with the result bit-packed on device: the D2H
    readback is [B, ceil((1+2E)/8)] uint8 instead of [B, 1+2E] bool."""
    return _bitpack_rows(eval_packed_jit(
        params, attrs_val, members_c, cpu_dense, config_id,
        attr_bytes, byte_ovf, attrs_num, num_valid, rel_rows, member_ovf))


def _extra_operands(db) -> tuple:
    """The ISSUE 14 operand tail of one DeviceBatch, as jnp arrays (None
    entries stay None — structural, like the DFA lane)."""
    return tuple(
        jnp.asarray(a) if a is not None else None
        for a in (db.attrs_num, db.num_valid, db.rel_rows, db.member_ovf))


def dispatch_packed(params, db, bitpack: bool = False) -> "jax.Array":
    """Enqueue one compact batch (compiler/pack.py DeviceBatch) without
    blocking; returns the on-device packed [B, 1+2E] result — or the
    [B, W] uint8 bitmask with ``bitpack=True`` — for a deferred readback
    (jax async dispatch = transfer/compute of batch N+1 overlaps the
    readback of batch N)."""
    has_dfa = params["dfa_tables"] is not None
    fn = eval_bitpacked_jit if bitpack else eval_packed_jit
    return fn(
        params,
        jnp.asarray(db.attrs_val),
        jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense),
        jnp.asarray(db.config_id),
        jnp.asarray(db.attr_bytes) if has_dfa else None,
        jnp.asarray(db.byte_ovf) if has_dfa else None,
        *_extra_operands(db),
    )


# ---------------------------------------------------------------------------
# fused H2D staging: ONE host→device transfer per micro-batch
# ---------------------------------------------------------------------------
#
# The compact payload is 5-7 small tensors; each jnp.asarray is its own
# host→device transfer, and on a long link (the tunnel on this image; PCIe
# doorbells on a co-located chip) per-transfer latency stacks.  The fused
# path concatenates every operand's bytes into one contiguous uint8 staging
# buffer on host, ships it in a single transfer, and bitcast-decodes the
# operands back out INSIDE the jitted kernel (static layout → static slices;
# the decode is free relative to the transfer it replaces).
#
# Bitcast byte order must match numpy's little-endian view; _fused_probe
# verifies the round trip once per process and the engine falls back to
# per-operand transfers if the backend disagrees (big-endian hosts).

_FUSED_FIELDS = ("attrs_val", "members_c", "cpu_dense", "config_id",
                 "attr_bytes", "byte_ovf", "attrs_num", "num_valid",
                 "rel_rows", "member_ovf")


def fuse_batch(db) -> Tuple[np.ndarray, tuple]:
    """(staging buffer [N] uint8, static layout) for one DeviceBatch.  The
    layout — (field, dtype, shape, offset, nbytes) per operand — is
    hashable and static per (pad, eff) bucket, so it adds no jit variants
    beyond the existing shape grid."""
    segs = []
    layout = []
    off = 0
    for name in _FUSED_FIELDS:
        arr = getattr(db, name)
        if arr is None:
            continue
        a = np.ascontiguousarray(arr)
        flat = a.view(np.uint8).reshape(-1)
        layout.append((name, str(a.dtype), tuple(a.shape), off, flat.size))
        segs.append(flat)
        off += flat.size
    return np.concatenate(segs), tuple(layout)


def staged_h2d_bytes(db) -> int:
    """Exact request-operand bytes one launch of ``db`` stages host-to-
    device — the fused staging buffer size (sum of nbytes over the present
    _FUSED_FIELDS), identical for the per-operand fallback path.  Pure
    shape arithmetic for the kernel-cost ledger: no copy, no fuse."""
    total = 0
    for name in _FUSED_FIELDS:
        arr = getattr(db, name)
        if arr is not None:
            total += arr.nbytes
    return total


def _defuse(buf, layout):
    """Decode the staged operands out of the fused buffer (traced: static
    slices + bitcasts, no data movement beyond the one transfer)."""
    out = {}
    for name, dt, shape, off, size in layout:
        seg = jax.lax.slice_in_dim(buf, off, off + size)
        if dt == "bool":
            out[name] = seg.reshape(shape) != 0
        elif dt == "uint8":
            out[name] = seg.reshape(shape)
        else:
            npdt = np.dtype(dt)
            out[name] = jax.lax.bitcast_convert_type(
                seg.reshape(shape + (npdt.itemsize,)), npdt)
    return out


@partial(jax.jit, static_argnames=("layout",))
def eval_fused_jit(params, buf, layout):
    """eval over a fused staging buffer: ONE H2D transfer in, one
    bit-packed [B, ceil((1+2E)/8)] uint8 readback out (decode host-side
    with ``unpack_verdicts``)."""
    ops = _defuse(buf, layout)
    return _bitpack_rows(eval_packed_jit(
        params, ops["attrs_val"], ops["members_c"], ops["cpu_dense"],
        ops["config_id"], ops.get("attr_bytes"), ops.get("byte_ovf"),
        ops.get("attrs_num"), ops.get("num_valid"), ops.get("rel_rows"),
        ops.get("member_ovf"),
    ))


_FUSED_OK: Optional[bool] = None


@partial(jax.jit, static_argnames=("layout",))
def _defuse_probe(buf, layout):
    return tuple(_defuse(buf, layout).values())


def fused_h2d_supported() -> bool:
    """One-time probe that the backend's bitcast byte order matches numpy's
    view (little-endian); the engine degrades to per-operand transfers —
    never to wrong answers — when it does not."""
    global _FUSED_OK
    if _FUSED_OK is None:
        try:
            a16 = np.array([-7, 0, 1, 30000], dtype=np.int16)
            a32 = np.array([1, -2, 1 << 20], dtype=np.int32)
            buf = np.concatenate([a16.view(np.uint8).reshape(-1),
                                  a32.view(np.uint8).reshape(-1)])
            layout = (("attrs_val", "int16", (4,), 0, 8),
                      ("config_id", "int32", (3,), 8, 12))
            got16, got32 = _defuse_probe(jnp.asarray(buf), layout)
            _FUSED_OK = (np.array_equal(np.asarray(got16), a16)
                         and np.array_equal(np.asarray(got32), a32))
        except Exception:
            _FUSED_OK = False
    return _FUSED_OK


def dispatch_fused(params, db) -> "jax.Array":
    """Non-blocking launch of one compact batch with a single fused H2D
    transfer (falling back to per-operand transfers when the backend's
    bitcast disagrees with numpy byte order).  The result is the BIT-PACKED
    [B, W] uint8 readback (decode with ``unpack_verdicts``); the device→
    host copy starts eagerly so a later np.asarray only waits, never
    initiates."""
    try:
        from ..utils.metrics import observe_kernel_lane

        observe_kernel_lane(kernel_lane_of(params))
    except Exception:
        pass  # metrics are advisory; never fail a dispatch over them
    if params.get("fused") is not None:
        from . import fused_kernel as _fk

        return _fk.dispatch_megakernel(params, db)
    if fused_h2d_supported():
        buf, layout = fuse_batch(db)
        out = eval_fused_jit(params, jnp.asarray(buf), layout)
    else:
        out = dispatch_packed(params, db, bitpack=True)
    try:
        out.copy_to_host_async()
    except Exception:
        pass  # readback degrades to a blocking copy at np.asarray time
    return out


def eval_batch_jit(params, db) -> Tuple[np.ndarray, np.ndarray]:
    """Convenience wrapper: compact batch (compiler/pack.py DeviceBatch) →
    (own verdicts [B], full verdict matrix [B, G]) as numpy."""
    has_dfa = params["dfa_tables"] is not None
    own, verdict = _eval_jit(
        params,
        jnp.asarray(db.attrs_val),
        jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense),
        jnp.asarray(db.config_id),
        jnp.asarray(db.attr_bytes) if has_dfa else None,
        jnp.asarray(db.byte_ovf) if has_dfa else None,
        *_extra_operands(db),
    )
    return np.asarray(own), np.asarray(verdict)
