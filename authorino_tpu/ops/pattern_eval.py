"""Batched policy-evaluation kernel (pure JAX/XLA; static shapes, no
data-dependent control flow).

One call evaluates a micro-batch of requests against the *entire* compiled
rule corpus and returns per-request per-config allow verdicts.  This replaces
the reference's per-request goroutine fan-out + per-pattern gjson walk
(ref: pkg/service/auth_pipeline.go:150-182, pkg/jsonexp/expressions.go:59):
equal-priority rules across all configs fuse into one kernel launch
(SURVEY.md §2 P1/P2 mapping).

Two lanes:

  - ``matmul`` (default): gathers are pathological on TPU (scalar-unit
    loops), so every gather is reformulated as a one-hot matmul on the MXU —
    leaf operand gathers ride ``attrs @ attr_onehot``, the boolean circuit
    becomes per-level count matmuls (AND ≡ count==width, OR ≡ count>0), and
    per-config verdict extraction is an einsum against a one-hot of
    ``config_id``.  bf16 operands, f32 accumulation — exact for 0/1 values
    and interner ids < 2^24.
  - ``gather``: the direct jnp.take formulation (reference lane; also used
    when an interner outgrows exact-f32 range).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.compile import (
    FALSE_SLOT,
    OP_CPU,
    OP_EQ,
    OP_ERROR,
    OP_EXCL,
    OP_INCL,
    OP_NEQ,
    OP_REGEX_DFA,
    OP_TREE_CPU,
    TRUE_SLOT,
    CompiledPolicy,
)

__all__ = ["DevicePolicy", "to_device", "eval_verdicts", "eval_batch_jit"]

# exact integer range of f32 accumulation — larger interners must use the
# gather lane
_F32_EXACT = 1 << 24


def _eval_lane() -> str:
    return os.environ.get("AUTHORINO_TPU_EVAL_LANE", "matmul")


def _matmul_operands(policy: CompiledPolicy) -> dict:
    """One-hot / count matrices for the MXU lane (bf16; see module doc)."""
    L = policy.n_leaves
    A = policy.n_attrs
    attr_onehot = np.zeros((A, L), dtype=np.float32)
    attr_onehot[policy.leaf_attr, np.arange(L)] = 1.0

    # per-level count matrices over the buffer prefix visible to that level
    level_mats = []
    cursor = 2 + L  # TRUE/FALSE slots + leaf block
    for children, is_and in policy.levels:
        rows, width = children.shape
        m = np.zeros((rows, cursor), dtype=np.float32)
        np.add.at(m, (np.repeat(np.arange(rows), width), children.reshape(-1)), 1.0)
        level_mats.append((m, width))
        cursor += rows

    # eval-table one-hots over the full buffer
    G, E = policy.eval_rule.shape
    rule_m = np.zeros((G * E, cursor), dtype=np.float32)
    rule_m[np.arange(G * E), policy.eval_rule.reshape(-1)] = 1.0
    cond_m = np.zeros((G * E, cursor), dtype=np.float32)
    cond_m[np.arange(G * E), policy.eval_cond.reshape(-1)] = 1.0
    return {
        "attr_onehot": attr_onehot.astype(jnp.bfloat16),
        "level_mats": tuple(
            (m.astype(jnp.bfloat16), np.int32(w)) for m, w in level_mats
        ),
        "rule_m": rule_m.astype(jnp.bfloat16),
        "cond_m": cond_m.astype(jnp.bfloat16),
    }


def to_device(policy: CompiledPolicy, device=None) -> dict:
    """Upload a compiled corpus's operands as a pytree of device arrays.
    The engine double-buffers these and swaps atomically on reconcile
    (SURVEY.md §3.4: rule-tensor compile + device upload on index Set)."""
    put = partial(jax.device_put, device=device) if device is not None else jax.device_put
    lane = _eval_lane()
    if lane == "matmul" and len(policy.interner) >= _F32_EXACT:
        lane = "gather"  # ids no longer exact in f32 accumulation
    mm = jax.tree.map(put, _matmul_operands(policy)) if lane == "matmul" else None
    # per-dfa-row byte-tensor slot (attr → slot mapping folded in here)
    dfa_byte_slot = np.maximum(policy.attr_byte_slot[policy.dfa_leaf_attr], 0)
    return {
        "matmul": mm,
        "leaf_op": put(jnp.asarray(policy.leaf_op)),
        "leaf_attr": put(jnp.asarray(policy.leaf_attr)),
        "leaf_const": put(jnp.asarray(policy.leaf_const)),
        "levels": tuple(
            (put(jnp.asarray(children)), put(jnp.asarray(is_and)))
            for children, is_and in policy.levels
        ),
        "eval_cond": put(jnp.asarray(policy.eval_cond)),
        "eval_rule": put(jnp.asarray(policy.eval_rule)),
        "eval_has_cond": put(jnp.asarray(policy.eval_has_cond)),
        # device regex lane; None (a static pytree node, not a traced leaf)
        # when the corpus has no DFA-compilable regexes, so the kernel's
        # python-level `is None` check specializes at trace time
        "dfa_tables": put(jnp.asarray(policy.dfa_tables)) if policy.n_byte_attrs else None,
        "dfa_accept": put(jnp.asarray(policy.dfa_accept)) if policy.n_byte_attrs else None,
        "dfa_byte_slot": put(jnp.asarray(dfa_byte_slot.astype(np.int32))) if policy.n_byte_attrs else None,
        "leaf_dfa_row": put(jnp.asarray(policy.leaf_dfa_row)) if policy.n_byte_attrs else None,
    }


DevicePolicy = dict


def eval_verdicts(
    params: DevicePolicy,
    attrs_val: jnp.ndarray,      # [B, A] int32
    attrs_members: jnp.ndarray,  # [B, A, K] int32
    overflow: jnp.ndarray,       # [B, A] bool
    cpu_lane: jnp.ndarray,       # [B, L] bool
    attr_bytes: Optional[jnp.ndarray] = None,  # [B, NB, LB] uint8
    byte_ovf: Optional[jnp.ndarray] = None,    # [B, NB] bool
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """Returns (verdict [B, G] bool, (rule_results [B, G, E], skipped [B, G, E]))."""
    leaf_op = params["leaf_op"]          # [L]
    leaf_attr = params["leaf_attr"]      # [L]
    leaf_const = params["leaf_const"]    # [L]

    B = attrs_val.shape[0]

    # ---- leaf evaluation -------------------------------------------------
    val = jnp.take(attrs_val, leaf_attr, axis=1)            # [B, L]
    eq = val == leaf_const[None, :]
    memb = jnp.take(attrs_members, leaf_attr, axis=1)       # [B, L, K]
    incl = jnp.any(memb == leaf_const[None, :, None], axis=-1)
    ovf = jnp.take(overflow, leaf_attr, axis=1)             # [B, L]

    # ---- device regex lane: DFA scan over value bytes --------------------
    if params["dfa_tables"] is not None and attr_bytes is not None:
        tables = params["dfa_tables"]          # [R, S, 256] uint8
        R = tables.shape[0]
        row_idx = jnp.arange(R)[None, :]
        row_bytes = jnp.take(attr_bytes, params["dfa_byte_slot"], axis=1)  # [B, R, LB]

        def dfa_step(states, byte_col):  # states [B,R] i32, byte_col [B,R] u8
            nxt = tables[row_idx, states, byte_col.astype(jnp.int32)]
            return nxt.astype(jnp.int32), None

        init = jnp.zeros((B, R), dtype=jnp.int32)
        final, _ = jax.lax.scan(dfa_step, init, jnp.transpose(row_bytes, (2, 0, 1)))
        dfa_row_res = params["dfa_accept"][row_idx, final]   # [B, R]
        leaf_dfa = jnp.take(dfa_row_res, params["leaf_dfa_row"], axis=1)  # [B, L]
        leaf_slot = jnp.take(params["dfa_byte_slot"], params["leaf_dfa_row"])
        leaf_bovf = jnp.take(byte_ovf, leaf_slot, axis=1)    # [B, L]
        dfa_leaf_val = jnp.where(leaf_bovf, cpu_lane, leaf_dfa)
    else:
        dfa_leaf_val = cpu_lane  # regexes ride the CPU lane entirely

    op = leaf_op[None, :]
    res = jnp.where(
        op == OP_EQ, eq,
        jnp.where(
            op == OP_NEQ, ~eq,
            jnp.where(
                op == OP_INCL, jnp.where(ovf, cpu_lane, incl),
                jnp.where(
                    op == OP_EXCL, jnp.where(ovf, cpu_lane, ~incl),
                    jnp.where(
                        op == OP_REGEX_DFA, dfa_leaf_val,
                        # OP_CPU (regex) and OP_TREE_CPU ride the lane; OP_ERROR → False
                        jnp.where((op == OP_CPU) | (op == OP_TREE_CPU), cpu_lane, False),
                    ),
                ),
            ),
        ),
    )

    # ---- boolean-circuit reduction, level by level -----------------------
    true_col = jnp.ones((B, 1), dtype=bool)
    false_col = jnp.zeros((B, 1), dtype=bool)
    buffer = jnp.concatenate([true_col, false_col, res], axis=1)
    for children, is_and in params["levels"]:
        ch = jnp.take(buffer, children.reshape(-1), axis=1)
        ch = ch.reshape(B, children.shape[0], children.shape[1])
        node = jnp.where(is_and[None, :], jnp.all(ch, axis=-1), jnp.any(ch, axis=-1))
        buffer = jnp.concatenate([buffer, node], axis=1)

    # ---- per-config verdicts: ∧ over evaluators of (¬cond ∨ rule) --------
    cond = jnp.take(buffer, params["eval_cond"].reshape(-1), axis=1)
    rule = jnp.take(buffer, params["eval_rule"].reshape(-1), axis=1)
    G, E = params["eval_rule"].shape
    cond = cond.reshape(B, G, E)
    rule = rule.reshape(B, G, E)
    skipped = params["eval_has_cond"][None, :, :] & ~cond
    contrib = jnp.where(skipped, True, rule)
    verdict = jnp.all(contrib, axis=-1)                      # [B, G]
    return verdict, (rule, skipped)


def forward(params, attrs_val, attrs_members, overflow, cpu_lane, config_id,
            attr_bytes=None, byte_ovf=None):
    """Canonical forward step: encoded micro-batch → (own verdicts [B],
    full verdict matrix [B, G]).  The single source of truth for
    verdict-selection logic (PolicyModel and the engine both use it)."""
    verdict, _ = eval_verdicts(
        params, attrs_val, attrs_members, overflow, cpu_lane, attr_bytes, byte_ovf
    )
    # select each request's own config column
    own = jnp.take_along_axis(verdict, config_id[:, None], axis=1)[:, 0]
    return own, verdict


_eval_jit = jax.jit(forward)


@partial(jax.jit, static_argnames=())
def eval_full_jit(params, attrs_val, attrs_members, overflow, cpu_lane, config_id,
                  attr_bytes=None, byte_ovf=None):
    """Like _eval_jit but also returns each request's own per-evaluator rule
    results + skipped flags [B, E] — what the pipeline's batched
    pattern-matching evaluators consume (runtime/engine.py)."""
    verdict, (rule, skipped) = eval_verdicts(
        params, attrs_val, attrs_members, overflow, cpu_lane, attr_bytes, byte_ovf
    )
    own = jnp.take_along_axis(verdict, config_id[:, None], axis=1)[:, 0]
    idx = config_id[:, None, None]
    own_rule = jnp.take_along_axis(rule, idx, axis=1)[:, 0, :]
    own_skipped = jnp.take_along_axis(skipped, idx, axis=1)[:, 0, :]
    return own, own_rule, own_skipped


def eval_batch_jit(params, encoded) -> Tuple[np.ndarray, np.ndarray]:
    """Convenience wrapper: encoded batch (numpy) → (own verdicts [B],
    full verdict matrix [B, G]) as numpy."""
    has_dfa = params["dfa_tables"] is not None
    own, verdict = _eval_jit(
        params,
        jnp.asarray(encoded.attrs_val),
        jnp.asarray(encoded.attrs_members),
        jnp.asarray(encoded.overflow),
        jnp.asarray(encoded.cpu_lane),
        jnp.asarray(encoded.config_id),
        jnp.asarray(encoded.attr_bytes) if has_dfa else None,
        jnp.asarray(encoded.byte_ovf) if has_dfa else None,
    )
    return np.asarray(own), np.asarray(verdict)
