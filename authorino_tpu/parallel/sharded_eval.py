"""Tensor-parallel (rules-axis) + data-parallel (batch-axis) policy
evaluation over a jax.sharding.Mesh.

The reference scales horizontally by label-selector sharding of AuthConfigs
across replicas (ref: controllers/label_selector.go:14-45,
docs/user-guides/sharding.md).  The TPU-era equivalent (SURVEY.md §2 P3):
partition the *config axis* of the rule corpus across mesh shards — each
shard holds the full boolean circuit of its configs, so the tree reduction
stays shard-local and the only cross-shard communication is the final
verdict gather, which XLA lays onto ICI.

Layout:
  - configs are round-robined into ``mp`` groups; each group compiles as its
    own sub-corpus against a shared interner, with ShapeTargets forcing
    identical operand shapes (incl. DFA row/state/byte-slot axes, so the
    device regex lane rides the mesh too); arrays stack on a leading [S] axis
  - mesh ('dp', 'mp'): batch is sharded over dp, the [S] corpus axis over mp
  - shard_map evaluates each (dp, mp) block locally → verdict [B, S*G] plus
    per-evaluator rule/skipped [B, S*G, E] — the same outputs as the
    single-corpus ``eval_full_jit``, so PolicyEngine can serve from a
    sharded snapshot when more than one device is present
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compiler.compile import (
    CompiledPolicy,
    ConfigRules,
    ShapeTargets,
    compile_corpus,
)
from ..compiler.encode import encode_batch
from ..compiler.intern import StringInterner
from ..ops.pattern_eval import (
    _bitpack_rows,
    eval_verdicts,
    to_device,
    unpack_verdicts,
)

__all__ = ["ShardedPolicyModel", "build_mesh"]

log = logging.getLogger("authorino_tpu.sharded_eval")


# jitted sharded steps cached per (mesh, has_dfa, has_matmul, n_levels):
# reconcile-time apply_snapshot builds a fresh ShardedPolicyModel, and a
# per-model jax.jit(shard_map(...)) closure would force a full XLA recompile
# on every snapshot even at unchanged shapes — the sharded analog of the
# module-level eval_packed_jit cache on the single-corpus path.  The flags
# pin the params/specs pytree STRUCTURE (lane presence changes it), so a
# gather-lane model can never reuse a matmul-traced step.
_STEP_CACHE: Dict[Tuple[Mesh, bool, bool, int], Any] = {}


def _sharded_step(mesh: Mesh, has_dfa: bool, has_matmul: bool, n_levels: int, specs):
    """Own-config evaluation step over the mesh: each mp shard evaluates its
    sub-corpus, selects the rows of requests whose config it owns, and the
    tiny [B], [B, E] results combine with one psum over 'mp' — so the
    device→host readback is own-rows only, never the [B, S*G(, E)] matrices
    (the sharded analog of eval_packed_jit's one-small-readback contract).
    ``specs`` mirrors the stacked-params structure (P('mp') on every leaf);
    the cache key's flags pin that structure."""
    key = (mesh, has_dfa, has_matmul, n_levels)
    step = _STEP_CACHE.get(key)
    if step is not None:
        return step

    def local_eval(params, attrs_val, members_c, cpu_dense,
                   attr_bytes, byte_ovf, shard_of, row_of):
        # params leading axis is the local S slice (size 1 per mp shard)
        sq = jax.tree_util.tree_map(lambda a: a[0], params)
        verdict, (rule, skipped) = eval_verdicts(
            sq,
            attrs_val[:, 0],
            members_c[:, 0],
            cpu_dense[:, 0],
            attr_bytes[:, 0] if has_dfa else None,
            byte_ovf[:, 0] if has_dfa else None,
        )
        # own-config one-hot rows local to this shard (other shards see all-
        # False masks for the request); psum over mp merges the disjoint parts
        G = verdict.shape[1]
        mp_idx = jax.lax.axis_index("mp")
        mask = (shard_of == mp_idx)[:, None] & (
            row_of[:, None] == jnp.arange(G, dtype=row_of.dtype)[None, :]
        )                                                        # [B_l, G]
        own = jnp.any(verdict & mask, axis=1)
        own_rule = jnp.any(rule & mask[:, :, None], axis=1)      # [B_l, E]
        own_skip = jnp.any(skipped & mask[:, :, None], axis=1)
        merged = jax.lax.psum(
            jnp.concatenate(
                [own[:, None], own_rule, own_skip], axis=1
            ).astype(jnp.int32),
            "mp",
        )
        return merged > 0                                        # [B_l, 1+2E]

    byte_specs = (
        (P("dp", "mp", None, None), P("dp", "mp", None))
        if has_dfa
        else (None, None)
    )
    mapped = jax.shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(
            specs,
            P("dp", "mp", None),
            P("dp", "mp", None, None),
            P("dp", "mp", None),
        ) + byte_specs + (P("dp"), P("dp")),
        out_specs=P("dp"),
    )

    def bitpacked_step(params, *operands):
        # D2H readback rides the link as a u8 bitmask (8 verdicts/byte —
        # decode host-side with ops.pattern_eval.unpack_verdicts), same
        # packed-readback contract as the single-corpus eval_fused_jit
        return _bitpack_rows(mapped(params, *operands))

    step = jax.jit(bitpacked_step)
    _STEP_CACHE[key] = step
    return step


def build_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None) -> Mesh:
    devices = np.asarray(jax.devices()[: n_devices or len(jax.devices())])
    n = devices.size
    if dp is None:
        dp = 2 if n % 2 == 0 and n > 1 else 1
    mp = n // dp
    return Mesh(devices[: dp * mp].reshape(dp, mp), ("dp", "mp"))


@dataclass
class _ShardedEncoded:
    attrs_val: np.ndarray      # [B, S, A]
    members_c: np.ndarray      # [B, S, M, K] — compact membership rows
    cpu_dense: np.ndarray      # [B, S, C] — dense CPU-lane columns
    attr_bytes: Optional[np.ndarray]  # [B, S, NB, LB] uint8 (None: no DFA lane)
    byte_ovf: Optional[np.ndarray]    # [B, S, NB] bool
    shard_of: np.ndarray       # [B] which shard owns the request's config
    row_of: np.ndarray         # [B] row within that shard
    host_fallback: np.ndarray  # [B] bool — exact re-decision on host


class ShardedPolicyModel:
    """Rule corpus partitioned over the 'mp' mesh axis; batch over 'dp'."""

    def __init__(self, configs: Sequence[ConfigRules], mesh: Mesh, members_k: int = 16):
        self.mesh = mesh
        S = mesh.shape["mp"]
        self.n_shards = S
        interner = StringInterner()
        groups: List[List[ConfigRules]] = [[] for _ in range(S)]
        self.locator: Dict[str, Tuple[int, int]] = {}
        for i, cfg in enumerate(configs):
            shard = i % S
            self.locator[cfg.name] = (shard, len(groups[shard]))
            groups[shard].append(cfg)

        # two-pass compile: natural shapes → union targets → final compile.
        # The union carries the DFA row/state/byte axes, so shards with
        # regexes stack their device-DFA tables and regex-free shards carry
        # a dummy lane of the same shape.  One dfa_cache spans both passes
        # and all shards: each distinct regex determinizes exactly once.
        dfa_cache: Dict[str, Any] = {}
        first = [
            compile_corpus(g, members_k=members_k, interner=interner, dfa_cache=dfa_cache)
            for g in groups
        ]
        targets = ShapeTargets.union([p.shape_targets() for p in first])
        self.shards: List[CompiledPolicy] = [
            compile_corpus(g, members_k=members_k, interner=interner, targets=targets,
                           dfa_cache=dfa_cache)
            for g in groups
        ]
        self.has_dfa = self.shards[0].n_byte_attrs > 0
        # targets unified every operand shape (incl. eval-table rows), so
        # the whole per-shard device pytree — gather lane, matmul lane, DFA
        # lane — stacks on a leading [S] axis with one tree.map
        self.configs_per_shard = self.shards[0].n_configs
        # [S, G] verdict-cache eligibility, indexed (shard_of, row_of) by
        # the engine's dedup/cache encode stage
        self.config_cacheable = np.stack(
            [p.config_cacheable for p in self.shards])
        # host-side staging: stack numpy operands, then ONE mesh-sharded
        # device_put per leaf — each shard's slice transfers straight to its
        # devices (no transient 2-3x corpus copy on device 0)
        per_shard_params = [to_device(p, host=True) for p in self.shards]
        self.params = jax.tree.map(
            lambda *xs: np.stack(xs), *per_shard_params
        )
        self.has_matmul = self.params.get("matmul") is not None
        specs = jax.tree.map(lambda _: P("mp"), self.params)
        self.params = jax.tree.map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            self.params, specs,
        )
        n_levels = len(self.shards[0].levels)
        self._step = _sharded_step(
            mesh, self.has_dfa, self.has_matmul, n_levels, specs
        )

    # ------------------------------------------------------------------

    def encode(self, docs: Sequence[Any], config_names: Sequence[str], batch_pad: int = 0) -> _ShardedEncoded:
        from ..compiler.intern import EMPTY_ID, PAD
        from ..compiler.pack import pack_batch

        B = max(len(docs), 1)
        if batch_pad and batch_pad > B:
            B = batch_pad
        dp = self.mesh.shape["dp"]
        if B % dp:
            B += dp - B % dp
        S = self.n_shards
        p0 = self.shards[0]
        A, K = p0.n_attrs, p0.members_k
        M, C = p0.n_member_attrs, p0.n_cpu_leaves
        attrs_val = np.full((B, S, A), EMPTY_ID, dtype=np.int32)
        members_c = np.full((B, S, M, K), PAD, dtype=np.int32)
        cpu_dense = np.zeros((B, S, C), dtype=bool)
        if self.has_dfa:
            from ..compiler.compile import DFA_VALUE_BYTES

            NB = p0.n_byte_attrs
            attr_bytes = np.zeros((B, S, NB, DFA_VALUE_BYTES), dtype=np.uint8)
            byte_ovf = np.zeros((B, S, NB), dtype=bool)
        else:
            attr_bytes = byte_ovf = None
        shard_of = np.zeros((B,), dtype=np.int32)
        row_of = np.zeros((B,), dtype=np.int32)
        host_fallback = np.zeros((B,), dtype=bool)
        # group requests by owning shard and encode each group in ONE
        # batched call (per-request encode_batch would dominate the hot path)
        by_shard: Dict[int, List[int]] = {}
        for r, (doc, name) in enumerate(zip(docs, config_names)):
            shard, row = self.locator[name]
            shard_of[r], row_of[r] = shard, row
            by_shard.setdefault(shard, []).append(r)
        for shard, rs in by_shard.items():
            enc = encode_batch(
                self.shards[shard],
                [docs[r] for r in rs],
                [int(row_of[r]) for r in rs],
            )
            db = pack_batch(self.shards[shard], enc, trim_bytes=False)
            attrs_val[rs, shard] = db.attrs_val[: len(rs)]
            members_c[rs, shard] = db.members_c[: len(rs)]
            cpu_dense[rs, shard] = db.cpu_dense[: len(rs)]
            if self.has_dfa:
                # per-shard batches may be byte-trimmed (pack._trim_bytes);
                # assign into the prefix, then trim the assembled tensor once
                lb = db.attr_bytes.shape[-1]
                attr_bytes[rs, shard, :, :lb] = db.attr_bytes[: len(rs)]
                byte_ovf[rs, shard] = db.byte_ovf[: len(rs)]
            host_fallback[rs] = db.host_fallback[: len(rs)]
        if self.has_dfa:
            from ..compiler.pack import _trim_bytes

            attr_bytes = _trim_bytes(attr_bytes)
        return _ShardedEncoded(
            attrs_val, members_c, cpu_dense, attr_bytes, byte_ovf,
            shard_of, row_of, host_fallback,
        )

    def row_keys(self, encoded: _ShardedEncoded, n: int):
        """Canonical per-row keys for dedup + the verdict cache: the full
        operand bytes plus shard_of/row_of (config identity on the mesh)
        and the lossy-row flag (compiler/pack.py row_key_bytes doc)."""
        from ..compiler.pack import row_key_bytes

        return row_key_bytes(
            [encoded.shard_of, encoded.row_of, encoded.attrs_val,
             encoded.members_c, encoded.cpu_dense, encoded.attr_bytes,
             encoded.byte_ovf, encoded.host_fallback], n)

    def select_rows(self, encoded: _ShardedEncoded, rows: Sequence[int],
                    batch_pad: int = 0) -> _ShardedEncoded:
        """Row-subset view for dedup dispatch: the unique rows re-padded to
        ``batch_pad`` (dp-aligned like encode) by repeating the first row —
        padding rows' verdicts are discarded by the inverse fan-out."""
        u = len(rows)
        B = max(u, 1, batch_pad)
        dp = self.mesh.shape["dp"]
        if B % dp:
            B += dp - B % dp
        fill = rows[0] if u else 0
        idx = np.asarray(list(rows) + [fill] * (B - u))

        def take(a):
            return a[idx] if a is not None else None

        return _ShardedEncoded(
            take(encoded.attrs_val), take(encoded.members_c),
            take(encoded.cpu_dense), take(encoded.attr_bytes),
            take(encoded.byte_ovf), take(encoded.shard_of),
            take(encoded.row_of), take(encoded.host_fallback),
        )

    def dispatch_full(self, encoded: _ShardedEncoded):
        """Non-blocking launch: returns the ON-DEVICE packed own-rows
        result [B, 1+2E] (readback copy started eagerly), so the caller can
        keep further batches in flight while this one rides the link — the
        sharded mirror of the engine's pipelined dispatch window."""
        packed = self._step(
            self.params,
            jnp.asarray(encoded.attrs_val),
            jnp.asarray(encoded.members_c),
            jnp.asarray(encoded.cpu_dense),
            jnp.asarray(encoded.attr_bytes) if self.has_dfa else None,
            jnp.asarray(encoded.byte_ovf) if self.has_dfa else None,
            jnp.asarray(encoded.shard_of),
            jnp.asarray(encoded.row_of),
        )
        try:
            packed.copy_to_host_async()
        except Exception:
            pass  # readback degrades to a blocking copy at np.asarray time
        return packed

    def _run_step(self, encoded: _ShardedEncoded) -> np.ndarray:
        """Own-rows result [B, 1+2E] bool, decoded from the bit-packed
        readback — one small (u8 bitmask) transfer per batch (own-config
        selection happens on device, inside the shard_map)."""
        E = int(self.shards[0].eval_rule.shape[1])
        return unpack_verdicts(
            np.asarray(self.dispatch_full(encoded)), 1 + 2 * E)

    def apply(self, encoded: _ShardedEncoded) -> np.ndarray:
        return self._run_step(encoded)[:, 0]

    def apply_full(self, encoded: _ShardedEncoded) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Own-config (verdict [B], rule results [B, E], skipped [B, E]) —
        the same contract as the single-corpus ``eval_full_jit``."""
        packed = self._run_step(encoded)
        E = int(self.shards[0].eval_rule.shape[1])
        own = packed[:, 0]
        own_rule = packed[:, 1:1 + E].copy()      # writable: host fallback
        own_skipped = packed[:, 1 + E:1 + 2 * E].copy()
        return own, own_rule, own_skipped

    def host_decide(self, config_name: str, doc: Any) -> Tuple[np.ndarray, np.ndarray]:
        """Exact host-oracle decision for ONE request of this mesh corpus:
        (rule_results [E], skipped [E]) with the kernel's padding/tail
        semantics.  The engine's degraded lane (runtime/engine.py
        _degrade_batch) re-decides whole batches through this when the
        device path fails or the circuit breaker is open — the sharded
        mirror of host_results on the single corpus."""
        from ..models.policy_model import host_results

        shard, row = self.locator[config_name]
        return host_results(self.shards[shard], doc, int(row))[1:]

    def host_decide_many(self, config_names: Sequence[str],
                         docs: Sequence[Any]) -> List[Optional[Tuple[np.ndarray, np.ndarray]]]:
        """Batch form of host_decide for the engine's degraded and brownout
        lanes: one (rule_results [E], skipped [E]) per request, or None for
        a row whose oracle run itself failed (the caller resolves those
        typed UNAVAILABLE, fail closed — one bad row never fails its
        batchmates)."""
        out: List[Optional[Tuple[np.ndarray, np.ndarray]]] = []
        for name, doc in zip(config_names, docs):
            try:
                out.append(self.host_decide(name, doc))
            except Exception:
                log.exception("host oracle failed for config %r", name)
                out.append(None)
        return out

    def apply_fallback(self, host_fallback: np.ndarray, docs: Sequence[Any],
                       config_names: Sequence[str], own_rule: np.ndarray,
                       own_skipped: np.ndarray,
                       max_fallback: Optional[int] = None) -> None:
        """Host-oracle completion for membership-overflow rows — the ONE
        definition shared by finalize_full and the engine's pipelined
        (dedup-aware) finalize, so fallback semantics can't drift between
        the blocking and serving paths.  Mutates own_rule/own_skipped in
        place; at most ``max_fallback`` rows re-decide (beyond the cap:
        fail-closed deny + auth_server_host_fallback_shed_total)."""
        from ..models.policy_model import apply_host_fallback, host_results
        from ..utils import metrics as metrics_mod

        def decide(r: int):
            shard, row = self.locator[config_names[r]]
            return host_results(self.shards[shard], docs[r], int(row))[1:]

        fallback_rows = np.nonzero(host_fallback[: len(docs)])[0]
        metrics_mod.batch_host_fallback.labels("engine").observe(
            len(fallback_rows))
        apply_host_fallback(
            decide, fallback_rows,
            own_rule, own_skipped, max_fallback,
        )

    def finalize_full(
        self, packed, enc: _ShardedEncoded, docs: Sequence[Any],
        config_names: Sequence[str], max_fallback: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Completion half of run_full: takes the (device or already-numpy)
        packed result of ``dispatch_full(enc)`` and applies the host-oracle
        fallback.  Runs on the engine's completion stage under pipelining."""
        packed = np.asarray(packed)
        E = int(self.shards[0].eval_rule.shape[1])
        if packed.dtype == np.uint8:
            packed = unpack_verdicts(packed, 1 + 2 * E)  # bit-packed readback
        own_rule = packed[:, 1:1 + E].copy()
        own_skipped = packed[:, 1 + E:1 + 2 * E].copy()
        self.apply_fallback(enc.host_fallback, docs, config_names,
                            own_rule, own_skipped, max_fallback)
        return own_rule, own_skipped

    def run_full(
        self, docs: Sequence[Any], config_names: Sequence[str], batch_pad: int = 0,
        max_fallback: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Serving entry (PolicyEngine batch contract): per-request
        per-evaluator (rule_results [B, E], skipped [B, E]).  Blocking
        convenience composition of encode → dispatch_full → finalize_full;
        the engine's pipeline calls the three stages separately so batch
        N+1 encodes while batch N is still on the wire."""
        enc = self.encode(docs, config_names, batch_pad=batch_pad)
        return self.finalize_full(self.dispatch_full(enc), enc, docs,
                                  config_names, max_fallback=max_fallback)

    def decide(self, docs: Sequence[Any], config_names: Sequence[str]) -> List[bool]:
        from ..models.policy_model import host_results

        enc = self.encode(docs, config_names)
        own = self.apply(enc)
        out = [bool(b) for b in own[: len(docs)]]
        for r in np.nonzero(enc.host_fallback[: len(docs)])[0]:
            shard, row = self.locator[config_names[r]]
            out[r], _, _ = host_results(self.shards[shard], docs[r], int(row))
        return out
