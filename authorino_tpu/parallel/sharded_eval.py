"""Tensor-parallel (rules-axis) + data-parallel (batch-axis) policy
evaluation over a jax.sharding.Mesh.

The reference scales horizontally by label-selector sharding of AuthConfigs
across replicas (ref: controllers/label_selector.go:14-45,
docs/user-guides/sharding.md).  The TPU-era equivalent (SURVEY.md §2 P3):
partition the *config axis* of the rule corpus across mesh shards — each
shard holds the full boolean circuit of its configs, so the tree reduction
stays shard-local and the only cross-shard communication is the final
verdict gather, which XLA lays onto ICI.

Layout:
  - configs are round-robined into ``mp`` groups; each group compiles as its
    own sub-corpus against a shared interner, with ShapeTargets forcing
    identical operand shapes (incl. DFA row/state/byte-slot axes, so the
    device regex lane rides the mesh too); arrays stack on a leading [S] axis
  - mesh ('dp', 'mp'): batch is sharded over dp, the [S] corpus axis over mp
  - shard_map evaluates each (dp, mp) block locally → verdict [B, S*G] plus
    per-evaluator rule/skipped [B, S*G, E] — the same outputs as the
    single-corpus ``eval_full_jit``, so PolicyEngine can serve from a
    sharded snapshot when more than one device is present

Mesh as the first-class lane (ISSUE 11):

  - **shard-map port**: ``jax.shard_map`` only exists on newer jax; this
    image's jax 0.4.37 ships it as ``jax.experimental.shard_map.shard_map``.
    ``_shard_map`` resolves the fast path when present and falls back to the
    experimental module — the seed AttributeError family converts to
    passing tests.
  - **grid relief**: each mp shard compiles only its sub-corpus, so its
    member-attr grid M is ~1/mp of the monolithic corpus — the per-device
    membership payload budget (M × K) supports a proportionally LARGER
    compact K.  ``members_k`` is boosted to ``min(members_k * mp,
    max(members_k, MEMBERS_K_RELIEF_CAP))``: requests whose role lists
    overflowed the single-corpus K (the ``cpu-grid-overflow`` host-oracle
    rows) ride the kernel when the corpus is rule-sharded across ≥2
    devices.
  - **two-phase staging**: ``defer_upload=True`` compiles and stacks the
    operands HOST-side only; ``upload()`` stages them onto the mesh.  The
    engine's --strict-verify lints the packed shards between the two — a
    corrupt corpus is rejected before any byte touches a device, matching
    the single-corpus ordering (the PR 4 caveat, fixed).
  - **per-shard delta uploads**: ``upload(prev=...)`` diffs the stacked
    host views; the leading axis of every stacked leaf IS the shard axis,
    so ``plan_delta``'s changed-leading-rows mode ships bytes only to the
    shard(s) a mutation touched (measured per shard in
    auth_server_mesh_shard_upload_bytes).
  - **per-device failover**: ``dispatch_routed`` probes the fault plane per
    device, keeps per-DEVICE circuit breakers (runtime/breaker.py
    DeviceBreakerSet, process-wide per mesh so state survives reconciles),
    and re-dispatches a batch that failed on one device to the healthy
    device with the emptiest in-flight window (occupancy-aware routing) —
    host-oracle degrade only begins once EVERY device is down
    (MeshUnavailable).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compiler.compile import (
    CompiledPolicy,
    ConfigRules,
    ShapeTargets,
    compile_corpus,
)
from ..compiler.encode import encode_batch
from ..compiler.intern import StringInterner
from ..ops.pattern_eval import (
    _bitpack_rows,
    eval_verdicts,
    packed_width,
    to_device,
    unpack_verdicts,
)
from ..runtime.kernel_cost import LEDGER

__all__ = ["ShardedPolicyModel", "build_mesh", "MeshUnavailable",
           "MEMBERS_K_RELIEF_CAP", "flat_config_rows"]


def flat_config_rows(shards, rows, configs_per_shard):
    """Flatten mesh (shard, row) config coordinates to the single flat row
    key the heat map, the per-authconfig telemetry bins and the tenant QoS
    folds (ISSUE 15) all share: ``shard * configs_per_shard + row``.  One
    vectorized expression — callers pass whole batch arrays."""
    import numpy as _np

    return (_np.asarray(shards, dtype=_np.int64) * int(configs_per_shard)
            + _np.asarray(rows, dtype=_np.int64))

log = logging.getLogger("authorino_tpu.sharded_eval")

# jax.shard_map is the stable spelling on newer jax; 0.4.37 (this image)
# only has the experimental module.  Resolve once at import.
try:
    _shard_map = jax.shard_map  # type: ignore[attr-defined]
except AttributeError:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

# grid relief ceiling: rule-sharding shrinks each shard's member-attr grid
# ~1/mp, so the compact membership K can grow ~mp× inside the same
# per-device payload budget.  64 covers any operationally plausible role
# list; beyond it the host-fallback lane remains (exactness is never K's
# job — K only decides which rows ride the kernel).
MEMBERS_K_RELIEF_CAP = 64


class MeshUnavailable(RuntimeError):
    """Every mesh device is down (breakers open / probes failing): the
    caller's host-oracle degrade path is the only lane left."""


# jitted sharded steps cached per (mesh, lane flags, n_levels):
# reconcile-time apply_snapshot builds a fresh ShardedPolicyModel, and a
# per-model jax.jit(shard_map(...)) closure would force a full XLA recompile
# on every snapshot even at unchanged shapes — the sharded analog of the
# module-level eval_packed_jit cache on the single-corpus path.  The flags
# pin the params/specs pytree STRUCTURE (lane presence changes it), so a
# gather-lane model can never reuse a matmul-traced step.  ``extras`` is
# the (has_num, has_rel, has_ovf, has_fused) tuple of the ISSUE 14 operand
# lanes plus the ISSUE 17 fused-layout subtree (structure-changing too).
_STEP_CACHE: Dict[Tuple[Mesh, bool, bool, int, tuple], Any] = {}


def _sharded_step(mesh: Mesh, has_dfa: bool, has_matmul: bool, n_levels: int,
                  specs,
                  extras: tuple = (False, False, False, False)):
    """Own-config evaluation step over the mesh: each mp shard evaluates its
    sub-corpus, selects the rows of requests whose config it owns, and the
    tiny [B], [B, E] results combine with one psum over 'mp' — so the
    device→host readback is own-rows only, never the [B, S*G(, E)] matrices
    (the sharded analog of eval_packed_jit's one-small-readback contract).
    ``specs`` mirrors the stacked-params structure (P('mp') on every leaf);
    the cache key's flags pin that structure."""
    has_num, has_rel, has_ovf = extras[:3]
    key = (mesh, has_dfa, has_matmul, n_levels, extras)
    step = _STEP_CACHE.get(key)
    if step is not None:
        return step

    def local_eval(params, attrs_val, members_c, cpu_dense,
                   attr_bytes, byte_ovf, attrs_num, num_valid, rel_rows,
                   member_ovf, shard_of, row_of):
        # params leading axis is the local S slice (size 1 per mp shard)
        sq = jax.tree_util.tree_map(lambda a: a[0], params)
        verdict, (rule, skipped) = eval_verdicts(
            sq,
            attrs_val[:, 0],
            members_c[:, 0],
            cpu_dense[:, 0],
            attr_bytes[:, 0] if has_dfa else None,
            byte_ovf[:, 0] if has_dfa else None,
            attrs_num[:, 0] if has_num else None,
            num_valid[:, 0] if has_num else None,
            rel_rows[:, 0] if has_rel else None,
            member_ovf[:, 0] if has_ovf else None,
        )
        # own-config one-hot rows local to this shard (other shards see all-
        # False masks for the request); psum over mp merges the disjoint parts
        G = verdict.shape[1]
        mp_idx = jax.lax.axis_index("mp")
        mask = (shard_of == mp_idx)[:, None] & (
            row_of[:, None] == jnp.arange(G, dtype=row_of.dtype)[None, :]
        )                                                        # [B_l, G]
        own = jnp.any(verdict & mask, axis=1)
        own_rule = jnp.any(rule & mask[:, :, None], axis=1)      # [B_l, E]
        own_skip = jnp.any(skipped & mask[:, :, None], axis=1)
        merged = jax.lax.psum(
            jnp.concatenate(
                [own[:, None], own_rule, own_skip], axis=1
            ).astype(jnp.int32),
            "mp",
        )
        return merged > 0                                        # [B_l, 1+2E]

    byte_specs = (
        (P("dp", "mp", None, None), P("dp", "mp", None))
        if has_dfa
        else (None, None)
    )
    num_specs = ((P("dp", "mp", None), P("dp", "mp", None))
                 if has_num else (None, None))
    rel_specs = ((P("dp", "mp", None),) if has_rel else (None,))
    ovf_specs = ((P("dp", "mp", None),) if has_ovf else (None,))
    mapped = _shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(
            specs,
            P("dp", "mp", None),
            P("dp", "mp", None, None),
            P("dp", "mp", None),
        ) + byte_specs + num_specs + rel_specs + ovf_specs
        + (P("dp"), P("dp")),
        out_specs=P("dp"),
    )

    def bitpacked_step(params, *operands):
        # D2H readback rides the link as a u8 bitmask (8 verdicts/byte —
        # decode host-side with ops.pattern_eval.unpack_verdicts), same
        # packed-readback contract as the single-corpus eval_fused_jit
        return _bitpack_rows(mapped(params, *operands))

    step = jax.jit(bitpacked_step)
    _STEP_CACHE[key] = step
    return step


def _eval_stacked(params, attrs_val, members_c, cpu_dense,
                  attr_bytes, byte_ovf, attrs_num, num_valid, rel_rows,
                  member_ovf, shard_of, row_of):
    """Single-DEVICE evaluation of the whole stacked corpus — the failover
    twin of the shard_map step: vmap over the [S] shard axis replaces the
    mesh partition, the own-config mask-reduce replaces the psum.  Same
    operands, same bit-packed [B, ceil((1+2E)/8)] readback, bit-identical
    verdicts (the kernel is a pure per-row function and vmap is exact)."""
    def per_shard(sq, av, mc, cd, ab, bo, an, nv, rr, mo):
        verdict, (rule, skipped) = eval_verdicts(
            sq, av, mc, cd, ab, bo, an, nv, rr, mo)
        return verdict, rule, skipped

    ops = [jnp.moveaxis(attrs_val, 1, 0), jnp.moveaxis(members_c, 1, 0),
           jnp.moveaxis(cpu_dense, 1, 0)]
    axes = [0, 0, 0, 0]
    for a in (attr_bytes, byte_ovf, attrs_num, num_valid, rel_rows,
              member_ovf):
        if a is not None:
            ops.append(jnp.moveaxis(a, 1, 0))
            axes.append(0)
        else:
            ops.append(None)
            axes.append(None)
    verdict, rule, skipped = jax.vmap(per_shard, in_axes=tuple(axes))(
        params, *ops)
    S, _, G = verdict.shape
    own_mask = (
        (shard_of[None, :, None]
         == jnp.arange(S, dtype=shard_of.dtype)[:, None, None])
        & (row_of[None, :, None]
           == jnp.arange(G, dtype=row_of.dtype)[None, None, :])
    )                                                            # [S, B, G]
    own = jnp.any(verdict & own_mask, axis=(0, 2))
    own_rule = jnp.any(rule & own_mask[..., None], axis=(0, 2))
    own_skip = jnp.any(skipped & own_mask[..., None], axis=(0, 2))
    return _bitpack_rows(
        jnp.concatenate([own[:, None], own_rule, own_skip], axis=1))


_EVAL_STACKED_JIT = jax.jit(_eval_stacked)


def build_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None) -> Mesh:
    devices = np.asarray(jax.devices()[: n_devices or len(jax.devices())])
    n = devices.size
    if dp is None:
        dp = 2 if n % 2 == 0 and n > 1 else 1
    mp = n // dp
    return Mesh(devices[: dp * mp].reshape(dp, mp), ("dp", "mp"))


# ---------------------------------------------------------------------------
# per-mesh routing state: device breakers, occupancy, failover evidence.
# Process-wide keyed by the Mesh object (the engine resolves ONE mesh and
# reuses it across reconciles), so device health and in-flight occupancy
# survive snapshot swaps — a device is sick or busy, not a snapshot.
# ---------------------------------------------------------------------------

_MESH_STATE: Dict[Mesh, "MeshState"] = {}
_MESH_STATE_LOCK = threading.Lock()


class MeshState:
    def __init__(self, mesh: Mesh, threshold: int = 3, reset_s: float = 5.0):
        from ..runtime.breaker import DeviceBreakerSet

        self.device_ids = [int(d.id) for d in mesh.devices.flat]
        self.breakers = DeviceBreakerSet("mesh", self.device_ids,
                                         threshold=threshold, reset_s=reset_s)
        self.lock = threading.Lock()
        # Serializes the ENQUEUE of collective-bearing (psum) programs:
        # concurrent shard_map launches from different dispatcher threads
        # can interleave the per-device execution queues in inconsistent
        # order, deadlocking the cross-device rendezvous (observed as stuck
        # AllReduce participants on forced-host CPU devices; the same
        # cross-thread enqueue race exists on real chips).  Only the
        # dispatch call is held — execution and readback stay async, so
        # pipelining is unaffected.
        self.launch_lock = threading.Lock()
        self.occupancy: Dict[int, int] = {d: 0 for d in self.device_ids}
        self.occupancy_peak: Dict[int, int] = {d: 0 for d in self.device_ids}
        self.launches: Dict[int, int] = {d: 0 for d in self.device_ids}
        self.failovers: Dict[int, int] = {d: 0 for d in self.device_ids}

    def acquire(self, model: "ShardedPolicyModel", devices: List[int]
                ) -> "MeshRoute":
        from ..utils import metrics as metrics_mod

        with self.lock:
            for d in devices:
                n = self.occupancy[d] = self.occupancy.get(d, 0) + 1
                if n > self.occupancy_peak.get(d, 0):
                    self.occupancy_peak[d] = n
                self.launches[d] = self.launches.get(d, 0) + 1
                metrics_mod.mesh_shard_occupancy.labels(str(d)).set(n)
        return MeshRoute(self, devices)

    def release(self, devices: List[int]) -> None:
        from ..utils import metrics as metrics_mod

        with self.lock:
            for d in devices:
                n = self.occupancy[d] = max(0, self.occupancy.get(d, 0) - 1)
                metrics_mod.mesh_shard_occupancy.labels(str(d)).set(n)

    def device_failed(self, device_id: int, lane: str,
                      failover: bool = True) -> None:
        """Breaker + evidence fold for one attributed device failure.
        ``failover=True`` (dispatch-time: the batch re-dispatches elsewhere
        right now) also counts auth_server_device_failover_total; readback/
        watchdog failures reported via ``complete_route`` pass False — they
        feed the breaker, but whether the RETRY resolves on a device or
        degrades is the engine's story, not this counter's."""
        from ..runtime.flight_recorder import RECORDER
        from ..utils import metrics as metrics_mod

        self.breakers.record_failure(device_id)
        if failover:
            metrics_mod.device_failover.labels(str(device_id)).inc()
            with self.lock:
                self.failovers[device_id] = self.failovers.get(device_id, 0) + 1
        RECORDER.record("device-failover", lane=lane,
                        detail={"device": device_id})

    def to_json(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "devices": list(self.device_ids),
                "occupancy": {str(d): n for d, n in self.occupancy.items()},
                "occupancy_peak": {str(d): n
                                   for d, n in self.occupancy_peak.items()},
                "launches": {str(d): n for d, n in self.launches.items()},
                "failovers": {str(d): n for d, n in self.failovers.items()},
                "breakers": self.breakers.to_json(),
            }


def _mesh_state(mesh: Mesh, threshold: int = 3,
                reset_s: float = 5.0) -> MeshState:
    """One MeshState per mesh, process-wide.  The breaker knobs apply only
    at CREATION (device health outlives snapshots by design, so the first
    engine to touch a mesh fixes its per-device breaker tuning)."""
    state = _MESH_STATE.get(mesh)
    if state is None:
        with _MESH_STATE_LOCK:
            state = _MESH_STATE.get(mesh)
            if state is None:
                state = _MESH_STATE[mesh] = MeshState(
                    mesh, threshold=threshold, reset_s=reset_s)
    return state


def _reset_mesh_state_for_tests() -> None:
    """Drop all per-mesh routing state (breakers, occupancy, failover
    evidence).  Tests only: equal meshes share one MeshState by design (a
    device's health outlives snapshots), so a fault-injection test must not
    leak open breakers into its neighbours."""
    with _MESH_STATE_LOCK:
        _MESH_STATE.clear()


class MeshRoute:
    """One launched batch's claim on its device windows: which devices it
    occupies and the idempotent release.  The engine releases on terminal
    completion (success, degrade, watchdog) and records the per-device
    breaker outcome via ``ShardedPolicyModel.complete_route``."""

    __slots__ = ("state", "devices", "_done")

    def __init__(self, state: MeshState, devices: List[int]):
        self.state = state
        self.devices = list(devices)
        self._done = False

    def release(self) -> None:
        if not self._done:
            self._done = True
            self.state.release(self.devices)


@dataclass
class _ShardedEncoded:
    attrs_val: np.ndarray      # [B, S, A]
    members_c: np.ndarray      # [B, S, M, K] — compact membership rows
    cpu_dense: np.ndarray      # [B, S, C] — dense CPU-lane columns
    attr_bytes: Optional[np.ndarray]  # [B, S, NB, LB] uint8 (None: no DFA lane)
    byte_ovf: Optional[np.ndarray]    # [B, S, NB] bool
    shard_of: np.ndarray       # [B] which shard owns the request's config
    row_of: np.ndarray         # [B] row within that shard
    host_fallback: np.ndarray  # [B] bool — exact re-decision on host
    # ISSUE 14 lanes (None when the stacked corpus lacks them)
    attrs_num: Optional[np.ndarray] = None   # [B, S, NN] int32
    num_valid: Optional[np.ndarray] = None   # [B, S, NN] bool
    rel_rows: Optional[np.ndarray] = None    # [B, S, NR] int32
    member_ovf: Optional[np.ndarray] = None  # [B, S, M] bool

class ShardedPolicyModel:
    """Rule corpus partitioned over the 'mp' mesh axis; batch over 'dp'.

    Two-phase: the constructor compiles the shards and stacks every operand
    HOST-side (``host_view``); ``upload()`` stages them onto the mesh (one
    mesh-sharded device_put per leaf, or a per-shard delta against a
    previous model).  ``defer_upload=True`` stops after the host phase so a
    strict-verify lint can gate the upload (ISSUE 11 satellite — the
    single-corpus path's lint-before-upload ordering, restored here)."""

    def __init__(self, configs: Sequence[ConfigRules], mesh: Mesh,
                 members_k: int = 16, interner: Optional[StringInterner] = None,
                 defer_upload: bool = False, grid_relief: bool = True,
                 breaker_threshold: int = 3, breaker_reset_s: float = 5.0,
                 ovf_assist: Optional[bool] = None,
                 kernel_lane: Optional[str] = None):
        self.mesh = mesh
        self.kernel_lane = kernel_lane
        S = mesh.shape["mp"]
        self.n_shards = S
        self.members_k = members_k  # requested (single-corpus-equivalent) K
        # grid relief (ISSUE 11): each shard's member grid is ~1/mp of the
        # monolithic corpus, so the same per-device payload budget funds a
        # ~mp× larger compact K — single-corpus membership-overflow rows
        # (the cpu-grid-overflow host-oracle caveat) ride the kernel here
        if grid_relief and S > 1:
            self.members_k_eff = min(members_k * S,
                                     max(members_k, MEMBERS_K_RELIEF_CAP))
        else:
            self.members_k_eff = members_k
        self.interner = interner if interner is not None else StringInterner()
        groups: List[List[ConfigRules]] = [[] for _ in range(S)]
        self.locator: Dict[str, Tuple[int, int]] = {}
        for i, cfg in enumerate(configs):
            shard = i % S
            self.locator[cfg.name] = (shard, len(groups[shard]))
            groups[shard].append(cfg)

        # two-pass compile: natural shapes → union targets → final compile.
        # The union carries the DFA row/state/byte axes, so shards with
        # regexes stack their device-DFA tables and regex-free shards carry
        # a dummy lane of the same shape.  One dfa_cache spans both passes
        # and all shards: each distinct regex determinizes exactly once.
        dfa_cache: Dict[str, Any] = {}
        k = self.members_k_eff
        first = [
            compile_corpus(g, members_k=k, interner=self.interner,
                           dfa_cache=dfa_cache, ovf_assist=ovf_assist)
            for g in groups
        ]
        targets = ShapeTargets.union([p.shape_targets() for p in first])
        self.shards: List[CompiledPolicy] = [
            compile_corpus(g, members_k=k, interner=self.interner,
                           targets=targets, dfa_cache=dfa_cache,
                           ovf_assist=ovf_assist)
            for g in groups
        ]
        self.has_dfa = self.shards[0].n_byte_attrs > 0
        # ISSUE 14 lane flags: structural across shards (ShapeTargets union)
        self.has_num = int(getattr(self.shards[0], "n_num_attrs", 0)) > 0
        self.has_rel = int(getattr(self.shards[0], "n_rel_slots", 0)) > 0
        self.has_ovf = bool(getattr(self.shards[0], "ovf_assist", False))
        # targets unified every operand shape (incl. eval-table rows), so
        # the whole per-shard device pytree — gather lane, matmul lane, DFA
        # lane — stacks on a leading [S] axis with one tree.map
        self.configs_per_shard = self.shards[0].n_configs
        # [S, G] verdict-cache eligibility, indexed (shard_of, row_of) by
        # the engine's dedup/cache encode stage
        self.config_cacheable = np.stack(
            [p.config_cacheable for p in self.shards])
        # host-side staging: stack numpy operands; upload() ships each
        # shard's slice straight to its devices via ONE mesh-sharded
        # device_put per leaf (no transient 2-3x corpus copy on device 0).
        # The stacked view is retained: the next reconcile diffs against it
        # for the per-shard delta upload, and the failover path device_puts
        # it onto a single healthy device.
        per_shard_params = [to_device(p, host=True, lane=kernel_lane)
                            for p in self.shards]
        self.host_view = jax.tree.map(
            lambda *xs: np.stack(xs), *per_shard_params
        )
        self.has_matmul = self.host_view.get("matmul") is not None
        self.has_fused = self.host_view.get("fused") is not None
        self.params = None            # set by upload()
        self.upload_report: Optional[Dict[str, Any]] = None
        self._step = None
        # routing state is per-MESH (process-wide): device health and
        # occupancy survive reconciles (first creator's breaker knobs win)
        self.state = _mesh_state(mesh, threshold=breaker_threshold,
                                 reset_s=breaker_reset_s)
        self._dev_by_id = {int(d.id): d for d in mesh.devices.flat}
        self._device_params: Dict[int, Any] = {}  # failover staging cache
        self._device_params_lock = threading.Lock()
        if not defer_upload:
            self.upload()

    # ---- staging ----------------------------------------------------------

    def upload(self, prev: "Optional[ShardedPolicyModel]" = None
               ) -> Dict[str, Any]:
        """Stage the stacked host operands onto the mesh.  With ``prev`` (a
        previously-uploaded model on the SAME mesh) a delta plan is
        computed between the stacked host views: the leading axis of every
        stacked leaf is the shard axis, so ``plan_delta``'s changed-rows
        mode ships bytes only to the shard(s) a reconcile touched —
        unchanged shards receive zero bytes (per-shard delta uploads,
        measured in auth_server_mesh_shard_upload_bytes{shard}).  Returns
        the upload report (also retained as ``self.upload_report``)."""
        from ..snapshots.diff import plan_delta
        from ..utils import metrics as metrics_mod

        specs = jax.tree.map(lambda _: P("mp"), self.host_view)
        sharding = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs)
        plan = None
        if (prev is not None and prev.mesh is self.mesh
                and prev.params is not None and prev.host_view is not None):
            # rows_win_factor=1.0: the leading axis is the shard axis, so
            # any strict row subset confines traffic to the owning shards
            plan = plan_delta(prev.host_view, self.host_view,
                              rows_win_factor=1.0)
        S = self.n_shards
        per_shard = [0] * S
        if plan is None:
            self.params = jax.tree.map(
                lambda a, sh: jax.device_put(a, sh),
                self.host_view, sharding)
            total = 0

            def _count(a):
                nonlocal total
                arr = np.asarray(a)
                total += arr.nbytes
                for s in range(min(S, arr.shape[0] if arr.ndim else 0)):
                    per_shard[s] += arr[s].nbytes

            jax.tree.map(_count, self.host_view)
            report = {"mode": "full", "upload_bytes": total,
                      "full_bytes": total, "arrays_reused": 0,
                      "arrays_touched": []}
        else:
            by_name = {e.name: e for e in plan.entries}
            uploaded = 0

            def leaf(name, new_h, prev_d, sh):
                nonlocal uploaded
                e = by_name.get(name)
                new_h = np.asarray(new_h)
                if e is None or prev_d is None or e.mode == "full":
                    uploaded += new_h.nbytes
                    for s in range(min(S, new_h.shape[0])):
                        per_shard[s] += new_h[s].nbytes
                    return jax.device_put(new_h, sh)
                if e.mode == "reuse":
                    return prev_d
                # rows mode: the leading axis is the SHARD axis, so the
                # changed rows name exactly the shards whose slice this
                # reconcile rewrote.  Functional scatter: the previous
                # device buffers stay intact for in-flight batches; the
                # H2D traffic is the changed shard slices + indices.
                idx = e.rows
                uploaded += int(e.upload_bytes)
                for s in idx.tolist():
                    if s < S:
                        per_shard[s] += new_h[s].nbytes
                out = prev_d.at[jnp.asarray(idx)].set(
                    jnp.asarray(new_h[idx]))
                return jax.device_put(out, sh)

            def rebuild(prefix, new_v, prev_v, sh):
                if new_v is None:
                    return None
                if isinstance(new_v, dict):
                    pd = prev_v if isinstance(prev_v, dict) else {}
                    sd = sh if isinstance(sh, dict) else {}
                    return {k: rebuild(f"{prefix}.{k}" if prefix else str(k),
                                       new_v[k], pd.get(k), sd.get(k))
                            for k in new_v}
                if isinstance(new_v, (tuple, list)):
                    pt = prev_v if isinstance(prev_v, (tuple, list)) else ()
                    st = sh if isinstance(sh, (tuple, list)) else ()
                    return tuple(
                        rebuild(f"{prefix}.{i}", x,
                                pt[i] if i < len(pt) else None,
                                st[i] if i < len(st) else None)
                        for i, x in enumerate(new_v))
                return leaf(prefix, new_v, prev_v, sh)

            self.params = rebuild("", self.host_view, prev.params, sharding)
            report = dict(plan.to_json(), upload_bytes=uploaded)
        report["per_shard_bytes"] = {str(s): int(b)
                                     for s, b in enumerate(per_shard)}
        for s, b in enumerate(per_shard):
            if b:
                metrics_mod.mesh_shard_upload_bytes.labels(str(s)).inc(b)
        self.upload_report = report
        n_levels = len(self.shards[0].levels)
        self._step = _sharded_step(
            self.mesh, self.has_dfa, self.has_matmul, n_levels, specs,
            extras=(self.has_num, self.has_rel, self.has_ovf,
                    self.has_fused),
        )
        return report

    def cache_tokens(self, fingerprints: Dict[str, str]):
        """Per-shard per-row verdict-cache tokens: (encoding_epoch of the
        OWNING shard's compiled layout, the config's source fingerprint) —
        the mesh twin of the single-corpus snapshot tokens (ISSUE 11
        satellite: PR 8 parity).  Indexed [shard_of][row_of] by the
        engine's dedup/cache stage; entries of configs a reconcile did not
        touch keep their tokens (same interner ⇒ same epoch) and SURVIVE
        the swap."""
        from ..snapshots.fingerprint import cache_tokens as _tokens

        return [_tokens(p, fingerprints) for p in self.shards]

    # ------------------------------------------------------------------

    def encode(self, docs: Sequence[Any], config_names: Sequence[str], batch_pad: int = 0) -> _ShardedEncoded:
        from ..compiler.intern import EMPTY_ID, PAD
        from ..compiler.pack import pack_batch

        B = max(len(docs), 1)
        if batch_pad and batch_pad > B:
            B = batch_pad
        dp = self.mesh.shape["dp"]
        if B % dp:
            B += dp - B % dp
        S = self.n_shards
        p0 = self.shards[0]
        A, K = p0.n_attrs, p0.members_k
        M, C = p0.n_member_attrs, p0.n_cpu_leaves
        attrs_val = np.full((B, S, A), EMPTY_ID, dtype=np.int32)
        members_c = np.full((B, S, M, K), PAD, dtype=np.int32)
        cpu_dense = np.zeros((B, S, C), dtype=bool)
        if self.has_dfa:
            from ..compiler.compile import DFA_VALUE_BYTES

            NB = p0.n_byte_attrs
            attr_bytes = np.zeros((B, S, NB, DFA_VALUE_BYTES), dtype=np.uint8)
            byte_ovf = np.zeros((B, S, NB), dtype=bool)
        else:
            attr_bytes = byte_ovf = None
        if self.has_num:
            NN = p0.n_num_attrs
            attrs_num = np.zeros((B, S, NN), dtype=np.int32)
            num_valid = np.zeros((B, S, NN), dtype=bool)
        else:
            attrs_num = num_valid = None
        rel_rows = (np.zeros((B, S, p0.n_rel_slots), dtype=np.int32)
                    if self.has_rel else None)
        member_ovf = (np.zeros((B, S, M), dtype=bool)
                      if self.has_ovf else None)
        shard_of = np.zeros((B,), dtype=np.int32)
        row_of = np.zeros((B,), dtype=np.int32)
        host_fallback = np.zeros((B,), dtype=bool)
        # group requests by owning shard and encode each group in ONE
        # batched call (per-request encode_batch would dominate the hot path)
        by_shard: Dict[int, List[int]] = {}
        for r, (doc, name) in enumerate(zip(docs, config_names)):
            shard, row = self.locator[name]
            shard_of[r], row_of[r] = shard, row
            by_shard.setdefault(shard, []).append(r)
        for shard, rs in by_shard.items():
            enc = encode_batch(
                self.shards[shard],
                [docs[r] for r in rs],
                [int(row_of[r]) for r in rs],
            )
            db = pack_batch(self.shards[shard], enc, trim_bytes=False)
            attrs_val[rs, shard] = db.attrs_val[: len(rs)]
            members_c[rs, shard] = db.members_c[: len(rs)]
            cpu_dense[rs, shard] = db.cpu_dense[: len(rs)]
            if self.has_dfa:
                # per-shard batches may be byte-trimmed (pack._trim_bytes);
                # assign into the prefix, then trim the assembled tensor once
                lb = db.attr_bytes.shape[-1]
                attr_bytes[rs, shard, :, :lb] = db.attr_bytes[: len(rs)]
                byte_ovf[rs, shard] = db.byte_ovf[: len(rs)]
            if self.has_num:
                attrs_num[rs, shard] = db.attrs_num[: len(rs)]
                num_valid[rs, shard] = db.num_valid[: len(rs)]
            if self.has_rel:
                rel_rows[rs, shard] = db.rel_rows[: len(rs)]
            if self.has_ovf:
                member_ovf[rs, shard] = db.member_ovf[: len(rs)]
            host_fallback[rs] = db.host_fallback[: len(rs)]
        if self.has_dfa:
            from ..compiler.pack import _trim_bytes

            attr_bytes = _trim_bytes(attr_bytes)
        return _ShardedEncoded(
            attrs_val, members_c, cpu_dense, attr_bytes, byte_ovf,
            shard_of, row_of, host_fallback,
            attrs_num=attrs_num, num_valid=num_valid,
            rel_rows=rel_rows, member_ovf=member_ovf,
        )

    def row_keys(self, encoded: _ShardedEncoded, n: int):
        """Canonical per-row keys for dedup + the verdict cache: the full
        operand bytes plus shard_of/row_of (config identity on the mesh)
        and the lossy-row flag (compiler/pack.py row_key_bytes doc)."""
        from ..compiler.pack import row_key_bytes

        return row_key_bytes(
            [encoded.shard_of, encoded.row_of, encoded.attrs_val,
             encoded.members_c, encoded.cpu_dense, encoded.attr_bytes,
             encoded.byte_ovf, encoded.host_fallback, encoded.attrs_num,
             encoded.num_valid, encoded.rel_rows, encoded.member_ovf], n)

    def select_rows(self, encoded: _ShardedEncoded, rows: Sequence[int],
                    batch_pad: int = 0) -> _ShardedEncoded:
        """Row-subset view for dedup dispatch: the unique rows re-padded to
        ``batch_pad`` (dp-aligned like encode) by repeating the first row —
        padding rows' verdicts are discarded by the inverse fan-out."""
        u = len(rows)
        B = max(u, 1, batch_pad)
        dp = self.mesh.shape["dp"]
        if B % dp:
            B += dp - B % dp
        fill = rows[0] if u else 0
        idx = np.asarray(list(rows) + [fill] * (B - u))

        def take(a):
            return a[idx] if a is not None else None

        return _ShardedEncoded(
            take(encoded.attrs_val), take(encoded.members_c),
            take(encoded.cpu_dense), take(encoded.attr_bytes),
            take(encoded.byte_ovf), take(encoded.shard_of),
            take(encoded.row_of), take(encoded.host_fallback),
            attrs_num=take(encoded.attrs_num),
            num_valid=take(encoded.num_valid),
            rel_rows=take(encoded.rel_rows),
            member_ovf=take(encoded.member_ovf),
        )

    def dispatch_full(self, encoded: _ShardedEncoded):
        """Non-blocking launch: returns the ON-DEVICE packed own-rows
        result [B, 1+2E] (readback copy started eagerly), so the caller can
        keep further batches in flight while this one rides the link — the
        sharded mirror of the engine's pipelined dispatch window."""
        if self._step is None:
            raise RuntimeError(
                "ShardedPolicyModel not staged: call upload() after the "
                "deferred (strict-verify) construction")
        # launch_lock: enqueue-order consistency for the psum collective
        # (see MeshState) — held for the async dispatch only
        with self.state.launch_lock:
            packed = self._step(
                self.params,
                jnp.asarray(encoded.attrs_val),
                jnp.asarray(encoded.members_c),
                jnp.asarray(encoded.cpu_dense),
                jnp.asarray(encoded.attr_bytes) if self.has_dfa else None,
                jnp.asarray(encoded.byte_ovf) if self.has_dfa else None,
                jnp.asarray(encoded.attrs_num) if self.has_num else None,
                jnp.asarray(encoded.num_valid) if self.has_num else None,
                jnp.asarray(encoded.rel_rows) if self.has_rel else None,
                jnp.asarray(encoded.member_ovf) if self.has_ovf else None,
                jnp.asarray(encoded.shard_of),
                jnp.asarray(encoded.row_of),
            )
        try:
            packed.copy_to_host_async()
        except Exception:
            pass  # readback degrades to a blocking copy at np.asarray time
        # ISSUE 16: ONE collective launch per shard-step — the psum merge
        # is part of the same program, so a 2x4 mesh still counts 1 here
        LEDGER.observe_launch("mesh", 1,
                              h2d_bytes=self._encoded_h2d_bytes(encoded),
                              d2h_bytes=self._d2h_bytes(encoded))
        return packed

    def _encoded_h2d_bytes(self, encoded: _ShardedEncoded) -> int:
        """Request-operand bytes one launch of ``encoded`` stages (every
        present operand incl. the shard_of/row_of routing rows) — pure
        shape arithmetic for the kernel-cost ledger."""
        total = 0
        for name in ("attrs_val", "members_c", "cpu_dense", "attr_bytes",
                     "byte_ovf", "attrs_num", "num_valid", "rel_rows",
                     "member_ovf", "shard_of", "row_of"):
            arr = getattr(encoded, name, None)
            if arr is not None:
                total += arr.nbytes
        return total

    def _d2h_bytes(self, encoded: _ShardedEncoded) -> int:
        """Readback bytes of one launch: the bitpacked [B, W] uint8
        own-rows result."""
        E = int(self.shards[0].eval_rule.shape[1])
        return int(encoded.attrs_val.shape[0]) * packed_width(1 + 2 * E)

    # ---- per-device failover (ISSUE 11) ----------------------------------

    def device_params(self, device_id: int):
        """The stacked corpus staged onto ONE device (failover lane),
        cached per device — built lazily the first time a device serves a
        failover batch, reused for the rest of the incident."""
        params = self._device_params.get(device_id)
        if params is None:
            with self._device_params_lock:
                params = self._device_params.get(device_id)
                if params is None:
                    device = self._dev_by_id[device_id]
                    params = jax.tree.map(
                        lambda a: jax.device_put(a, device), self.host_view)
                    self._device_params[device_id] = params
        return params

    def dispatch_on_device(self, encoded: _ShardedEncoded, device_id: int):
        """Single-device launch of one batch against the WHOLE stacked
        corpus (vmap over the shard axis replaces the mesh partition) —
        the failover lane when part of the mesh is down.  Same bit-packed
        own-rows readback as ``dispatch_full``."""
        device = self._dev_by_id[device_id]

        def put(a):
            return jax.device_put(np.asarray(a), device) if a is not None \
                else None

        packed = _EVAL_STACKED_JIT(
            self.device_params(device_id),
            put(encoded.attrs_val),
            put(encoded.members_c),
            put(encoded.cpu_dense),
            put(encoded.attr_bytes) if self.has_dfa else None,
            put(encoded.byte_ovf) if self.has_dfa else None,
            put(encoded.attrs_num) if self.has_num else None,
            put(encoded.num_valid) if self.has_num else None,
            put(encoded.rel_rows) if self.has_rel else None,
            put(encoded.member_ovf) if self.has_ovf else None,
            put(encoded.shard_of),
            put(encoded.row_of),
        )
        try:
            packed.copy_to_host_async()
        except Exception:
            pass
        # failover lane: a re-dispatch is a REAL extra launch — the ledger
        # shows it as launches_per_batch > 1 instead of hiding it
        LEDGER.observe_launch("mesh", 1,
                              h2d_bytes=self._encoded_h2d_bytes(encoded),
                              d2h_bytes=self._d2h_bytes(encoded))
        return packed

    def dispatch_routed(self, encoded: _ShardedEncoded, lane: str = "engine"
                        ) -> Tuple[Any, MeshRoute]:
        """Breaker- and occupancy-aware launch (the engine's mesh entry):

        1. every device healthy → the full-mesh shard_map launch;
        2. a device fails its fault probe / launch → its per-device breaker
           records the failure and the batch re-dispatches to the healthy
           device with the EMPTIEST in-flight window (occupancy-aware
           routing) — before any host-oracle involvement;
        3. no device left → MeshUnavailable (the caller's host-oracle
           degrade is the only lane past this point).

        Returns (on-device packed handle, MeshRoute).  The route carries
        the occupied device windows; the caller releases it at terminal
        completion via ``complete_route``."""
        from ..runtime import faults

        state = self.state
        tried: set = set()
        full_mesh_eligible = state.breakers.all_closed()
        while True:
            if full_mesh_eligible:
                full_mesh_eligible = False
                try:
                    if faults.ACTIVE:
                        for d in state.device_ids:
                            faults.FAULTS.check("kernel", lane, device=d)
                    handle = self.dispatch_full(encoded)
                    return handle, state.acquire(self, list(state.device_ids))
                except MeshUnavailable:
                    raise
                except Exception as e:
                    dev = getattr(e, "device_id", None)
                    if dev is None:
                        raise  # unattributed: the engine's retry/degrade owns it
                    state.device_failed(int(dev), lane)
                    tried.add(int(dev))
                    log.warning(
                        "mesh device %d failed a full-mesh launch probe: "
                        "failing the batch over to a healthy device", dev)
                    continue
            cands = [d for d in state.breakers.candidates() if d not in tried]
            if not cands:
                raise MeshUnavailable(
                    f"no healthy mesh device left (excluded {sorted(tried)})")
            # DUE PROBES first: an open-past-cooldown device only recovers
            # if some batch actually probes it, and the breaker's single
            # probe slot (allow_device) keeps every other batch on healthy
            # devices while the probe is in flight — closed-first ordering
            # would starve the probe and strand the mesh in single-device
            # dispatch forever.  Within each class, emptiest in-flight
            # window first (the occupancy-aware cut).
            from ..runtime.breaker import CLOSED

            with state.lock:
                cands.sort(key=lambda d: (
                    state.breakers.get(d).state == CLOSED,
                    state.occupancy.get(d, 0)))
            dev = cands[0]
            if not state.breakers.get(dev).allow_device():
                tried.add(dev)
                continue
            try:
                if faults.ACTIVE:
                    faults.FAULTS.check("kernel", lane, device=dev)
                handle = self.dispatch_on_device(encoded, dev)
                return handle, state.acquire(self, [dev])
            except Exception:
                state.device_failed(dev, lane)
                tried.add(dev)
                continue

    def complete_route(self, route: Optional[MeshRoute], ok: bool,
                       lane: str = "engine") -> None:
        """Terminal accounting for one routed batch: per-device breaker
        verdicts (a single-device route's failure is attributable; a
        full-mesh readback failure is not — the lane-global breaker owns
        those) and the idempotent occupancy release."""
        if route is None:
            return
        try:
            if ok:
                self.state.breakers.record_success(route.devices)
            elif len(route.devices) == 1:
                self.state.device_failed(route.devices[0], lane,
                                         failover=False)
        finally:
            route.release()

    def cost_feed(self) -> float:
        """Mesh-lane cost multiplier for the lane-selection cost model
        (ISSUE 12, runtime/lane_select.py): ≥ 1.0, rising as devices trip
        their breakers — a partially-down mesh concentrates the same load
        on the survivors, so a device dispatch is expected to cost
        proportionally more than the healthy-mesh RTT EWMA says.  All
        devices down returns the full device count (the selector then
        prefers the host lane for everything it is allowed to take, which
        is exactly the degrade behavior the breaker enforces anyway)."""
        from ..runtime.breaker import CLOSED

        breakers = self.state.breakers.breakers
        total = len(breakers)
        if not total:
            return 1.0
        healthy = sum(1 for b in breakers.values() if b.state == CLOSED)
        return float(total) / float(max(1, healthy))

    def mesh_vars(self) -> Dict[str, Any]:
        """JSON-safe mesh-lane state for /debug/vars + bench artifacts."""
        out = self.state.to_json()
        out.update({
            "dp": int(self.mesh.shape["dp"]),
            "mp": int(self.mesh.shape["mp"]),
            "members_k": self.members_k,
            "members_k_eff": self.members_k_eff,
            "configs_per_shard": self.configs_per_shard,
            "upload": self.upload_report,
        })
        return out

    # ------------------------------------------------------------------

    def _run_step(self, encoded: _ShardedEncoded) -> np.ndarray:
        """Own-rows result [B, 1+2E] bool, decoded from the bit-packed
        readback — one small (u8 bitmask) transfer per batch (own-config
        selection happens on device, inside the shard_map)."""
        E = int(self.shards[0].eval_rule.shape[1])
        return unpack_verdicts(
            np.asarray(self.dispatch_full(encoded)), 1 + 2 * E)

    def apply(self, encoded: _ShardedEncoded) -> np.ndarray:
        return self._run_step(encoded)[:, 0]

    def apply_full(self, encoded: _ShardedEncoded) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Own-config (verdict [B], rule results [B, E], skipped [B, E]) —
        the same contract as the single-corpus ``eval_full_jit``."""
        packed = self._run_step(encoded)
        E = int(self.shards[0].eval_rule.shape[1])
        own = packed[:, 0]
        own_rule = packed[:, 1:1 + E].copy()      # writable: host fallback
        own_skipped = packed[:, 1 + E:1 + 2 * E].copy()
        return own, own_rule, own_skipped

    def host_decide(self, config_name: str, doc: Any) -> Tuple[np.ndarray, np.ndarray]:
        """Exact host-oracle decision for ONE request of this mesh corpus:
        (rule_results [E], skipped [E]) with the kernel's padding/tail
        semantics.  The engine's degraded lane (runtime/engine.py
        _degrade_batch) re-decides whole batches through this when the
        device path fails or the circuit breaker is open — the sharded
        mirror of host_results on the single corpus."""
        from ..models.policy_model import host_results

        shard, row = self.locator[config_name]
        return host_results(self.shards[shard], doc, int(row))[1:]

    def host_decide_many(self, config_names: Sequence[str],
                         docs: Sequence[Any]) -> List[Optional[Tuple[np.ndarray, np.ndarray]]]:
        """Batch form of host_decide for the engine's degraded and brownout
        lanes: one (rule_results [E], skipped [E]) per request, or None for
        a row whose oracle run itself failed (the caller resolves those
        typed UNAVAILABLE, fail closed — one bad row never fails its
        batchmates)."""
        out: List[Optional[Tuple[np.ndarray, np.ndarray]]] = []
        for name, doc in zip(config_names, docs):
            try:
                out.append(self.host_decide(name, doc))
            except Exception:
                log.exception("host oracle failed for config %r", name)
                out.append(None)
        return out

    def apply_fallback(self, host_fallback: np.ndarray, docs: Sequence[Any],
                       config_names: Sequence[str], own_rule: np.ndarray,
                       own_skipped: np.ndarray,
                       max_fallback: Optional[int] = None) -> None:
        """Host-oracle completion for membership-overflow rows — the ONE
        definition shared by finalize_full and the engine's pipelined
        (dedup-aware) finalize, so fallback semantics can't drift between
        the blocking and serving paths.  Mutates own_rule/own_skipped in
        place; at most ``max_fallback`` rows re-decide (beyond the cap:
        fail-closed deny + auth_server_host_fallback_shed_total)."""
        from ..models.policy_model import apply_host_fallback, host_results
        from ..utils import metrics as metrics_mod

        def decide(r: int):
            shard, row = self.locator[config_names[r]]
            return host_results(self.shards[shard], docs[r], int(row))[1:]

        fallback_rows = np.nonzero(host_fallback[: len(docs)])[0]
        metrics_mod.batch_host_fallback.labels("engine").observe(
            len(fallback_rows))
        apply_host_fallback(
            decide, fallback_rows,
            own_rule, own_skipped, max_fallback,
        )

    def finalize_full(
        self, packed, enc: _ShardedEncoded, docs: Sequence[Any],
        config_names: Sequence[str], max_fallback: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Completion half of run_full: takes the (device or already-numpy)
        packed result of ``dispatch_full(enc)`` and applies the host-oracle
        fallback.  Runs on the engine's completion stage under pipelining."""
        packed = np.asarray(packed)
        E = int(self.shards[0].eval_rule.shape[1])
        if packed.dtype == np.uint8:
            packed = unpack_verdicts(packed, 1 + 2 * E)  # bit-packed readback
        own_rule = packed[:, 1:1 + E].copy()
        own_skipped = packed[:, 1 + E:1 + 2 * E].copy()
        self.apply_fallback(enc.host_fallback, docs, config_names,
                            own_rule, own_skipped, max_fallback)
        return own_rule, own_skipped

    def run_full(
        self, docs: Sequence[Any], config_names: Sequence[str], batch_pad: int = 0,
        max_fallback: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Serving entry (PolicyEngine batch contract): per-request
        per-evaluator (rule_results [B, E], skipped [B, E]).  Blocking
        convenience composition of encode → dispatch_full → finalize_full;
        the engine's pipeline calls the three stages separately so batch
        N+1 encodes while batch N is still on the wire."""
        enc = self.encode(docs, config_names, batch_pad=batch_pad)
        return self.finalize_full(self.dispatch_full(enc), enc, docs,
                                  config_names, max_fallback=max_fallback)

    def decide(self, docs: Sequence[Any], config_names: Sequence[str]) -> List[bool]:
        from ..models.policy_model import host_results

        enc = self.encode(docs, config_names)
        own = self.apply(enc)
        out = [bool(b) for b in own[: len(docs)]]
        for r in np.nonzero(enc.host_fallback[: len(docs)])[0]:
            shard, row = self.locator[config_names[r]]
            out[r], _, _ = host_results(self.shards[shard], docs[r], int(row))
        return out
