"""Tensor-parallel (rules-axis) + data-parallel (batch-axis) policy
evaluation over a jax.sharding.Mesh.

The reference scales horizontally by label-selector sharding of AuthConfigs
across replicas (ref: controllers/label_selector.go:14-45,
docs/user-guides/sharding.md).  The TPU-era equivalent (SURVEY.md §2 P3):
partition the *config axis* of the rule corpus across mesh shards — each
shard holds the full boolean circuit of its configs, so the tree reduction
stays shard-local and the only cross-shard communication is the final
verdict gather, which XLA lays onto ICI.

Layout:
  - configs are round-robined into ``mp`` groups; each group compiles as its
    own sub-corpus against a shared interner, with ShapeTargets forcing
    identical operand shapes; arrays stack on a leading [S] axis
  - mesh ('dp', 'mp'): batch is sharded over dp, the [S] corpus axis over mp
  - shard_map evaluates each (dp, mp) block locally → verdict [B, S*G]
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compiler.compile import (
    CompiledPolicy,
    ConfigRules,
    ShapeTargets,
    compile_corpus,
)
from ..compiler.encode import encode_batch
from ..compiler.intern import StringInterner
from ..ops.pattern_eval import eval_verdicts, to_device

__all__ = ["ShardedPolicyModel", "build_mesh"]


def build_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None) -> Mesh:
    devices = np.asarray(jax.devices()[: n_devices or len(jax.devices())])
    n = devices.size
    if dp is None:
        dp = 2 if n % 2 == 0 and n > 1 else 1
    mp = n // dp
    return Mesh(devices[: dp * mp].reshape(dp, mp), ("dp", "mp"))


@dataclass
class _ShardedEncoded:
    attrs_val: np.ndarray      # [B, S, A]
    members_c: np.ndarray      # [B, S, M, K] — compact membership rows
    cpu_dense: np.ndarray      # [B, S, C] — dense CPU-lane columns
    shard_of: np.ndarray       # [B] which shard owns the request's config
    row_of: np.ndarray         # [B] row within that shard
    host_fallback: np.ndarray  # [B] bool — exact re-decision on host


class ShardedPolicyModel:
    """Rule corpus partitioned over the 'mp' mesh axis; batch over 'dp'."""

    def __init__(self, configs: Sequence[ConfigRules], mesh: Mesh, members_k: int = 16):
        self.mesh = mesh
        S = mesh.shape["mp"]
        self.n_shards = S
        interner = StringInterner()
        groups: List[List[ConfigRules]] = [[] for _ in range(S)]
        self.locator: Dict[str, Tuple[int, int]] = {}
        for i, cfg in enumerate(configs):
            shard = i % S
            self.locator[cfg.name] = (shard, len(groups[shard]))
            groups[shard].append(cfg)

        # two-pass compile: natural shapes → union targets → final compile.
        # enable_dfa=False: regexes ride the CPU lane here — DFA table shapes
        # are not yet unified across shards (single-corpus serving uses them)
        first = [
            compile_corpus(g, members_k=members_k, interner=interner, enable_dfa=False)
            for g in groups
        ]
        targets = ShapeTargets.union([p.shape_targets() for p in first])
        self.shards: List[CompiledPolicy] = [
            compile_corpus(g, members_k=members_k, interner=interner, targets=targets, enable_dfa=False)
            for g in groups
        ]
        # eval tables may still differ in row count (configs per shard): pad G
        G = max(p.n_configs for p in self.shards)
        self.configs_per_shard = G

        def pad_rows(a: np.ndarray, fill) -> np.ndarray:
            if a.shape[0] == G:
                return a
            pad = np.full((G - a.shape[0],) + a.shape[1:], fill, dtype=a.dtype)
            return np.concatenate([a, pad], axis=0)

        stacked: Dict[str, Any] = {}
        # gather lane: the stacked params keep only gather-lane keys, so
        # building matmul operands per shard would be wasted upload
        per_shard_params = [to_device(p, lane="gather") for p in self.shards]
        # stack on leading S axis (device-side stack is fine at these sizes)
        from ..compiler.compile import TRUE_SLOT

        def stack(key):
            return jnp.stack([pp[key] for pp in per_shard_params])

        eval_cond = np.stack([pad_rows(p.eval_cond, TRUE_SLOT) for p in self.shards])
        eval_rule = np.stack([pad_rows(p.eval_rule, TRUE_SLOT) for p in self.shards])
        eval_has = np.stack([pad_rows(p.eval_has_cond, False) for p in self.shards])
        n_levels = len(self.shards[0].levels)
        self.params = {
            "leaf_op": stack("leaf_op"),
            "leaf_attr": stack("leaf_attr"),
            "leaf_const": stack("leaf_const"),
            "member_slot_of_leaf": stack("member_slot_of_leaf"),
            "cpu_scatter_idx": stack("cpu_scatter_idx"),
            "levels": tuple(
                (
                    jnp.stack([jnp.asarray(p.levels[l][0]) for p in self.shards]),
                    jnp.stack([jnp.asarray(p.levels[l][1]) for p in self.shards]),
                )
                for l in range(n_levels)
            ),
            "eval_cond": jnp.asarray(eval_cond),
            "eval_rule": jnp.asarray(eval_rule),
            "eval_has_cond": jnp.asarray(eval_has),
            # regexes ride the CPU lane in the sharded path (enable_dfa=False)
            "dfa_tables": None,
            "dfa_accept": None,
            "dfa_byte_slot": None,
            "leaf_dfa_row": None,
        }
        self._place_params()
        self._step = self._build_step()

    # ------------------------------------------------------------------

    def _param_specs(self):
        lspec = tuple((P("mp"), P("mp")) for _ in self.params["levels"])
        return {
            "leaf_op": P("mp"),
            "leaf_attr": P("mp"),
            "leaf_const": P("mp"),
            "member_slot_of_leaf": P("mp"),
            "cpu_scatter_idx": P("mp"),
            "levels": lspec,
            "eval_cond": P("mp"),
            "eval_rule": P("mp"),
            "eval_has_cond": P("mp"),
            # None params are empty pytree nodes; specs mirror the structure
            "dfa_tables": None,
            "dfa_accept": None,
            "dfa_byte_slot": None,
            "leaf_dfa_row": None,
        }

    def _place_params(self):
        specs = self._param_specs()

        def place(a, spec):
            return jax.device_put(a, NamedSharding(self.mesh, spec))

        p = self.params
        self.params = {
            "leaf_op": place(p["leaf_op"], specs["leaf_op"]),
            "leaf_attr": place(p["leaf_attr"], specs["leaf_attr"]),
            "leaf_const": place(p["leaf_const"], specs["leaf_const"]),
            "member_slot_of_leaf": place(p["member_slot_of_leaf"], specs["member_slot_of_leaf"]),
            "cpu_scatter_idx": place(p["cpu_scatter_idx"], specs["cpu_scatter_idx"]),
            "levels": tuple(
                (place(c, P("mp")), place(a, P("mp"))) for c, a in p["levels"]
            ),
            "eval_cond": place(p["eval_cond"], specs["eval_cond"]),
            "eval_rule": place(p["eval_rule"], specs["eval_rule"]),
            "eval_has_cond": place(p["eval_has_cond"], specs["eval_has_cond"]),
            "dfa_tables": None,
            "dfa_accept": None,
            "dfa_byte_slot": None,
            "leaf_dfa_row": None,
        }

    def _build_step(self):
        shard_map = jax.shard_map

        mesh = self.mesh
        specs = self._param_specs()

        def local_eval(params, attrs_val, members_c, cpu_dense):
            # params leading axis is the local S slice (size 1 per mp shard)
            sq = jax.tree_util.tree_map(lambda a: a[0], params)
            verdict, _ = eval_verdicts(
                sq, attrs_val[:, 0], members_c[:, 0], cpu_dense[:, 0]
            )
            return verdict  # [B_local, G]

        step = shard_map(
            local_eval,
            mesh=mesh,
            in_specs=(
                specs,
                P("dp", "mp", None),
                P("dp", "mp", None, None),
                P("dp", "mp", None),
            ),
            out_specs=P("dp", "mp"),
        )
        return jax.jit(step)

    # ------------------------------------------------------------------

    def encode(self, docs: Sequence[Any], config_names: Sequence[str], batch_pad: int = 0) -> _ShardedEncoded:
        from ..compiler.intern import EMPTY_ID, PAD
        from ..compiler.pack import pack_batch

        B = max(len(docs), 1)
        if batch_pad and batch_pad > B:
            B = batch_pad
        dp = self.mesh.shape["dp"]
        if B % dp:
            B += dp - B % dp
        S = self.n_shards
        p0 = self.shards[0]
        A, K = p0.n_attrs, p0.members_k
        M, C = p0.n_member_attrs, p0.n_cpu_leaves
        attrs_val = np.full((B, S, A), EMPTY_ID, dtype=np.int32)
        members_c = np.full((B, S, M, K), PAD, dtype=np.int32)
        cpu_dense = np.zeros((B, S, C), dtype=bool)
        shard_of = np.zeros((B,), dtype=np.int32)
        row_of = np.zeros((B,), dtype=np.int32)
        host_fallback = np.zeros((B,), dtype=bool)
        # group requests by owning shard and encode each group in ONE
        # batched call (per-request encode_batch would dominate the hot path)
        by_shard: Dict[int, List[int]] = {}
        for r, (doc, name) in enumerate(zip(docs, config_names)):
            shard, row = self.locator[name]
            shard_of[r], row_of[r] = shard, row
            by_shard.setdefault(shard, []).append(r)
        for shard, rs in by_shard.items():
            enc = encode_batch(
                self.shards[shard],
                [docs[r] for r in rs],
                [int(row_of[r]) for r in rs],
            )
            db = pack_batch(self.shards[shard], enc)
            attrs_val[rs, shard] = db.attrs_val[: len(rs)]
            members_c[rs, shard] = db.members_c[: len(rs)]
            cpu_dense[rs, shard] = db.cpu_dense[: len(rs)]
            host_fallback[rs] = db.host_fallback[: len(rs)]
        return _ShardedEncoded(attrs_val, members_c, cpu_dense, shard_of, row_of, host_fallback)

    def apply(self, encoded: _ShardedEncoded) -> np.ndarray:
        verdict = self._step(
            self.params,
            jnp.asarray(encoded.attrs_val),
            jnp.asarray(encoded.members_c),
            jnp.asarray(encoded.cpu_dense),
        )
        v = np.asarray(verdict)  # [B, S*G]
        flat = encoded.shard_of * self.configs_per_shard + encoded.row_of
        return v[np.arange(v.shape[0]), flat]

    def decide(self, docs: Sequence[Any], config_names: Sequence[str]) -> List[bool]:
        from ..models.policy_model import host_results

        enc = self.encode(docs, config_names)
        own = self.apply(enc)
        out = [bool(b) for b in own[: len(docs)]]
        for r in np.nonzero(enc.host_fallback[: len(docs)])[0]:
            shard, row = self.locator[config_names[r]]
            out[r], _, _ = host_results(self.shards[shard], docs[r], int(row))
        return out
