"""Mesh/sharding layer: dp (batch) × mp (rules/configs) policy evaluation."""

from .sharded_eval import ShardedPolicyModel, build_mesh  # noqa: F401
