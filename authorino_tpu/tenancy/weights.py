"""Tenant QoS weights and quotas (ISSUE 15).

The tenant is the unit the stack already carries per row: the AuthConfig
(``config_id`` / host identity).  Operators express QoS intent as AuthConfig
ANNOTATIONS — nothing new to deploy, and the weight travels with the config
through every control-plane path (reconcile, snapshot distribution,
replay):

- ``authorino.tpu/qos-class``:  a named service class (``gold``/``silver``/
  ``bronze``) mapping to a weight — the coarse knob most tenants use;
- ``authorino.tpu/qos-weight``: an explicit positive float weight,
  overriding the class — the fine knob;
- ``authorino.tpu/qos-quota-rps``: a per-tenant admission token-bucket rate
  (requests/second; absent or 0 = no quota).

Weights are RELATIVE shares for the weighted-fair batch cut
(tenancy/fair_cut.py): a weight-4 tenant may fill 4x the batch rows of a
weight-1 tenant when both are backlogged; an un-annotated tenant rides the
default class (weight 1).  The cut is work-conserving, so weights only bind
under contention — a sole-backlogged tenant always gets the whole batch.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

__all__ = ["WEIGHT_ANNOTATION", "CLASS_ANNOTATION", "QUOTA_ANNOTATION",
           "QOS_CLASSES", "DEFAULT_WEIGHT", "WeightBook",
           "weight_from_annotations", "quota_from_annotations"]

WEIGHT_ANNOTATION = "authorino.tpu/qos-weight"
CLASS_ANNOTATION = "authorino.tpu/qos-class"
QUOTA_ANNOTATION = "authorino.tpu/qos-quota-rps"

# the default class is the FLOOR, not zero: an un-annotated cold tenant must
# still hold a share against an annotated hot one
QOS_CLASSES: Dict[str, float] = {"gold": 4.0, "silver": 2.0, "bronze": 1.0}
DEFAULT_WEIGHT = 1.0


def weight_from_annotations(ann: Optional[Mapping[str, Any]],
                            default: float = DEFAULT_WEIGHT) -> float:
    """Resolve one tenant's weight from its AuthConfig annotations.
    Explicit weight wins over class; junk values fall back to the default
    (a typo must never zero a tenant's share)."""
    if not ann:
        return default
    raw = ann.get(WEIGHT_ANNOTATION)
    if raw is not None:
        try:
            w = float(raw)
            if w > 0:
                return w
        except (TypeError, ValueError):
            pass
    cls = ann.get(CLASS_ANNOTATION)
    if cls is not None:
        w = QOS_CLASSES.get(str(cls).strip().lower())
        if w:
            return w
    return default


def quota_from_annotations(ann: Optional[Mapping[str, Any]],
                           default: float = 0.0) -> float:
    """Per-tenant admission quota in requests/second (0 = unlimited)."""
    if not ann:
        return default
    raw = ann.get(QUOTA_ANNOTATION)
    if raw is None:
        return default
    try:
        q = float(raw)
        return q if q > 0 else default
    except (TypeError, ValueError):
        return default


class WeightBook:
    """The resolved (weight, quota) table for the serving snapshot's
    tenants.  Rebuilt at reconcile from entry annotations plus operator
    overrides (CLI ``--tenant-weight name=w``); reads are GIL-atomic dict
    lookups on the submit path."""

    def __init__(self, default_weight: float = DEFAULT_WEIGHT,
                 default_quota_rps: float = 0.0,
                 overrides: Optional[Dict[str, float]] = None):
        self.default_weight = max(float(default_weight), 1e-6)
        self.default_quota_rps = float(default_quota_rps)
        self.overrides = dict(overrides or {})
        self._weights: Dict[str, float] = {}
        self._quotas: Dict[str, float] = {}

    def rebuild(self, annotations_by_tenant: Mapping[str, Optional[Mapping[str, Any]]]) -> None:
        weights: Dict[str, float] = {}
        quotas: Dict[str, float] = {}
        for name, ann in annotations_by_tenant.items():
            weights[name] = weight_from_annotations(ann, self.default_weight)
            quotas[name] = quota_from_annotations(ann, self.default_quota_rps)
        for name, w in self.overrides.items():
            if w > 0:
                weights[name] = float(w)
        self._weights = weights
        self._quotas = quotas

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, self.default_weight)

    def quota_rps(self, tenant: str) -> float:
        return self._quotas.get(tenant, self.default_quota_rps)

    def global_share(self, tenant: str) -> float:
        """This tenant's weighted share among EVERY tenant the snapshot
        knows (the queue-occupancy entitlement): the shared queue belongs
        to the whole corpus, so occupancy bounds must not inflate just
        because the other tenants are currently fast enough to not
        backlog.  Falls back to 1.0 when the book is empty (single-tenant
        or pre-reconcile)."""
        if not self._weights:
            return 1.0
        total = sum(self._weights.values())
        mine = self.weight(tenant)
        if tenant not in self._weights:
            total += mine
        return mine / total if total > 0 else 1.0

    def share(self, tenant: str, among) -> float:
        """This tenant's weighted share among ``among`` (an iterable of
        tenant names, the backlogged set).  Returns 1.0 when the tenant is
        alone (or the set is empty) — share only binds under contention."""
        total = 0.0
        mine = self.weight(tenant)
        seen_self = False
        for t in among:
            total += self.weight(t)
            if t == tenant:
                seen_self = True
        if not seen_self:
            total += mine
        if total <= 0.0:
            return 1.0
        return mine / total

    def to_json(self) -> Dict[str, Any]:
        non_default = {t: w for t, w in self._weights.items()
                       if w != self.default_weight}
        quotas = {t: q for t, q in self._quotas.items() if q}
        return {
            "default_weight": self.default_weight,
            "default_quota_rps": self.default_quota_rps,
            "tenants": len(self._weights),
            "non_default_weights": dict(sorted(non_default.items())[:32]),
            "quotas": dict(sorted(quotas.items())[:32]),
            "overrides": dict(self.overrides),
        }
