"""The tenant QoS plane (ISSUE 15, docs/tenancy.md): one object wiring
weights, the fair cutter, per-tenant admission, the tenant observability
folds and the noisy-neighbor detector into the serving engines.

Integration seams (all per batch or per submit, never per request beyond a
dict lookup):

- ``PolicyEngine.submit``   -> ``admit`` (quota / containment pacing /
  tenant-aware doom depth) + ``on_enqueue``
- ``PolicyEngine._maybe_dispatch`` -> ``cut`` (the weighted-fair batch
  cut), ``on_dequeue``, ``split_contained`` (host-lane diversion)
- both lanes' completion folds -> ``fold`` (tenant counters, wait EWMAs,
  per-tenant SLO burn, detector cadence)
- ``/debug/tenants``        -> ``to_json``
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from .containment import NoisyNeighborDetector
from .fair_cut import FairCutter
from .quota import (
    R_TENANT_CONTAINED,
    R_TENANT_QUOTA,
    R_TENANT_SHARE,
    TenantAdmission,
)
from .stats import TenantStats
from .weights import WeightBook

__all__ = ["TenantPlane"]


class TenantPlane:
    def __init__(self, lane: str = "engine", enabled: bool = True,
                 default_weight: float = 1.0,
                 weight_overrides: Optional[Dict[str, float]] = None,
                 default_quota_rps: float = 0.0,
                 admission_target_s: float = 0.05,
                 contain_threshold: float = 3.0,
                 contain_sustain_s: float = 0.25,
                 # release hysteresis deliberately LONG relative to
                 # detection: containment's own success clears the
                 # pressure signals (that is the point), so a short
                 # release timer would oscillate — release, re-flood,
                 # re-contain — once per timer period for as long as the
                 # neighbor stays noisy.  Re-containment takes ~sustain_s,
                 # so the cost of a late release is negligible; the cost
                 # of an early one is the whole containment win.
                 contain_release_s: float = 5.0,
                 contain_allowance_rps: float = 100.0,
                 top_k: int = 16,
                 wait_ewma=None, wait_target_s=None, reject_count=None):
        self.lane = lane
        self.enabled = bool(enabled)
        self.book = WeightBook(default_weight=default_weight,
                               default_quota_rps=default_quota_rps,
                               overrides=weight_overrides)
        self.cutter = FairCutter(self.book.weight)
        self.admission = TenantAdmission(self.book,
                                         target_s=admission_target_s)
        self.stats = TenantStats(lane, top_k=top_k)
        self.stats.wait_sink = self.admission.observe_waits
        self.detector = NoisyNeighborDetector(
            self.book, self.stats,
            wait_ewma=wait_ewma or (lambda: 0.0),
            target_s=wait_target_s or (lambda: admission_target_s),
            lane=lane, threshold=contain_threshold,
            sustain_s=contain_sustain_s, release_s=contain_release_s,
            allowance_rps=contain_allowance_rps,
            reject_count=reject_count)

    # -- reconcile ----------------------------------------------------------

    def bind_entries(self, entries) -> None:
        """Rebuild the weight/quota book from the reconcile's entries (the
        AuthConfig annotations travel on EngineEntry)."""
        self.book.rebuild({
            e.id: getattr(e, "annotations", None) for e in entries})

    # -- admission (engine submit path) -------------------------------------

    def admit(self, tenant: str, now: Optional[float] = None,
              depth: int = 0,
              effective_cap: int = 0) -> Optional[Tuple[int, str]]:
        """Tenant-scoped admission decision: quota first, then the
        per-tenant queue-occupancy bound (``depth``/``effective_cap`` are
        the shared queue's live depth and wait-targeted cap), then
        containment pacing.  Returns None (admitted) or the typed
        (code, reason)."""
        if not self.enabled:
            return None
        now = time.monotonic() if now is None else now
        rej = self.admission.quota_reject(tenant, now=now)
        if rej is None:
            rej = self.admission.share_reject(tenant, depth, effective_cap)
        if rej is not None:
            return rej
        if self.detector.is_contained(tenant) and \
                self.detector.pace_reject(tenant, now=now):
            from ..utils.rpc import RESOURCE_EXHAUSTED

            return (RESOURCE_EXHAUSTED, R_TENANT_CONTAINED)
        return None

    def count_reject(self, tenant: str, reason: str) -> None:
        self.admission.count_reject(tenant, reason)
        self.stats.count_reject(tenant, reason)

    def doom_depth(self, tenant: str, global_depth: int) -> Optional[int]:
        """Tenant-aware depth for the doomed-deadline predictor, or None
        when the plane is off (global behavior)."""
        if not self.enabled:
            return None
        return self.admission.doom_depth(tenant, global_depth)

    # -- the cut (engine queue lock held) -----------------------------------

    def cut(self, queue, n: int) -> List[Any]:
        return self.cutter.cut(queue, n)

    def on_enqueue(self, tenant: str) -> None:
        if self.enabled:
            self.admission.on_enqueue(tenant)

    def on_dequeue(self, batch) -> None:
        if self.enabled:
            self.admission.on_dequeue(batch)

    def has_contained(self) -> bool:
        return self.enabled and self.detector.has_contained()

    def is_contained(self, tenant: str) -> bool:
        return self.enabled and self.detector.is_contained(tenant)

    def split_contained(self, batch) -> Tuple[List[Any], List[Any]]:
        """(keep, diverted): contained tenants' rows peel off to the exact
        host-oracle lane."""
        keep, div = [], []
        for p in batch:
            (div if self.detector.is_contained(p.config_name)
             else keep).append(p)
        return keep, div

    # -- the per-batch fold --------------------------------------------------

    def fold(self, heat, rows, firing=None, shards=None, waits=None,
             bad_mask=None, denied_mask=None,
             lane: Optional[str] = None) -> None:
        if not self.enabled:
            return
        self.stats.fold(heat, rows, firing=firing, shards=shards,
                        waits=waits, bad_mask=bad_mask,
                        denied_mask=denied_mask, lane=lane)
        self.detector.maybe_check()

    # -- introspection -------------------------------------------------------

    def to_json(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "lane": self.lane,
            "weights": self.book.to_json(),
            "fair_cut": self.cutter.to_json(),
            "admission": self.admission.to_json(),
            "stats": self.stats.to_json(),
            "containment": self.detector.to_json(),
        }
