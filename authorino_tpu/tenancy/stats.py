"""Per-tenant observability folds (ISSUE 15): the tenant axis of PR 9's
vectorized provenance/SLO folds.

One call per micro-batch (never per request): ``fold`` groups the batch's
kernel rows by tenant with one ``np.unique`` + ``np.bincount`` pass — the
Python work is bounded by DISTINCT tenants in the batch, exactly the
composite-key discipline the rule heat map set — and accumulates per-tenant
requests, denies, queue-wait means, SLO bad counts and a served-rate EWMA
(the noisy-neighbor detector's share signal).

Prometheus exposition is bounded-cardinality by construction: the flush
(amortized on a cadence, forced by /debug reads) assigns real tenant label
values only to the top-K tenants by cumulative request volume and folds
everyone else into the reserved ``other`` bucket.  K is clamped to the
family's declared hard bound in ``utils.metrics.TENANT_LABEL_BOUNDS`` —
the table the metrics-catalog cardinality lint enforces."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ..utils import metrics as metrics_mod
from ..utils.slo import KeyedBurn

__all__ = ["TenantStats"]


class _TenantCounters:
    __slots__ = ("requests", "denies", "slo_bad", "wait_ewma", "rate_ewma",
                 "rate_t", "rate_pend", "last_seen")

    def __init__(self, now: float):
        self.requests = 0
        self.denies = 0
        self.slo_bad = 0
        self.wait_ewma = 0.0
        self.rate_ewma = 0.0   # served rows/s (decaying)
        self.rate_t = now
        # rows folded since the last rate-EWMA step: batches can land far
        # faster than the 50ms rate window, and dividing only the LAST
        # batch's rows by the full elapsed dt would silently undercount
        # exactly the hot tenants the detector's share signal exists for
        self.rate_pend = 0
        self.last_seen = now


class TenantStats:
    FLUSH_S = 2.0

    def __init__(self, lane: str, top_k: int = 16, max_tenants: int = 8192,
                 burn_window_s: float = 60.0, gc_idle_s: float = 600.0):
        self.lane = lane
        bound = min(metrics_mod.TENANT_LABEL_BOUNDS.get(
            "auth_server_tenant_requests_total", 32), 32)
        self.top_k = max(1, min(int(top_k), bound))
        self.max_tenants = int(max_tenants)
        self.gc_idle_s = float(gc_idle_s)
        self._lock = threading.Lock()
        self._t: Dict[str, _TenantCounters] = {}
        # Prometheus deltas keyed by the FOLD's lane (the plane is shared
        # across engine + native; the aggregate _t table serves shares/
        # waits, but exported counters must say which lane served)
        self._lane_delta: Dict[str, Dict[str, list]] = {}
        self.burn = KeyedBurn(window_s=burn_window_s)
        self._last_flush = time.monotonic()
        self._label_of: Dict[str, str] = {}  # tenant -> prometheus label
        self.fold_calls = 0
        self.total_requests = 0
        # wait-observation sink (TenantAdmission.observe_waits), attached
        # by the plane so the per-tenant CoDel signal rides this same fold
        self.wait_sink = None

    # -- folding (one call per batch) ---------------------------------------

    def fold(self, heat, rows, firing=None, shards=None, waits=None,
             bad_mask=None, denied_mask=None, lane: Optional[str] = None,
             now: Optional[float] = None) -> None:
        """Fold one batch's tenant axis.  ``heat`` resolves kernel rows to
        tenant names (the snapshot's HeatMap — attribution and tenancy
        read identical evidence); ``firing`` (or ``denied_mask``) marks
        denials; ``waits`` (seconds, per row, optional) are QUEUE waits —
        they feed the per-tenant wait EWMAs and the per-tenant CoDel sink
        (pass None on lanes without a per-request queue clock);
        ``bad_mask`` (bool per row, optional) marks SLO-budget burns
        (callers decide the SLI — sojourn vs batch round trip); ``lane``
        labels the Prometheus deltas (defaults to the plane's lane)."""
        if heat is None:
            return
        rows = np.asarray(rows, dtype=np.int64)
        n = int(rows.size)
        if not n:
            return
        now = time.monotonic() if now is None else now
        lane = lane or self.lane
        self.fold_calls += 1
        self.total_requests += n
        flat = rows
        cps = getattr(heat, "configs_per_shard", None)
        if shards is not None and cps:
            flat = np.asarray(shards, dtype=np.int64) * cps + rows
        if denied_mask is None and firing is not None:
            denied_mask = np.asarray(firing, dtype=np.int64) >= 0
        uniq, inv = np.unique(flat, return_inverse=True)
        tot = np.bincount(inv, minlength=len(uniq))
        den = (np.bincount(inv[denied_mask], minlength=len(uniq))
               if denied_mask is not None and np.any(denied_mask)
               else np.zeros(len(uniq), dtype=np.int64))
        if waits is not None:
            waits = np.asarray(waits, dtype=np.float64)
            if waits.size == n:
                wsum = np.bincount(inv, weights=waits, minlength=len(uniq))
                wmin = np.full(len(uniq), np.inf)
                np.minimum.at(wmin, inv, waits)
            else:
                waits = None
        bad = None
        if bad_mask is not None:
            bad_mask = np.asarray(bad_mask, dtype=bool)
            bad = (np.bincount(inv[bad_mask], minlength=len(uniq))
                   if np.any(bad_mask)
                   else np.zeros(len(uniq), dtype=np.int64))
        with self._lock:
            per_lane = self._lane_delta.setdefault(lane, {})
            for i, u in enumerate(uniq):
                name = heat.name(int(u))
                if not name:
                    continue
                c = self._t.get(name)
                if c is None:
                    c = self._t[name] = _TenantCounters(now)
                k = int(tot[i])
                c.requests += k
                c.denies += int(den[i])
                c.last_seen = now
                # served-rate EWMA: rows accumulate across folds inside
                # the 50ms window, then the whole window's rows divide
                # the elapsed dt (never just the last batch's)
                c.rate_pend += k
                dt = now - c.rate_t
                if dt > 0.05:
                    inst = c.rate_pend / dt
                    c.rate_ewma = inst if not c.rate_ewma else \
                        0.7 * c.rate_ewma + 0.3 * inst
                    c.rate_t = now
                    c.rate_pend = 0
                if waits is not None:
                    mean = float(wsum[i]) / k
                    c.wait_ewma = mean if not c.wait_ewma else \
                        0.8 * c.wait_ewma + 0.2 * mean
                    if self.wait_sink is not None:
                        self.wait_sink(name, mean, float(wmin[i]), now)
                b = int(bad[i]) if bad is not None else 0
                if b:
                    c.slo_bad += b
                if bad is not None:
                    self.burn.fold(name, k, b, now=now)
                d = per_lane.setdefault(name, [0, 0, 0])
                d[0] += k
                d[1] += int(den[i])
                d[2] += b
        if now - self._last_flush > self.FLUSH_S:
            self.flush(now=now)

    # -- shares (the detector's signal) -------------------------------------

    def share(self, tenant: str) -> float:
        """This tenant's share of the lane's recently-served rows (rate
        EWMAs — decays as traffic shifts)."""
        with self._lock:
            c = self._t.get(tenant)
            if c is None or not c.rate_ewma:
                return 0.0
            total = sum(x.rate_ewma for x in self._t.values())
            return c.rate_ewma / total if total > 0 else 0.0

    def shares(self) -> Dict[str, float]:
        with self._lock:
            total = sum(x.rate_ewma for x in self._t.values())
            if total <= 0:
                return {}
            return {t: c.rate_ewma / total for t, c in self._t.items()
                    if c.rate_ewma > 0}

    def rate(self, tenant: str) -> float:
        with self._lock:
            c = self._t.get(tenant)
            return c.rate_ewma if c is not None else 0.0

    def export_fold(self) -> Dict[str, Dict[str, float]]:
        """Raw per-tenant counters for the fleet fold publisher (ISSUE 18):
        cumulative requests/denies/slo_bad plus the live served-rate EWMA.
        Cumulative counts let the aggregator difference consecutive folds
        into deltas; the rate EWMAs are what global tenant share sums —
        per-replica SHARES cannot be averaged (consistent-hash routing
        concentrates tenants, so a fleet-hot tenant can look locally
        entitled on every replica at once — the exact blindness the global
        fold exists to remove)."""
        with self._lock:
            return {name: {
                "requests": c.requests,
                "denies": c.denies,
                "slo_bad": c.slo_bad,
                "rate": c.rate_ewma,
            } for name, c in self._t.items()}

    # -- prometheus flush (top-K + other) -----------------------------------

    def _labels(self) -> Dict[str, str]:
        """Tenant -> label value: the top-K tenants by cumulative volume
        get their own value, everyone else folds into `other`.  A tenant
        that falls OUT of the top-K keeps its minted label (monotonic
        counters must not teleport into `other`); the hard bound holds
        because minted labels only grow to the bound and then stop."""
        ranked = sorted(self._t.items(), key=lambda kv: -kv[1].requests)
        bound = min(metrics_mod.TENANT_LABEL_BOUNDS.get(
            "auth_server_tenant_requests_total", 32), 32)
        for name, _ in ranked[:self.top_k]:
            if name not in self._label_of and len(self._label_of) < bound:
                self._label_of[name] = name
        return self._label_of

    def flush(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._last_flush = now
            labels = self._labels()
            deltas = []
            for lane, per in self._lane_delta.items():
                for name, (dr, dd, db) in per.items():
                    deltas.append((lane,
                                   labels.get(name,
                                              metrics_mod.TENANT_OTHER),
                                   dr, dd, db))
            self._lane_delta.clear()
            gauges = [(labels[name], c.wait_ewma) for name, c in
                      self._t.items() if name in labels]
            if len(self._t) > self.max_tenants:
                for t in [t for t, c in self._t.items()
                          if now - c.last_seen > self.gc_idle_s]:
                    self._t.pop(t, None)
        for lane, label, dr, dd, db in deltas:
            if dr:
                metrics_mod.tenant_requests.labels(lane, label).inc(dr)
            if dd:
                metrics_mod.tenant_denied.labels(lane, label).inc(dd)
            if db:
                metrics_mod.tenant_slo_bad.labels(lane, label).inc(db)
        for label, w in gauges:
            metrics_mod.tenant_queue_wait.labels(label).set(round(w, 6))

    def count_reject(self, tenant: str, reason: str) -> None:
        with self._lock:
            label = self._label_of.get(tenant, metrics_mod.TENANT_OTHER)
        metrics_mod.tenant_rejected.labels(label, reason).inc()

    # -- introspection -------------------------------------------------------

    def to_json(self, top: int = 16) -> Dict[str, Any]:
        with self._lock:
            ranked = sorted(self._t.items(), key=lambda kv: -kv[1].requests)
            total_rate = sum(c.rate_ewma for _, c in ranked) or 1.0
            rows = [{
                "tenant": name,
                "requests": c.requests,
                "denies": c.denies,
                "slo_bad": c.slo_bad,
                "queue_wait_ewma_ms": round(c.wait_ewma * 1e3, 3),
                "share": round(c.rate_ewma / total_rate, 4),
            } for name, c in ranked[:top]]
            n = len(self._t)
        return {
            "lane": self.lane,
            "tenants_seen": n,
            "top_k": self.top_k,
            "fold_calls": self.fold_calls,
            "requests_total": self.total_requests,
            "top": rows,
            "slo_burn": self.burn.to_json(top=8),
        }
