"""Per-tenant admission: token-bucket quotas, per-tenant CoDel wait
tracking, and tenant-aware doomed-deadline depth (ISSUE 15).

The global admission gate (runtime/admission.py) protects the PROCESS; this
module scopes the same disciplines to one tenant so the protection itself
cannot become a noisy-neighbor amplifier:

- **quota**: a per-tenant token bucket (rate from the
  ``authorino.tpu/qos-quota-rps`` annotation or the CLI default).  A tenant
  over its quota gets a typed ``RESOURCE_EXHAUSTED`` scoped to THAT tenant
  — the global OVERLOADED latch is untouched and every other tenant keeps
  its full admission budget;
- **per-tenant CoDel wait**: each tenant's observed queue waits feed its
  own EWMA + standing-above-target detector (the same min-wait discipline
  as the global gate, folded per batch from the tenant axis) — surfaced on
  /debug/tenants and consumed by the noisy-neighbor detector;
- **tenant-aware doom depth**: the doomed-deadline shedder used to predict
  wait from the GLOBAL queue depth, so one tenant's standing backlog doomed
  every tenant's deadlines.  ``doom_depth`` returns the depth THIS tenant's
  request actually waits behind under the weighted-fair cut: its own
  backlog scaled by the inverse of its fair share.  A cold tenant in front
  of a hot standing queue predicts a near-zero wait — exactly what the
  fair cut delivers.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..utils.rpc import RESOURCE_EXHAUSTED

__all__ = ["TenantAdmission", "R_TENANT_QUOTA", "R_TENANT_CONTAINED",
           "R_TENANT_SHARE", "TokenBucket"]

# rejection reason labels (ride auth_server_admission_rejected_total and
# auth_server_tenant_rejected_total)
R_TENANT_QUOTA = "tenant-quota"
R_TENANT_CONTAINED = "tenant-contained"
R_TENANT_SHARE = "tenant-queue-share"


class TokenBucket:
    __slots__ = ("rate", "burst", "tokens", "t")

    def __init__(self, rate: float, burst: Optional[float] = None,
                 now: Optional[float] = None):
        self.rate = float(rate)
        # one second of burst headroom by default: quotas bound sustained
        # rates, they must not chop a normal arrival burst into rejections
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self.tokens = self.burst
        self.t = time.monotonic() if now is None else now

    def allow(self, now: Optional[float] = None, n: float = 1.0) -> bool:
        now = time.monotonic() if now is None else now
        self.tokens = min(self.burst,
                          self.tokens + (now - self.t) * self.rate)
        self.t = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class _TenantWait:
    """One tenant's CoDel-ish wait state, fed per batch (never per
    request) from the tenant-axis fold."""

    __slots__ = ("ewma", "above_since", "overloaded", "last_obs")

    def __init__(self):
        self.ewma = 0.0
        self.above_since: Optional[float] = None
        self.overloaded = False
        self.last_obs = 0.0


class TenantAdmission:
    """Per-tenant admission state for one serving lane.  All feeds are per
    batch or per submit; every dict is bounded by live tenants (entries of
    tenants idle past ``gc_idle_s`` are dropped on the amortized sweep)."""

    def __init__(self, weight_book, target_s: float = 0.05,
                 interval_s: float = 0.5, gc_idle_s: float = 300.0,
                 max_tenants: int = 8192):
        self.book = weight_book
        self.target_s = float(target_s)
        self.interval_s = float(interval_s)
        self.gc_idle_s = float(gc_idle_s)
        self.max_tenants = int(max_tenants)
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._waits: Dict[str, _TenantWait] = {}
        self._backlog: Dict[str, int] = {}
        self.rejected: Dict[str, Dict[str, int]] = {}  # tenant -> reason -> n
        self._last_gc = time.monotonic()

    # -- backlog ------------------------------------------------------------
    # enqueue/dequeue run under the engine's queue lock, but doom_depth
    # reads from event loops WITHOUT it — the plane's own lock makes the
    # backlog-iteration in share() safe (an unguarded dict iteration under
    # concurrent dequeues raises RuntimeError mid-submit)

    def on_enqueue(self, tenant: str) -> None:
        with self._lock:
            self._backlog[tenant] = self._backlog.get(tenant, 0) + 1

    def on_dequeue(self, batch) -> None:
        with self._lock:
            for p in batch:
                t = p.config_name
                left = self._backlog.get(t, 0) - 1
                if left > 0:
                    self._backlog[t] = left
                else:
                    self._backlog.pop(t, None)

    def backlog(self, tenant: str) -> int:
        return self._backlog.get(tenant, 0)

    def backlogged_tenants(self):
        with self._lock:
            return list(self._backlog)

    # -- quota --------------------------------------------------------------

    def quota_reject(self, tenant: str,
                     now: Optional[float] = None) -> Optional[Tuple[int, str]]:
        rate = self.book.quota_rps(tenant)
        if not rate:
            return None
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None or bucket.rate != rate:
                bucket = TokenBucket(rate, now=now)
                self._buckets[tenant] = bucket
            if bucket.allow(now):
                return None
        return (RESOURCE_EXHAUSTED, R_TENANT_QUOTA)

    # -- per-tenant queue-occupancy bound -----------------------------------

    # headroom over the exact weighted share of the queue cap, and the
    # floor below which the bound never bites (a burst of a handful of
    # rows is normal arrival jitter, not occupation)
    SHARE_HEADROOM = 2.0
    SHARE_FLOOR = 16

    def share_reject(self, tenant: str, global_depth: int,
                     effective_cap: int) -> Optional[Tuple[int, str]]:
        """Per-tenant queue-occupancy bound — the WFQ companion the fair
        cut needs at ADMISSION time: the cut divides service fairly, but
        the shared queue itself is a bounded resource, and a flooding
        tenant that fills it to the global cap gets every OTHER tenant's
        arrivals rejected indiscriminately by the global gate (and worse,
        only after they waited).  Once the queue is past half its
        wait-targeted cap, a tenant whose own standing backlog already
        exceeds its weighted share of the cap (x SHARE_HEADROOM, floored)
        is rejected typed and tenant-scoped IMMEDIATELY — milliseconds,
        not detector latency — so the queue always keeps room for
        everyone else.  Below half-cap the bound never bites: bursts into
        an idle queue are absorbed whole (work conservation)."""
        if effective_cap <= 0 or global_depth < effective_cap // 2:
            return None
        mine = self._backlog.get(tenant, 0)
        if mine < self.SHARE_FLOOR:
            return None
        # entitlement against the WHOLE corpus (global_share), not the
        # currently-backlogged set: the shared queue belongs to every
        # tenant, and a flooding tenant must not earn a bigger occupancy
        # just because its victims are momentarily fast enough to drain
        share = self.book.global_share(tenant)
        limit = max(self.SHARE_FLOOR,
                    int(self.SHARE_HEADROOM * share * effective_cap))
        if mine >= limit:
            return (RESOURCE_EXHAUSTED, R_TENANT_SHARE)
        return None

    # -- tenant-aware doomed depth -------------------------------------------

    def doom_depth(self, tenant: str, global_depth: int) -> int:
        """The queue depth this tenant's NEXT request effectively waits
        behind under the weighted-fair cut: its own backlog divided by its
        fair share of service.  Bounded by the global depth — fair queuing
        can only make a tenant's wait shorter than FIFO, never longer."""
        mine = self._backlog.get(tenant, 0)
        if mine <= 0:
            return 0
        with self._lock:
            among = list(self._backlog)
        share = self.book.share(tenant, among)
        eff = int(mine / max(share, 1e-6))
        return min(eff, int(global_depth))

    # -- per-tenant CoDel wait (fed per batch from the tenant fold) ---------

    def observe_waits(self, tenant: str, mean_wait: float, min_wait: float,
                      now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            w = self._waits.get(tenant)
            if w is None:
                w = self._waits[tenant] = _TenantWait()
            w.last_obs = now
            w.ewma = mean_wait if not w.ewma else \
                0.8 * w.ewma + 0.2 * mean_wait
            if min_wait <= self.target_s:
                w.above_since = None
                w.overloaded = False
            elif w.above_since is None:
                w.above_since = now
            elif now - w.above_since >= self.interval_s:
                w.overloaded = True
        self._maybe_gc(now)

    def wait_ewma(self, tenant: str) -> float:
        w = self._waits.get(tenant)
        return w.ewma if w is not None else 0.0

    def overloaded(self, tenant: str) -> bool:
        w = self._waits.get(tenant)
        return bool(w is not None and w.overloaded)

    # -- accounting ----------------------------------------------------------

    def count_reject(self, tenant: str, reason: str) -> None:
        with self._lock:
            per = self.rejected.setdefault(tenant, {})
            per[reason] = per.get(reason, 0) + 1

    def _maybe_gc(self, now: float) -> None:
        if (now - self._last_gc < self.gc_idle_s
                and len(self._waits) <= self.max_tenants):
            return
        with self._lock:
            self._last_gc = now
            stale = [t for t, w in self._waits.items()
                     if now - w.last_obs > self.gc_idle_s
                     and t not in self._backlog]
            for t in stale:
                self._waits.pop(t, None)
                self._buckets.pop(t, None)

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            overloaded = sorted(t for t, w in self._waits.items()
                                if w.overloaded)
            worst = sorted(((t, round(w.ewma, 6))
                            for t, w in self._waits.items()),
                           key=lambda x: -x[1])[:8]
        return {
            "target_s": self.target_s,
            "backlogged_tenants": len(self._backlog),
            "tracked_tenants": len(self._waits),
            "overloaded_tenants": overloaded[:16],
            "worst_wait_ewma_s": dict(worst),
            "rejected": {t: dict(r)
                         for t, r in sorted(self.rejected.items())[:32]},
        }
