"""Tenant QoS plane (ISSUE 15, docs/tenancy.md): weighted-fair batch cuts,
per-tenant SLO/quotas, and noisy-neighbor containment — the tenant is the
(AuthConfig/host) identity every kernel row already carries as
``config_id``."""

from .containment import NoisyNeighborDetector
from .fair_cut import FairCutter
from .plane import TenantPlane
from .quota import R_TENANT_CONTAINED, R_TENANT_QUOTA, TenantAdmission, TokenBucket
from .stats import TenantStats
from .weights import (
    CLASS_ANNOTATION,
    DEFAULT_WEIGHT,
    QOS_CLASSES,
    QUOTA_ANNOTATION,
    WEIGHT_ANNOTATION,
    WeightBook,
)

__all__ = [
    "TenantPlane", "FairCutter", "TenantAdmission", "TenantStats",
    "NoisyNeighborDetector", "TokenBucket", "WeightBook",
    "WEIGHT_ANNOTATION", "CLASS_ANNOTATION", "QUOTA_ANNOTATION",
    "QOS_CLASSES", "DEFAULT_WEIGHT", "R_TENANT_QUOTA",
    "R_TENANT_CONTAINED",
]
