"""Weighted-fair batch cuts (ISSUE 15): deficit-round-robin INSIDE the
engine's submit-queue cut — fairness is a property of the cut, not a
pre-queue.

The engine's dispatcher used to pop the leftmost ``n`` requests per cut.
Under a hot-tenant burst that is strictly FIFO-unfair: the hot tenant's
standing queue fills every batch and a cold tenant's lone request waits out
the entire hot backlog.  The fair cutter replaces the pop with a
deficit-round-robin selection over per-tenant virtual queues (materialized
from the one real deque at cut time — requests never migrate between
queues, so arrival order within a tenant is preserved exactly):

- each backlogged tenant accrues ``quantum x weight`` deficit per round and
  takes rows while its deficit covers them (row cost 1);
- the cut loops rounds until ``n`` rows are selected or the queue is empty —
  WORK-CONSERVING by construction: unused share spills to whoever is still
  backlogged, and a sole-backlogged tenant always gets the whole batch;
- deficits PERSIST across cuts while a tenant stays backlogged (share
  accuracy converges within one batch of slack) and reset when its virtual
  queue empties (classic DRR — an idle tenant cannot bank credit into a
  later burst);
- the selected rows keep their ARRIVAL order inside the batch, and the
  unselected remainder keeps its arrival order in the queue — fairness
  reorders service, it never re-decides anything (the kernel is a pure
  per-row function; tests pin byte-identical verdict + attribution vs the
  unfair cut).

Cost: one pass over the queue per cut, O(depth) — and the cutter only runs
when the cut is actually contended (depth > n); an uncontended cut takes
everything, exactly like the unfair pop.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List

__all__ = ["FairCutter"]


def _tenant_of(p: Any) -> str:
    return p.config_name


class FairCutter:
    """Deficit-round-robin cut over the engine's pending deque.

    ``cut(queue, n)`` MUTATES the deque: selected items are removed (and
    returned in arrival order), the rest stay queued in arrival order.
    Callers hold the queue lock; the cutter's own state (the persistent
    deficit table) has its own lock only for introspection safety."""

    def __init__(self, weight_of: Callable[[str], float],
                 quantum: float = 1.0, max_tenants: int = 4096):
        self.weight_of = weight_of
        self.quantum = max(float(quantum), 1e-6)
        self.max_tenants = int(max_tenants)
        self._deficit: Dict[str, float] = {}
        # persistent round-robin pointer: the tenant AFTER the one the
        # previous cut's boundary landed on starts the next cut — without
        # it, every cut would restart the round at the same tenant and the
        # boundary would systematically truncate the late tenants' share
        # (an ~0.5-row-per-cut bias the share-accuracy property test
        # catches over a few dozen cuts)
        self._last_served: str = ""
        self._lock = threading.Lock()
        self.cuts = 0
        self.contended_cuts = 0

    def cut(self, queue: deque, n: int) -> List[Any]:
        """Select up to ``n`` items from ``queue`` by weighted fair share."""
        self.cuts += 1
        depth = len(queue)
        if depth <= n:
            # uncontended: take everything (the unfair pop's exact result)
            out = list(queue)
            queue.clear()
            return out
        self.contended_cuts += 1
        # materialize per-tenant virtual queues (item order = arrival order)
        per: Dict[str, List[Any]] = {}
        arrival: List[Any] = list(queue)
        for p in arrival:
            per.setdefault(_tenant_of(p), []).append(p)
        with self._lock:
            deficit = self._deficit
            # round-robin over a stable tenant order; rounds continue until
            # the cut is full — work conserving
            heads: Dict[str, int] = {t: 0 for t in per}
            selected: set = set()
            taken = 0
            active = [t for t in per]
            if self._last_served in per:
                i = active.index(self._last_served) + 1
                active = active[i:] + active[:i]
            while taken < n and active:
                progressed = False
                still = []
                for t in active:
                    q = per[t]
                    h = heads[t]
                    if h >= len(q):
                        # virtual queue drained inside this cut: classic
                        # DRR deficit reset (no banking)
                        deficit.pop(t, None)
                        continue
                    # weight floor 0.05: a pathologically tiny weight must
                    # not turn one row into hundreds of accrual rounds
                    d = deficit.get(t, 0.0) + self.quantum * \
                        max(self.weight_of(t), 0.05)
                    while h < len(q) and d >= 1.0 and taken < n:
                        selected.add(id(q[h]))
                        h += 1
                        d -= 1.0
                        taken += 1
                        progressed = True
                        self._last_served = t
                    heads[t] = h
                    if h >= len(q):
                        # drained by this round: reset, nothing to carry
                        deficit.pop(t, None)
                    else:
                        deficit[t] = d
                        still.append(t)
                    if taken >= n:
                        break
                active = still
                if not progressed and active:
                    # every active tenant is below one row of deficit:
                    # loop again (each round adds quantum x weight) — with
                    # quantum >= 1 this cannot happen, but guard float dust
                    continue
            # tenants that left the queue entirely drop their deficit so
            # the table stays bounded by live tenants
            if len(deficit) > self.max_tenants:
                for t in list(deficit):
                    if t not in per:
                        deficit.pop(t, None)
        batch = [p for p in arrival if id(p) in selected]
        queue.clear()
        queue.extend(p for p in arrival if id(p) not in selected)
        return batch

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "quantum": self.quantum,
                "cuts": self.cuts,
                "contended_cuts": self.contended_cuts,
                "tenants_with_deficit": len(self._deficit),
            }
