"""Noisy-neighbor containment (ISSUE 15): a TENANT-scoped brownout/shed
instead of the global OVERLOADED latch.

Detection rides the per-tenant folds (tenancy/stats.py) on an amortized
cadence — never per request:

    contain(t) when  share(t) > weight_share(t) x threshold
               AND   global queue wait EWMA > the admission wait target
               ... sustained for ``sustain_s``

Both conditions matter: a hot tenant on an idle box is just traffic
(weights only bind under contention — the fair cut already gives everyone
their share), and a loaded box with proportional shares has no neighbor to
blame.  While contained, the tenant's rows are diverted at the batch cut to
the exact host-oracle lane (verdicts identical by construction — the oracle
is the kernel's reference) and, past a paced allowance, rejected typed
``RESOURCE_EXHAUSTED``/``tenant-contained`` at admission.  The global
latch, breaker and brownout state never see any of it.

Containment AUTO-RELEASES on decay: once the tenant's share falls back
inside its weighted entitlement (or the global wait clears) for
``release_s``, the clamp lifts.  Every transition lands in the flight
recorder; the CONTAIN transition is an anomaly (kind ``tenant-contained``)
and auto-dumps a diagnostic bundle."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..utils import metrics as metrics_mod
from .quota import TokenBucket

__all__ = ["NoisyNeighborDetector"]


class NoisyNeighborDetector:
    def __init__(self, weight_book, stats, wait_ewma: Callable[[], float],
                 target_s: Callable[[], float], lane: str = "engine",
                 threshold: float = 3.0, sustain_s: float = 0.5,
                 release_s: float = 5.0, min_share: float = 0.05,
                 max_contained: int = 8, check_interval_s: float = 0.1,
                 allowance_rps: float = 100.0, reject_count=None):
        """``threshold`` multiplies the tenant's WEIGHTED share entitlement
        (share > weight_share x threshold); ``min_share`` is an absolute
        floor so a 0.1%-share tenant can never be 'noisy' whatever its
        weight.  ``allowance_rps`` paces how much contained traffic still
        flows (host-lane diversion + typed rejections beyond it).

        ``reject_count`` (optional zero-arg callable): a monotonically
        increasing count of GLOBAL admission rejections (overload /
        queue-full).  It is the second pressure signal: the wait-targeted
        admission cap CLAMPS the queue at exactly the wait target — and
        the fair cut keeps the CoDel min-wait low by serving cold rows
        promptly — so under a contained-size queue + indiscriminate cap
        rejections the wait EWMA alone can sit right AT the target while
        cold tenants are being turned away.  Rising global rejections are
        pressure, whatever the wait gauge says."""
        self.book = weight_book
        self.stats = stats
        self.wait_ewma = wait_ewma
        self.target_s = target_s
        self.lane = lane
        self.threshold = float(threshold)
        self.sustain_s = float(sustain_s)
        self.release_s = float(release_s)
        self.min_share = float(min_share)
        self.max_contained = int(max_contained)
        self.check_interval_s = float(check_interval_s)
        self.allowance_rps = float(allowance_rps)
        self.reject_count = reject_count
        self._last_rejects = 0.0
        self._lock = threading.Lock()
        self._hot_since: Dict[str, float] = {}
        self._cool_since: Dict[str, float] = {}
        self._contained: Dict[str, Dict[str, Any]] = {}
        self._pacers: Dict[str, TokenBucket] = {}
        self._last_check = 0.0
        self.contain_total = 0
        self.release_total = 0

    # -- the per-batch entry point (amortized) -------------------------------

    def maybe_check(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if now - self._last_check < self.check_interval_s:
            return
        self._last_check = now
        try:
            self.check(now)
        except Exception:  # a detector bug must never fail a batch
            import logging

            logging.getLogger("authorino_tpu.tenancy").exception(
                "noisy-neighbor check failed (serving unaffected)")

    def check(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        shares = self.stats.shares()
        wait_hot = self.wait_ewma() > self.target_s()
        if self.reject_count is not None:
            try:
                r = float(self.reject_count())
            except Exception:
                r = self._last_rejects
            if r > self._last_rejects:
                wait_hot = True
            self._last_rejects = r
        weights_among = list(shares) or None
        with self._lock:
            # --- containment candidates
            if wait_hot and weights_among:
                for t, share in shares.items():
                    if t in self._contained:
                        continue
                    entitled = self.book.share(t, weights_among)
                    if share > max(entitled * self.threshold,
                                   self.min_share):
                        since = self._hot_since.setdefault(t, now)
                        if (now - since >= self.sustain_s
                                and len(self._contained)
                                < self.max_contained):
                            self._contain(t, share, entitled, now)
                    else:
                        self._hot_since.pop(t, None)
            else:
                self._hot_since.clear()
            # --- auto-release on decay
            for t in list(self._contained):
                share = shares.get(t, 0.0)
                entitled = self.book.share(t, weights_among or [t])
                cooled = (not wait_hot) or share <= entitled * 1.1
                if cooled:
                    since = self._cool_since.setdefault(t, now)
                    if now - since >= self.release_s:
                        self._release(t, now)
                else:
                    self._cool_since.pop(t, None)

    def _contain(self, tenant: str, share: float, entitled: float,
                 now: float) -> None:
        self._hot_since.pop(tenant, None)
        self._cool_since.pop(tenant, None)
        self._contained[tenant] = {
            "since": now, "share_at_contain": round(share, 4),
            "entitled_share": round(entitled, 4),
        }
        self._pacers[tenant] = TokenBucket(self.allowance_rps, now=now)
        self.contain_total += 1
        metrics_mod.tenant_contained.labels(tenant).set(1)
        from ..runtime.flight_recorder import RECORDER

        RECORDER.record("tenant-contained", lane=self.lane, detail={
            "tenant": tenant, "share": round(share, 4),
            "entitled_share": round(entitled, 4),
            "threshold": self.threshold,
            "contained_now": sorted(self._contained),
        })

    def _release(self, tenant: str, now: float) -> None:
        info = self._contained.pop(tenant, None)
        self._cool_since.pop(tenant, None)
        self._pacers.pop(tenant, None)
        self.release_total += 1
        metrics_mod.tenant_contained.labels(tenant).set(0)
        # drop the label child on release: live children then equal the
        # contained set (<= max_contained) — without this, every tenant
        # EVER contained would keep a permanent series and containment
        # churn across a large corpus would mint labels without bound,
        # the exact leak the declared TENANT_LABEL_BOUNDS forbids
        try:
            metrics_mod.tenant_contained.remove(tenant)
        except Exception:
            pass
        from ..runtime.flight_recorder import RECORDER

        RECORDER.record("tenant-released", lane=self.lane, detail={
            "tenant": tenant,
            "contained_s": round(now - info["since"], 3) if info else None,
        })

    def reset(self, now: Optional[float] = None) -> None:
        """Release every contained tenant and clear the hot/cool timers —
        bench/test seam for starting a measured window from a known
        state (records `tenant-released` per tenant like a normal
        decay)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            for t in list(self._contained):
                self._release(t, now)
            self._hot_since.clear()
            self._cool_since.clear()

    # -- enforcement hooks ---------------------------------------------------

    def is_contained(self, tenant: str) -> bool:
        return tenant in self._contained

    def has_contained(self) -> bool:
        return bool(self._contained)

    def pace_reject(self, tenant: str,
                    now: Optional[float] = None) -> bool:
        """True when a contained tenant's arrival should be REJECTED typed
        (past the paced allowance); False = admit (the cut will divert it
        to the host-oracle lane)."""
        pacer = self._pacers.get(tenant)
        if pacer is None:
            return False
        return not pacer.allow(now)

    def contained(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {t: dict(v) for t, v in self._contained.items()}

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "threshold": self.threshold,
                "sustain_s": self.sustain_s,
                "release_s": self.release_s,
                "max_contained": self.max_contained,
                "allowance_rps": self.allowance_rps,
                "contained": {t: dict(v)
                              for t, v in self._contained.items()},
                "contain_total": self.contain_total,
                "release_total": self.release_total,
            }
