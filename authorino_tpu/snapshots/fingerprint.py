"""Per-config fingerprints and the encoding epoch — the two keys the
incremental control plane hangs everything on.

A verdict is a pure function of three things:

  1. how the request was encoded into operand bytes    → the *epoch*
  2. which config's rules judge those bytes            → the *fingerprint*
  3. the operand bytes themselves                      → the row key
                                                         (compiler/pack.py)

``rules_fingerprint`` canonically digests one config's SOURCE expression
trees (selector / operator / constant strings — no interner ids, no buffer
slots), so it is stable across recompiles, compile order, and process
restarts.  It keys the compile cache (same source ⇒ same artifact) and,
jointly with the epoch, the per-config verdict cache: two snapshots that
agree on (epoch, fingerprint) decide identical verdicts for identical
operand bytes, so entries for untouched configs SURVIVE a snapshot swap —
the single biggest cache-efficiency cliff under churn (ROADMAP item 1).

``encoding_epoch`` digests everything that defines the *meaning* of an
encoded operand row: the positional attr→selector table, the compact
membership slots, the dense CPU-lane column identities, the DFA byte
slots, members_k, and the interner's identity serial (ids from different
interner objects are incomparable).  Any layout change yields a new epoch
and old entries become unreachable — structural invalidation, exactly like
PR 3's generation keying, but scoped to what actually changed."""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

from ..analysis.translation_validate import _sha, _tree_digest
from ..compiler.compile import DFA_VALUE_BYTES, CompiledPolicy

__all__ = ["rules_fingerprint", "encoding_epoch", "cache_tokens"]


def rules_fingerprint(cfg, memo: Optional[Dict[int, str]] = None) -> str:
    """Canonical semantic fingerprint of one ConfigRules' SOURCE trees.

    Deliberately name-free: two configs with identical rules share one
    fingerprint (and thus one compile-cache artifact — structural sharing
    across AuthConfigs).  Related to PR 6's ``config_fingerprint``, which
    digests the (source, compiled) pair for certificate keying; here only
    the source exists yet — compilation is deterministic given the source,
    so the source digest determines the artifact."""
    memo = memo if memo is not None else {}
    cols: List[Tuple[Optional[str], str]] = []
    for cond, rule in cfg.evaluators:
        cols.append((
            _tree_digest(cond, memo) if cond is not None else None,
            _tree_digest(rule, memo),
        ))
    return _sha(repr(("rules", tuple(cols))))


def encoding_epoch(policy: CompiledPolicy) -> str:
    """Digest of the operand-encoding layout of one compiled corpus (see
    module docstring).  Cached on the policy object — the layout is frozen
    at compile time."""
    cached = getattr(policy, "_enc_epoch", None)
    if cached is not None:
        return cached
    tree_memo: Dict[int, str] = {}
    # dense CPU-lane columns: the [B, C] booleans are positional — column j
    # IS the leaf cpu_leaf_list[j], identified canonically (op, selector,
    # pattern / whole-tree digest), never by leaf index
    cpu_desc = []
    rev = None
    for leaf in policy.cpu_leaf_list.tolist():
        rx = policy.leaf_regex[leaf]
        tree = policy.leaf_tree[leaf]
        # ovf_assist membership columns are identified by their CONSTANT
        # too (two incl leaves on one attr are distinct columns)
        const_s = None
        if bool(policy.leaf_is_membership[leaf]):
            if rev is None:
                rev = policy.interner.reverse()
            const_s = rev.get(int(policy.leaf_const[leaf]),
                              f"<id:{int(policy.leaf_const[leaf])}>")
        cpu_desc.append((
            int(policy.leaf_op[leaf]),
            policy.attr_selectors[int(policy.leaf_attr[leaf])],
            rx.pattern if rx is not None else None,
            _tree_digest(tree, tree_memo) if tree is not None else None,
            const_s,
        ))
    # byte-tensor slots: slot → selector (positional [B, NB, LB] axes)
    byte_slots: Dict[int, str] = {}
    for a_i, slot in enumerate(policy.attr_byte_slot.tolist()):
        if slot >= 0:
            byte_slots[int(slot)] = policy.attr_selectors[a_i]
    # ISSUE 14 operand lanes: numeric value slots are positional (slot →
    # selector); relation rows' MEANING is the (relation digest, entity →
    # row) assignment per slot; assist columns fold in via cpu_desc (the
    # membership leaves that join cpu_leaf_list change it) plus the
    # explicit flag (the [B, M] mask's presence itself)
    num_slots: Dict[int, str] = {}
    nas = getattr(policy, "num_attr_slot", None)
    if nas is not None:
        for a_i, slot in enumerate(nas.tolist()):
            if slot >= 0:
                num_slots[int(slot)] = policy.attr_selectors[a_i]
    rel_desc = []
    for slot, (attr, inst) in enumerate(getattr(policy, "rel_slots", None)
                                        or ()):
        closure = policy.rel_instances[inst]
        rel_desc.append((
            policy.attr_selectors[int(attr)], closure.digest,
            tuple(sorted((e, policy.rel_entity_rows[inst][e])
                         for e in policy.rel_entity_rows[inst])),
        ))
    payload = (
        int(policy.interner.serial),
        int(policy.members_k),
        tuple(policy.attr_selectors),
        (tuple(policy.attr_selectors[a] for a in policy.member_attrs.tolist()),
         int(policy.n_member_attrs)),
        (tuple(cpu_desc), int(policy.n_cpu_leaves)),
        (tuple(byte_slots.get(s) for s in range(policy.n_byte_attrs)),
         DFA_VALUE_BYTES),
        (tuple(num_slots.get(s)
               for s in range(int(getattr(policy, "n_num_attrs", 0) or 0))),
         tuple(rel_desc), bool(getattr(policy, "ovf_assist", False))),
    )
    epoch = hashlib.sha256(repr(payload).encode("utf-8")).hexdigest()[:16]
    policy._enc_epoch = epoch  # type: ignore[attr-defined]
    return epoch


def cache_tokens(policy: CompiledPolicy,
                 fingerprints: Dict[str, str]) -> List[Tuple[str, str]]:
    """Per-eval-row verdict-cache key tokens: (epoch, fingerprint) per
    config row.  Padded rows (mesh targets) get a sentinel token — no
    request can ever map to them (row ids only cover real configs)."""
    epoch = encoding_epoch(policy)
    Gp = int(policy.eval_rule.shape[0])
    toks: List[Tuple[str, str]] = [(epoch, "<pad>")] * Gp
    for name, row in policy.config_ids.items():
        toks[row] = (epoch, fingerprints.get(name, "<no-fp>:" + name))
    return toks
