"""Pickle-free snapshot container: a leader-serialized compiled corpus a
serving replica can load without recompiling anything.

Wire layout (all little-endian):

    MAGIC  "ATPUSNAP1\\0"
    u64    header length H
    H      JSON header — format version, meta (generation, per-config
           fingerprints, certified flag, translation-validation stats),
           the string-interner table, every JSON-safe policy field, and
           an array directory {name: {dtype, shape, offset, nbytes}}
    ...    raw C-contiguous array payload (offsets relative to its start)
    32     sha256 over EVERYTHING above — the load-time integrity gate

No pickle anywhere: the JSON header carries expression trees as plain
``{"p": [selector, op, value]}`` / ``{"all": [...]}`` / ``{"any": [...]}``
nodes and the loader reconstructs real Pattern/And/Or objects (re-running
their constructor validation), so a snapshot file can never smuggle code.
Integrity ≠ authorization: the sha256 detects corruption and truncation;
the ``certified`` flag (set only after the leader's strict-verify lint +
translation certification passed) is what the replica's admission gate
requires — see snapshots/distribution.py and docs/control_plane.md."""

from __future__ import annotations

import hashlib
import json
import re
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..compiler.compile import CompiledPolicy
from ..compiler.intern import StringInterner
from ..expressions.ast import And, Expression, InGroup, Operator, Or, Pattern
from ..relations.closure import RelationClosure

__all__ = ["serialize_policy", "deserialize_policy", "SnapshotFormatError",
           "expr_to_json", "expr_from_json"]

MAGIC = b"ATPUSNAP1\x00"
# version 1: the pre-ISSUE-14 layout.  Version 2 adds the numeric and
# relation operand lanes + the ovf_assist flag; it is emitted ONLY when a
# corpus actually uses them, so old blobs stay loadable and an old reader
# REJECTS (typed) a blob whose lanes it cannot evaluate instead of
# silently dropping them.
FORMAT_VERSION = 1
FORMAT_VERSION_RELATIONS = 2
_DIGEST_LEN = 32


class SnapshotFormatError(ValueError):
    """The blob is not a valid snapshot container (bad magic, truncated,
    checksum mismatch, or unsupported version).  Load-time only — the
    serving snapshot is never touched."""


# ---------------------------------------------------------------------------
# expression trees <-> JSON
# ---------------------------------------------------------------------------


def expr_to_json(expr: Expression,
                 relations: Optional[Dict[str, int]] = None,
                 rel_edges: Optional[List[Any]] = None) -> Any:
    if isinstance(expr, Pattern):
        return {"p": [expr.selector, expr.operator.value, expr.value]}
    if isinstance(expr, InGroup):
        # closures dedupe into a header-level edge-set table by digest;
        # the node carries only its index (ISSUE 14) — standalone callers
        # (no registry) inline the edges
        if relations is None or rel_edges is None:
            return {"rel": [expr.selector, expr.group,
                            [list(e) for e in expr.relation.edges]]}
        idx = relations.get(expr.relation.digest)
        if idx is None:
            idx = relations[expr.relation.digest] = len(rel_edges)
            rel_edges.append([list(e) for e in expr.relation.edges])
        return {"rel": [expr.selector, expr.group, idx]}
    tag = "all" if isinstance(expr, And) else "any"
    return {tag: [expr_to_json(c, relations, rel_edges)
                  for c in expr.children]}


def expr_from_json(d: Any,
                   closures: Optional[List[RelationClosure]] = None,
                   ) -> Expression:
    if not isinstance(d, dict) or len(d) != 1:
        raise SnapshotFormatError(f"malformed expression node: {d!r}")
    if "p" in d:
        sel, op, value = d["p"]
        return Pattern(str(sel), Operator.from_string(str(op)), str(value))
    if "rel" in d:
        sel, group, ref = d["rel"]
        if isinstance(ref, list):
            closure = RelationClosure(ref)  # inline edges (standalone form)
        else:
            if closures is None or not (0 <= int(ref) < len(closures)):
                raise SnapshotFormatError(
                    f"relation node references closure {ref!r} outside the "
                    "header registry")
            closure = closures[int(ref)]
        return InGroup(str(sel), str(group), closure)
    if "all" in d:
        return And(tuple(expr_from_json(c, closures) for c in d["all"]))
    if "any" in d:
        return Or(tuple(expr_from_json(c, closures) for c in d["any"]))
    raise SnapshotFormatError(f"unknown expression node: {list(d)!r}")


# ---------------------------------------------------------------------------
# serialize
# ---------------------------------------------------------------------------

_ARRAY_FIELDS = (
    "leaf_op", "leaf_attr", "leaf_const", "eval_cond", "eval_rule",
    "eval_has_cond", "dfa_tables", "dfa_accept", "dfa_table_of_row",
    "dfa_leaf_attr", "leaf_dfa_row", "attr_byte_slot", "leaf_is_membership",
    "member_attr_slot", "member_attrs", "cpu_leaf_list", "config_cacheable",
)


def serialize_policy(policy: CompiledPolicy,
                     meta: Optional[Dict[str, Any]] = None) -> bytes:
    """One compiled corpus → one self-verifying blob.  ``meta`` lands in
    the header verbatim (generation, fingerprints, certified, entries)."""
    arrays: Dict[str, np.ndarray] = {
        name: getattr(policy, name) for name in _ARRAY_FIELDS}
    for i, (children, is_and) in enumerate(policy.levels):
        arrays[f"levels.{i}.children"] = children
        arrays[f"levels.{i}.is_and"] = is_and

    # ISSUE 14 lanes: arrays + host metadata ride the container only when
    # a corpus uses them (then the format version bumps, so an older
    # reader rejects typed instead of silently dropping a lane)
    has_num = int(getattr(policy, "n_num_attrs", 0) or 0) > 0
    has_rel = int(getattr(policy, "n_rel_slots", 0) or 0) > 0
    has_assist = bool(getattr(policy, "ovf_assist", False))
    if has_num:
        arrays["num_attr_slot"] = policy.num_attr_slot
        arrays["num_attrs"] = policy.num_attrs
    if has_rel:
        arrays["rel_bits"] = policy.rel_bits
        arrays["leaf_rel_slot"] = policy.leaf_rel_slot
        arrays["leaf_rel_col"] = policy.leaf_rel_col
        arrays["rel_slot_attr"] = policy.rel_slot_attr

    directory: Dict[str, Dict[str, Any]] = {}
    payload = bytearray()
    for name, a in arrays.items():
        c = np.ascontiguousarray(a)
        directory[name] = {
            "dtype": c.dtype.str, "shape": list(c.shape),
            "offset": len(payload), "nbytes": int(c.nbytes),
        }
        payload += c.tobytes()

    # interner table: index IS the id (insertion-ordered dict, sequential
    # ids by construction — compiler/intern.py)
    interner_table = [None] * len(policy.interner)
    for s, i in policy.interner._table.items():
        interner_table[i] = s

    rel_registry: Dict[str, int] = {}
    rel_edges: List[Any] = []
    header = {
        "version": (FORMAT_VERSION_RELATIONS
                    if has_num or has_rel or has_assist else FORMAT_VERSION),
        "meta": meta or {},
        "n_levels": len(policy.levels),
        "n_byte_attrs": int(policy.n_byte_attrs),
        "members_k": int(policy.members_k),
        "n_member_attrs": int(policy.n_member_attrs),
        "n_cpu_leaves": int(policy.n_cpu_leaves),
        "interner": interner_table,
        "attr_selectors": list(policy.attr_selectors),
        "config_ids": dict(policy.config_ids),
        "config_attrs": [list(map(int, a)) for a in policy.config_attrs],
        "config_cpu_leaves": [list(map(int, a))
                              for a in policy.config_cpu_leaves],
        "leaf_regex": [rx.pattern if rx is not None else None
                       for rx in policy.leaf_regex],
        "leaf_tree": [expr_to_json(t, rel_registry, rel_edges)
                      if t is not None else None
                      for t in policy.leaf_tree],
        "config_exprs": [
            [[expr_to_json(cond, rel_registry, rel_edges)
              if cond is not None else None,
              expr_to_json(rule, rel_registry, rel_edges)]
             for cond, rule in evs]
            for evs in policy.config_exprs
        ],
        "arrays": directory,
    }
    if has_num or has_rel or has_assist:
        header["n_num_attrs"] = int(policy.n_num_attrs)
        header["n_rel_slots"] = int(policy.n_rel_slots)
        header["ovf_assist"] = bool(policy.ovf_assist)
        header["rel_slots"] = [list(map(int, s))
                               for s in (policy.rel_slots or ())]
        header["rel_col_names"] = [[int(i), str(g)]
                                   for i, g in (policy.rel_col_names or ())]
        header["rel_entity_rows"] = [
            {str(e): int(r) for e, r in m.items()}
            for m in (policy.rel_entity_rows or ())]
        header["rel_instances"] = [
            [list(e) for e in c.edges]
            for c in (policy.rel_instances or ())]
    if rel_edges:
        header["relations"] = rel_edges
    header_bytes = json.dumps(header, sort_keys=True,
                              separators=(",", ":")).encode("utf-8")
    body = MAGIC + struct.pack("<Q", len(header_bytes)) + header_bytes + payload
    return body + hashlib.sha256(body).digest()


# ---------------------------------------------------------------------------
# deserialize
# ---------------------------------------------------------------------------


def _read_header(blob: bytes) -> Tuple[Dict[str, Any], int]:
    if len(blob) < len(MAGIC) + 8 + _DIGEST_LEN:
        raise SnapshotFormatError("snapshot blob truncated")
    if blob[:len(MAGIC)] != MAGIC:
        raise SnapshotFormatError("bad snapshot magic")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if hashlib.sha256(body).digest() != digest:
        raise SnapshotFormatError("snapshot checksum mismatch (corrupt or "
                                  "tampered blob)")
    (hlen,) = struct.unpack_from("<Q", blob, len(MAGIC))
    start = len(MAGIC) + 8
    if start + hlen > len(body):
        raise SnapshotFormatError("snapshot header overruns the blob")
    try:
        header = json.loads(body[start:start + hlen].decode("utf-8"))
    except Exception as e:
        raise SnapshotFormatError(f"unparseable snapshot header: {e}")
    if header.get("version") not in (FORMAT_VERSION,
                                     FORMAT_VERSION_RELATIONS):
        raise SnapshotFormatError(
            f"unsupported snapshot format version {header.get('version')!r}")
    return header, start + hlen


def deserialize_policy(blob: bytes) -> Tuple[CompiledPolicy, Dict[str, Any]]:
    """Blob → (CompiledPolicy, header meta).  Pure deserialization: nothing
    is recompiled, no device is touched — the replica's whole point."""
    header, payload_off = _read_header(blob)
    payload = blob[payload_off:-_DIGEST_LEN]

    def arr(name: str) -> np.ndarray:
        spec = header["arrays"].get(name)
        if spec is None:
            raise SnapshotFormatError(f"snapshot missing array {name!r}")
        try:
            end = spec["offset"] + spec["nbytes"]
            if end > len(payload):
                raise SnapshotFormatError(
                    f"array {name!r} overruns the payload")
            a = np.frombuffer(payload[spec["offset"]:end],
                              dtype=np.dtype(spec["dtype"]))
            a = a.reshape(spec["shape"])
        except SnapshotFormatError:
            raise
        except Exception as e:
            # bad dtype strings, nbytes not a multiple of the itemsize,
            # shape mismatches — a checksum only proves the WRITER's bytes,
            # not that a (version-skewed or adversarial) writer wrote a
            # well-formed directory
            raise SnapshotFormatError(f"array {name!r} malformed: {e}")
        return np.array(a)  # explicit writable copy (frombuffer is RO)

    levels = tuple(
        (arr(f"levels.{i}.children"), arr(f"levels.{i}.is_and"))
        for i in range(int(header["n_levels"])))

    interner = StringInterner()
    table: Dict[str, int] = {}
    for i, s in enumerate(header["interner"]):
        table[str(s)] = i
    if table.get("") != 0:
        raise SnapshotFormatError("interner table must map \"\" to id 0")
    interner._table = table

    leaf_regex: List[Optional[re.Pattern]] = [
        re.compile(p) if p is not None else None
        for p in header["leaf_regex"]]
    # relation closures rebuild from the deduped edge-set registry (node
    # {"rel": [sel, group, idx]} references); digests recompute identically
    try:
        node_closures = [RelationClosure(e)
                         for e in header.get("relations") or ()]
        rel_instances = [RelationClosure(e)
                         for e in header.get("rel_instances") or ()]
    except Exception as e:
        raise SnapshotFormatError(f"malformed relation edge set: {e}")
    leaf_tree: List[Optional[Expression]] = [
        expr_from_json(t, node_closures) if t is not None else None
        for t in header["leaf_tree"]]
    config_exprs = [
        [(expr_from_json(c, node_closures) if c is not None else None,
          expr_from_json(r, node_closures))
         for c, r in evs]
        for evs in header["config_exprs"]]

    has_new = int(header.get("version", 1)) >= FORMAT_VERSION_RELATIONS
    n_num = int(header.get("n_num_attrs", 0) or 0) if has_new else 0
    n_rel = int(header.get("n_rel_slots", 0) or 0) if has_new else 0

    def arr_opt(name: str):
        return arr(name) if name in header["arrays"] else None

    policy = CompiledPolicy(
        leaf_op=arr("leaf_op"),
        leaf_attr=arr("leaf_attr"),
        leaf_const=arr("leaf_const"),
        levels=levels,
        eval_cond=arr("eval_cond"),
        eval_rule=arr("eval_rule"),
        eval_has_cond=arr("eval_has_cond"),
        dfa_tables=arr("dfa_tables"),
        dfa_accept=arr("dfa_accept"),
        dfa_table_of_row=arr("dfa_table_of_row"),
        dfa_leaf_attr=arr("dfa_leaf_attr"),
        leaf_dfa_row=arr("leaf_dfa_row"),
        attr_byte_slot=arr("attr_byte_slot"),
        n_byte_attrs=int(header["n_byte_attrs"]),
        interner=interner,
        attr_selectors=[str(s) for s in header["attr_selectors"]],
        config_ids={str(k): int(v)
                    for k, v in header["config_ids"].items()},
        config_attrs=[list(map(int, a)) for a in header["config_attrs"]],
        config_cpu_leaves=[list(map(int, a))
                           for a in header["config_cpu_leaves"]],
        leaf_regex=leaf_regex,
        leaf_tree=leaf_tree,
        leaf_is_membership=arr("leaf_is_membership"),
        members_k=int(header["members_k"]),
        member_attr_slot=arr("member_attr_slot"),
        member_attrs=arr("member_attrs"),
        n_member_attrs=int(header["n_member_attrs"]),
        cpu_leaf_list=arr("cpu_leaf_list"),
        n_cpu_leaves=int(header["n_cpu_leaves"]),
        config_exprs=config_exprs,
        config_cacheable=arr("config_cacheable"),
        num_attr_slot=arr_opt("num_attr_slot"),
        num_attrs=arr_opt("num_attrs"),
        n_num_attrs=n_num,
        rel_bits=arr_opt("rel_bits"),
        leaf_rel_slot=arr_opt("leaf_rel_slot"),
        leaf_rel_col=arr_opt("leaf_rel_col"),
        rel_slot_attr=arr_opt("rel_slot_attr"),
        n_rel_slots=n_rel,
        rel_instances=rel_instances,
        rel_entity_rows=[{str(e): int(r) for e, r in m.items()}
                         for m in (header.get("rel_entity_rows") or ())],
        rel_slots=[tuple(map(int, s))
                   for s in (header.get("rel_slots") or ())],
        rel_col_names=[(int(i), str(g))
                       for i, g in (header.get("rel_col_names") or ())],
        ovf_assist=bool(header.get("ovf_assist", False)),
    )
    return policy, dict(header.get("meta") or {})
