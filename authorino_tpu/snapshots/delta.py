"""Apply a delta plan as device uploads: only changed rows cross the link.

The previous snapshot's device params stay untouched (functional ``.at[]``
updates produce NEW device buffers), so double buffering and in-flight
batches keep working exactly as before — this module only changes how many
bytes the H2D staging of a reconcile ships."""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from .diff import DeltaPlan, plan_delta

__all__ = ["apply_delta", "full_upload", "view_bytes"]


def view_bytes(view: Dict[str, Any]) -> int:
    """Total operand bytes of one host view (the full-upload cost)."""
    total = 0

    def walk(v):
        nonlocal total
        if v is None:
            return
        if isinstance(v, dict):
            for x in v.values():
                walk(x)
        elif isinstance(v, (tuple, list)):
            for x in v:
                walk(x)
        else:
            total += int(np.asarray(v).nbytes)

    walk(view)
    return total


def full_upload(view: Dict[str, Any]) -> Tuple[Any, int]:
    """Stage every operand (the non-incremental path): device params pytree
    + bytes shipped."""
    import jax

    params = jax.tree.map(jax.device_put, view)
    return params, view_bytes(view)


def apply_delta(prev_params: Dict[str, Any], new_view: Dict[str, Any],
                plan: Optional[DeltaPlan]) -> Tuple[Any, int]:
    """Build the new device params from the previous snapshot's device
    buffers and the delta plan.  ``plan`` None (or any surprise) falls back
    to a full upload — the delta path is an optimization, never a
    correctness dependency."""
    if plan is None:
        return full_upload(new_view)
    import jax
    import jax.numpy as jnp

    by_name = {e.name: e for e in plan.entries}
    uploaded = 0

    def leaf(name: str, new_h, prev_d):
        nonlocal uploaded
        e = by_name.get(name)
        if e is None or prev_d is None or e.mode == "full":
            uploaded += int(np.asarray(new_h).nbytes)
            return jax.device_put(new_h)
        if e.mode == "reuse":
            return prev_d
        # rows: functional scatter of just the changed leading-axis rows —
        # H2D traffic is the rows plus their indices, nothing else.  The
        # previous device buffer is untouched (.at returns a new array):
        # in-flight batches of the old snapshot keep their params.
        idx = e.rows
        uploaded += int(e.upload_bytes)
        return prev_d.at[jnp.asarray(idx)].set(jnp.asarray(new_h[idx]))

    def rebuild(prefix: str, new_v, prev_v):
        if new_v is None:
            return None
        if isinstance(new_v, dict):
            pd = prev_v if isinstance(prev_v, dict) else {}
            return {k: rebuild(f"{prefix}.{k}" if prefix else str(k),
                               new_v[k], pd.get(k)) for k in new_v}
        if isinstance(new_v, (tuple, list)):
            pt = prev_v if isinstance(prev_v, (tuple, list)) else ()
            return tuple(
                rebuild(f"{prefix}.{i}", x,
                        pt[i] if i < len(pt) else None)
                for i, x in enumerate(new_v))
        return leaf(prefix, new_v, prev_v)

    return rebuild("", new_view, prev_params), uploaded
