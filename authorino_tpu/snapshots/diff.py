"""Snapshot diff plans — which configs changed, which operand rows they
touch, and what a delta upload ships vs a full re-stage.

Pure numpy (import-light): the same engine drives the reconcile-time delta
H2D upload (snapshots/delta.py), the analysis CLI's ``--snapshot-diff``,
and the churn bench.  A plan is computed between two HOST operand views
(ops/pattern_eval.to_device(host=True) pytrees); per operand it picks:

  reuse — byte-identical array: the previous device buffer serves as-is,
          zero bytes cross the link
  rows  — same shape/dtype, a minority of leading-axis rows differ: ship
          only those rows + their indices (a device-side scatter)
  full  — shape/dtype changed, or so many rows differ that a full
          re-stage is cheaper than the scatter

Exactness is trivial by construction: the plan only decides HOW the new
host arrays reach the device, never what they contain."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ArrayDelta", "DeltaPlan", "flatten_view", "plan_delta",
           "snapshot_diff", "format_snapshot_diff"]

# a rows-delta must beat a full upload by at least 2x to be worth the
# scatter's index traffic and launch overhead
_ROWS_WIN_FACTOR = 2


@dataclass
class ArrayDelta:
    name: str
    mode: str                          # "reuse" | "rows" | "full"
    rows: Optional[np.ndarray] = None  # changed leading-axis indices (rows)
    upload_bytes: int = 0
    full_bytes: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name, "mode": self.mode,
            "rows": int(self.rows.shape[0]) if self.rows is not None else 0,
            "upload_bytes": int(self.upload_bytes),
            "full_bytes": int(self.full_bytes),
        }


@dataclass
class DeltaPlan:
    entries: List[ArrayDelta] = field(default_factory=list)
    upload_bytes: int = 0
    full_bytes: int = 0

    @property
    def mode(self) -> str:
        if not self.entries:
            return "full"
        if all(e.mode == "reuse" for e in self.entries):
            return "reuse"
        return "delta"

    def to_json(self) -> Dict[str, Any]:
        touched = [e.to_json() for e in self.entries if e.mode != "reuse"]
        return {
            "mode": self.mode,
            "upload_bytes": int(self.upload_bytes),
            "full_bytes": int(self.full_bytes),
            "arrays_reused": sum(1 for e in self.entries if e.mode == "reuse"),
            "arrays_touched": touched,
        }


def flatten_view(view: Dict[str, Any]) -> Dict[str, Optional[np.ndarray]]:
    """Flatten a host operand pytree (to_device(host=True)) to named numpy
    leaves — generic over nested dicts/tuples, so BOTH kernel lanes diff
    (the gather lane's index tables and the matmul lane's one-hot spread /
    count matrices are all row-structured: a one-config change touches a
    handful of leading-axis rows)."""
    out: Dict[str, Optional[np.ndarray]] = {}

    def walk(prefix: str, v: Any) -> None:
        if v is None:
            out[prefix] = None
        elif isinstance(v, dict):
            for k in v:
                walk(f"{prefix}.{k}" if prefix else str(k), v[k])
        elif isinstance(v, (tuple, list)):
            for i, x in enumerate(v):
                walk(f"{prefix}.{i}", x)
        else:
            out[prefix] = np.asarray(v)

    walk("", view)
    return out


def _delta_one(name: str, old: np.ndarray, new: np.ndarray,
               rows_win_factor: float = _ROWS_WIN_FACTOR) -> ArrayDelta:
    full = int(new.nbytes)
    if old.shape != new.shape or old.dtype != new.dtype:
        return ArrayDelta(name, "full", upload_bytes=full, full_bytes=full)
    if old is new or np.array_equal(old, new):
        return ArrayDelta(name, "reuse", full_bytes=full)
    if new.ndim >= 1 and new.shape[0] > 1:
        diff = old != new
        if diff.ndim > 1:
            diff = diff.reshape(diff.shape[0], -1).any(axis=1)
        idx = np.nonzero(diff)[0].astype(np.int32)
        row_bytes = int(new[idx].nbytes + idx.nbytes)
        if row_bytes * rows_win_factor <= full:
            return ArrayDelta(name, "rows", rows=idx,
                              upload_bytes=row_bytes, full_bytes=full)
    return ArrayDelta(name, "full", upload_bytes=full, full_bytes=full)


def plan_delta(old_view: Optional[Dict[str, Any]],
               new_view: Dict[str, Any],
               rows_win_factor: float = _ROWS_WIN_FACTOR
               ) -> Optional[DeltaPlan]:
    """Diff two host operand views into a delta plan, or None when no
    structure-preserving delta exists (lane change, level-count change, a
    DFA lane appearing/vanishing, or no previous view at all) — the caller
    falls back to a full upload.

    ``rows_win_factor`` sets how decisively a rows-delta must beat the
    full upload.  The default (2x) is tuned for config-axis leading dims
    (hundreds of rows, scatter overhead matters).  The mesh lane passes
    1.0: there the leading axis is the SHARD axis (two to a handful of
    rows), and shipping ANY strict subset of shards is the point — it
    confines H2D traffic to the owning shard even when the byte win over
    a full restage is modest."""
    if old_view is None:
        return None
    old_flat = flatten_view(old_view)
    new_flat = flatten_view(new_view)
    if set(old_flat) != set(new_flat):
        # level count changed, or a whole lane (matmul/DFA) appeared or
        # vanished: the buffer layout reshuffled, restage everything
        return None
    plan = DeltaPlan()
    for name in new_flat:
        o, n = old_flat[name], new_flat[name]
        if o is None and n is None:
            continue  # e.g. no DFA lane on either side
        if o is None or n is None:
            return None  # DFA lane appeared/vanished: full restage
        plan.entries.append(_delta_one(name, o, n, rows_win_factor))
    plan.upload_bytes = sum(e.upload_bytes for e in plan.entries)
    plan.full_bytes = sum(e.full_bytes for e in plan.entries)
    return plan


# ---------------------------------------------------------------------------
# Config-level diff (fingerprint maps) + the human-readable rendering the
# analysis CLI prints
# ---------------------------------------------------------------------------


def snapshot_diff(old_fps: Dict[str, str],
                  new_fps: Dict[str, str]) -> Dict[str, Any]:
    """Name-level diff of two fingerprint maps: which configs a reconcile
    must recompile (added + changed), which verdict-cache entries survive
    (unchanged), and which die (removed + changed)."""
    old_names, new_names = set(old_fps), set(new_fps)
    added = sorted(new_names - old_names)
    removed = sorted(old_names - new_names)
    changed = sorted(n for n in (old_names & new_names)
                     if old_fps[n] != new_fps[n])
    unchanged = sorted(n for n in (old_names & new_names)
                       if old_fps[n] == new_fps[n])
    return {
        "added": added, "removed": removed, "changed": changed,
        "unchanged": len(unchanged),
        "recompile": sorted(set(added) | set(changed)),
    }


def format_snapshot_diff(old_meta: Dict[str, Any], new_meta: Dict[str, Any],
                         old_view: Optional[Dict[str, Any]] = None,
                         new_view: Optional[Dict[str, Any]] = None) -> str:
    """Human-readable diff between two (de)serialized snapshots: the
    config-level recompile set, then the operand-level rows/bytes a delta
    upload would ship.  ``*_meta`` carry the per-config fingerprint maps
    (snapshots/serialize.py header meta)."""
    d = snapshot_diff(old_meta.get("fingerprints", {}),
                      new_meta.get("fingerprints", {}))
    lines = [
        f"snapshot diff: generation {old_meta.get('generation', '?')} -> "
        f"{new_meta.get('generation', '?')}",
        f"  configs: {d['unchanged']} unchanged, "
        f"{len(d['changed'])} changed, {len(d['added'])} added, "
        f"{len(d['removed'])} removed",
    ]
    for kind in ("changed", "added", "removed"):
        for name in d[kind][:16]:
            lines.append(f"    {kind}: {name}")
        extra = len(d[kind]) - 16
        if extra > 0:
            lines.append(f"    ... and {extra} more {kind}")
    lines.append(f"  recompile set: {len(d['recompile'])} config(s)")
    if new_view is not None:
        plan = plan_delta(old_view, new_view)
        if plan is None:
            lines.append("  upload: FULL re-stage (no structure-preserving "
                         "delta between these snapshots)")
        else:
            lines.append(
                f"  upload: {plan.mode} — {plan.upload_bytes:,} bytes vs "
                f"{plan.full_bytes:,} full "
                f"({sum(1 for e in plan.entries if e.mode == 'reuse')} "
                f"operand(s) reused as-is)")
            for e in plan.entries:
                if e.mode == "reuse":
                    continue
                rows = (f"{int(e.rows.shape[0])} row(s)"
                        if e.rows is not None else "all")
                lines.append(f"    {e.name}: {e.mode} ({rows}, "
                             f"{e.upload_bytes:,} bytes)")
    return "\n".join(lines)
