"""Incremental compile & delta snapshot distribution (ISSUE 8 — ROADMAP
open item 1: the control plane at 100k AuthConfigs).

Every reconcile used to recompile the entire corpus into one monolithic
snapshot, re-upload every device tensor, and bump a global generation that
invalidated the whole verdict cache.  This package makes the control plane
incremental end to end:

  fingerprint.py   — canonical per-config source fingerprints (the compile-
                     cache key) and the encoding *epoch* (everything that
                     defines the meaning of an encoded operand row), the
                     two halves of the per-config verdict-cache key
  compile_cache.py — bounded persistent compile cache: fingerprint →
                     per-config artifact; re-reconciling an unchanged
                     corpus compiles ZERO configs, mutating one compiles
                     exactly that one
  diff.py          — snapshot diff plans: which configs changed, which
                     operand rows they touch, and how many bytes a delta
                     upload ships vs a full re-stage (pure numpy —
                     import-light, reused by the analysis CLI)
  delta.py         — applies a diff plan as delta H2D transfers
                     (device-side row scatter; only changed rows cross
                     the link)
  serialize.py     — pickle-free snapshot container (JSON header + raw
                     array payload + sha256 trailer)
  distribution.py  — compile-leader publish / serving-replica load over a
                     directory or HTTP, with the strict-verify certificate
                     as the admission gate

See docs/control_plane.md for the full design."""

from .compile_cache import CompileCache, CompileReport, ConfigArtifact
from .diff import format_snapshot_diff, plan_delta, snapshot_diff
from .fingerprint import cache_tokens, encoding_epoch, rules_fingerprint
from .serialize import (
    SnapshotFormatError,
    deserialize_policy,
    serialize_policy,
)

__all__ = [
    "CompileCache", "CompileReport", "ConfigArtifact",
    "rules_fingerprint", "encoding_epoch", "cache_tokens",
    "snapshot_diff", "plan_delta", "format_snapshot_diff",
    "serialize_policy", "deserialize_policy", "SnapshotFormatError",
]
