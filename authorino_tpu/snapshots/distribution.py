"""Compile-leader / serving-replica snapshot distribution.

The leader compiles, strict-verifies (PR 4 tensor lint + PR 6 translation
certification — the admission gate), serializes the vetted snapshot, and
publishes it atomically into a directory (tmp + rename, then a MANIFEST
pointer).  Replicas poll the directory (or an HTTP mirror of it) and apply
each new vetted snapshot WITHOUT recompiling anything: load is pure
deserialization + the local admission gate.  Compile once, serve many.

Failure modes (docs/control_plane.md):

  leader down          → the MANIFEST stops advancing; replicas keep
                         serving the last vetted snapshot indefinitely
  corrupt blob         → sha256 trailer mismatch: SnapshotLoadError at
                         load, old snapshot keeps serving
  uncertified blob     → ``certified`` missing/false in the meta: rejected
                         at admission (SnapshotRejected), old snapshot
                         keeps serving — a snapshot that never passed the
                         leader's strict verify can never serve
  torn publish         → the atomic rename makes a half-written blob
                         unreachable; the MANIFEST only ever points at a
                         fully-renamed file"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..utils.atomicio import atomic_write_bytes, atomic_write_json
from .serialize import SnapshotFormatError, deserialize_policy, serialize_policy

__all__ = [
    "SnapshotLoadError", "LoadedSnapshot", "SnapshotPublisher",
    "load_latest", "load_snapshot_blob", "SnapshotReplica",
    "load_hotset",
]

log = logging.getLogger("authorino_tpu.snapshots")

MANIFEST = "MANIFEST.json"
# verdict-cache hot-set digest (ISSUE 18, fleet/warmjoin.py): published
# NEXT TO the manifest, never inside it — a replica that predates the
# fleet plane keeps loading MANIFEST.json untouched
HOTSET = "HOTSET.json"


class SnapshotLoadError(RuntimeError):
    """A published snapshot could not be loaded (missing, corrupt,
    unparseable).  The caller's serving snapshot stays untouched."""


@dataclass
class LoadedSnapshot:
    policy: Any                      # CompiledPolicy (host arrays only)
    meta: Dict[str, Any]
    generation: int = 0
    digest: str = ""                 # manifest sha256 (hex) when known

    @property
    def certified(self) -> bool:
        return bool(self.meta.get("certified"))

    @property
    def fingerprints(self) -> Dict[str, str]:
        return dict(self.meta.get("fingerprints") or {})

    @property
    def entries(self) -> List[Tuple[str, List[str]]]:
        """(config id, hosts) pairs the leader served this corpus under."""
        return [(str(e["id"]), [str(h) for h in e.get("hosts", [])])
                for e in (self.meta.get("entries") or [])]


def _sha256_hex(blob: bytes) -> str:
    import hashlib

    return hashlib.sha256(blob).hexdigest()


# ---------------------------------------------------------------------------
# leader side
# ---------------------------------------------------------------------------


class SnapshotPublisher:
    """Atomic directory publisher.  ``publish_from_engine`` serializes the
    engine's CURRENT snapshot (fingerprints, certification state, host
    routing included) — attach it as a swap listener on the leader and
    every vetted reconcile becomes a published artifact."""

    def __init__(self, directory: str, keep: int = 4,
                 include_loaded: bool = False):
        self.directory = directory
        self.keep = max(1, int(keep))
        # include_loaded=True turns the publisher into a STATE-PLANE writer
        # (ISSUE 20, --state-dir): snapshots this process itself loaded
        # from an upstream publisher (published_origin set) are persisted
        # too, so a SIGKILLed replica restarts warm from its own disk.
        # The default (False) keeps the fleet loop breaker: replicas never
        # republish into a distribution directory.
        self.include_loaded = bool(include_loaded)
        os.makedirs(directory, exist_ok=True)
        # async publish machinery (attach): serialize+fsync must never sit
        # on the swap-listener critical path — a revoking reconcile has to
        # reach the native fast lane at swap speed, not behind disk I/O
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._engine = None
        self._last_published_snap: Any = None

    def publish_blob(self, blob: bytes, generation: int,
                     extra: Optional[Dict[str, Any]] = None) -> str:
        """``extra`` merges additional manifest fields — the change-safety
        record (ISSUE 10): ``active_generation`` (the leader's serving
        decision, which replicas converge on) and, after a guard-breach,
        the ``rollback``/``quarantine`` provenance, so a fleet operator
        can see WHY the manifest moved backwards semantically."""
        name = f"snapshot-{generation:012d}.atpusnap"
        path = os.path.join(self.directory, name)
        atomic_write_bytes(path, blob, artifact="snapshot-blob")
        manifest = {
            "current": name,
            "generation": int(generation),
            "active_generation": int(generation),
            "sha256": _sha256_hex(blob),
            "size": len(blob),
            "published_unix": time.time(),
        }
        if extra:
            manifest.update(extra)
        atomic_write_json(os.path.join(self.directory, MANIFEST), manifest,
                          artifact="manifest")
        self._gc(keep_name=name)
        return path

    def _gc(self, keep_name: str) -> None:
        snaps = sorted(n for n in os.listdir(self.directory)
                       if n.endswith(".atpusnap"))
        for n in snaps[:-self.keep]:
            if n != keep_name:
                try:
                    os.unlink(os.path.join(self.directory, n))
                except OSError:
                    pass

    def publish_hotset(self, digest: Dict[str, Any]) -> str:
        """Atomically publish the verdict-cache hot-set digest (ISSUE 18,
        fleet/warmjoin.py export_hotset) next to the manifest.  Same
        tmp+rename discipline as the blob: a joining replica never reads a
        torn digest.  Advisory data — a stale or missing HOTSET.json only
        costs a cold cache, never correctness (entries are re-validated
        against the joining snapshot's tokens at import)."""
        path = os.path.join(self.directory, HOTSET)
        atomic_write_json(path, digest, artifact="hotset")
        return path

    def publish_from_engine(self, engine) -> Optional[str]:
        """Serialize + publish the engine's current snapshot.  Returns the
        published path, or None when there is nothing publishable (no
        compiled corpus, or a mesh-sharded snapshot — per-shard policies
        do not round-trip through one container)."""
        snap = engine._snapshot
        if snap is None or snap.policy is None:
            return None
        if getattr(snap, "published_origin", None) and not self.include_loaded:
            # this snapshot was itself loaded from a publisher: replicas
            # never republish (loop breaker — see engine.from_published).
            # A state-plane publisher (include_loaded=True) opts out: its
            # directory is this process's own crash-recovery store, never
            # another replica's source (cli.py refuses --state-dir ==
            # --snapshot-source), so persisting loaded snapshots is safe.
            return None
        change_safety = getattr(snap, "change_safety", None)
        meta = {
            "generation": int(snap.generation),
            "certified": bool(getattr(snap, "lint_ok", False)),
            "fingerprints": dict(getattr(snap, "fingerprints", {}) or {}),
            "translation": getattr(snap, "translation", None),
            "entries": [{"id": e.id, "hosts": list(e.hosts)}
                        for e in snap.by_id.values()],
        }
        if change_safety:
            meta["change_safety"] = change_safety
        blob = serialize_policy(snap.policy, meta=meta)
        path = self.publish_blob(blob, snap.generation,
                                 extra=(dict(change_safety)
                                        if change_safety else None))
        log.info("published snapshot generation %d (%d bytes, certified=%s"
                 "%s) -> %s", snap.generation, len(blob), meta["certified"],
                 f", change_safety={sorted(change_safety)}"
                 if change_safety else "", path)
        return path

    def attach(self, engine) -> None:
        """Register as a swap listener: every engine snapshot swap (already
        vetted when --strict-verify is on) publishes — ASYNCHRONOUSLY, on
        the publisher's own thread.  The listener itself only sets an
        event, so revocation propagation to the other listeners (the
        native frontend's refresh) never waits behind serialize + fsync;
        back-to-back swaps coalesce to the newest snapshot (the manifest
        points at the latest generation anyway).  A publish failure must
        never fail a reconcile."""
        self._engine = engine
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._publish_loop, name="atpu-snapshot-publisher",
                daemon=True)
            self._thread.start()
        engine.add_swap_listener(self._wake.set)

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until the engine's CURRENT snapshot has been published (or
        the timeout expires — False).  Tests and orderly shutdown only;
        the serving path never needs it."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            engine = self._engine
            if engine is None or engine._snapshot is None \
                    or self._last_published_snap is engine._snapshot:
                return True
            time.sleep(0.005)
        return False

    def _publish_loop(self) -> None:
        from ..utils import metrics as metrics_mod

        while True:
            self._wake.wait()
            self._wake.clear()
            engine = self._engine
            snap = engine._snapshot if engine is not None else None
            if snap is None or snap is self._last_published_snap:
                continue
            try:
                if self.publish_from_engine(engine) is not None:
                    metrics_mod.snapshot_distribution.labels(
                        "leader", "published").inc()
            except Exception:
                log.exception("snapshot publish failed (serving unaffected)")
            finally:
                self._last_published_snap = snap


# ---------------------------------------------------------------------------
# replica side
# ---------------------------------------------------------------------------


def _read_source(source: str, name: str) -> bytes:
    """Read one artifact from a directory path or an http(s) mirror."""
    if source.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(source.rstrip("/") + "/" + name, timeout=10) as r:
            return r.read()
    path = os.path.join(source, name)
    with open(path, "rb") as f:
        return f.read()


def load_snapshot_blob(blob: bytes, digest: str = "") -> LoadedSnapshot:
    try:
        policy, meta = deserialize_policy(blob)
    except SnapshotFormatError as e:
        raise SnapshotLoadError(str(e))
    except Exception as e:
        # containment: NO malformed blob may escape as anything but a load
        # error — the replica's serving snapshot must survive every bad
        # publish (docs/control_plane.md failure modes)
        raise SnapshotLoadError(f"malformed snapshot blob: {e!r}")
    return LoadedSnapshot(policy=policy, meta=meta,
                          generation=int(meta.get("generation", 0)),
                          digest=digest)


def load_latest(source: str) -> LoadedSnapshot:
    """Resolve the MANIFEST and load the snapshot it points at, verifying
    the manifest digest against the blob BEFORE parsing anything."""
    try:
        manifest = json.loads(_read_source(source, MANIFEST).decode("utf-8"))
        name = str(manifest["current"])
        if "/" in name or name.startswith("."):
            raise SnapshotLoadError(f"suspicious manifest entry {name!r}")
        blob = _read_source(source, name)
    except SnapshotLoadError:
        raise
    except Exception as e:
        raise SnapshotLoadError(f"snapshot source unreadable: {e}")
    want = str(manifest.get("sha256", ""))
    got = _sha256_hex(blob)
    if want and got != want:
        raise SnapshotLoadError(
            f"manifest digest mismatch ({want[:12]}... != {got[:12]}...)")
    return load_snapshot_blob(blob, digest=got)


def load_hotset(source: str) -> Optional[Dict[str, Any]]:
    """Load the published verdict-cache hot-set digest, or None when the
    source has none (a pre-fleet leader, or hot-set publishing off).
    Malformed digests also resolve to None — warm-join is advisory; a
    replica must join cold rather than fail to join."""
    try:
        doc = json.loads(_read_source(source, HOTSET).decode("utf-8"))
    except Exception:
        return None
    return doc if isinstance(doc, dict) else None


class SnapshotReplica:
    """Poll a snapshot source and apply each new vetted snapshot to a local
    engine.  The engine's ``apply_published`` is the admission gate: an
    uncertified or locally-failing snapshot is rejected and the previous
    one keeps serving — leader down simply means no new generations."""

    # load-failure backoff: ceiling multiple of poll_s (a dead leader
    # settles at poll_s * 2**MAX_BACKOFF_DOUBLINGS between attempts)
    MAX_BACKOFF_DOUBLINGS = 5

    def __init__(self, engine, source: str, poll_s: float = 5.0):
        self.engine = engine
        self.source = source
        self.poll_s = max(0.2, float(poll_s))
        self._seen_digest: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.applied = 0
        self.rejected = 0
        self.errors = 0
        # consecutive load failures — drives the exponential poll backoff
        # and demotes repeat WARNINGs to DEBUG (a dead leader must not
        # flood the replica's log at the poll cadence); any successful
        # load (or a rejection — the source IS reachable) resets it
        self.error_streak = 0
        self.last_error: Optional[str] = None

    def poll_once(self) -> bool:
        """One load-and-apply attempt.  True when a NEW snapshot was
        applied; False on no-change, load failure, or rejection."""
        from ..runtime.engine import SnapshotRejected
        from ..utils import metrics as metrics_mod

        try:
            loaded = load_latest(self.source)
        except SnapshotLoadError as e:
            self.errors += 1
            self.error_streak += 1
            self.last_error = str(e)
            if self.error_streak == 1:
                metrics_mod.snapshot_distribution.labels(
                    "replica", "error").inc()
                log.warning("replica load failed (serving snapshot "
                            "unchanged; backing polls off): %s", e)
            else:
                # retries of a standing failure: counted, logged quietly —
                # the WARNING above already said the leader is unreadable
                metrics_mod.snapshot_distribution.labels(
                    "replica", "retry").inc()
                log.debug("replica load retry %d failed (next poll in "
                          "%.1fs): %s", self.error_streak,
                          self.next_poll_s(), e)
            return False
        self.error_streak = 0
        if loaded.digest and loaded.digest == self._seen_digest:
            return False
        try:
            self.engine.apply_published(loaded)
        except SnapshotRejected as e:
            self.rejected += 1
            self.last_error = str(e)
            # remember the digest: re-polling the same rejected blob every
            # interval would re-run the admission gate for nothing
            self._seen_digest = loaded.digest or None
            metrics_mod.snapshot_distribution.labels(
                "replica", "rejected").inc()
            log.error("replica REJECTED snapshot generation %d at admission "
                      "(previous snapshot keeps serving): %s",
                      loaded.generation, e)
            return False
        self._seen_digest = loaded.digest or None
        self.applied += 1
        self.last_error = None
        metrics_mod.snapshot_distribution.labels("replica", "applied").inc()
        log.info("replica applied snapshot generation %d (%d config(s))",
                 loaded.generation, len(loaded.policy.config_ids))
        return True

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="atpu-snapshot-replica",
                                        daemon=True)
        self._thread.start()

    def next_poll_s(self) -> float:
        """Current poll interval: poll_s while healthy, doubling per
        consecutive load failure up to poll_s * 2**MAX_BACKOFF_DOUBLINGS.
        A success (or an admission rejection — the source answered)
        snaps it back to poll_s."""
        if self.error_streak <= 0:
            return self.poll_s
        doublings = min(self.error_streak, self.MAX_BACKOFF_DOUBLINGS)
        return self.poll_s * (1 << doublings)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                log.exception("replica poll failed")
            self._stop.wait(self.next_poll_s())

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)

    def to_json(self) -> Dict[str, Any]:
        return {
            "source": self.source, "poll_s": self.poll_s,
            "next_poll_s": self.next_poll_s(),
            "applied": self.applied, "rejected": self.rejected,
            "errors": self.errors, "error_streak": self.error_streak,
            "last_error": self.last_error,
        }
