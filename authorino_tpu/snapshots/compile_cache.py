"""Bounded persistent compile cache: per-config artifacts keyed by source
fingerprint.

This extends PR 3's compile-time dedup memos (circuit nodes, DFA tables,
regex determinization) from *within one compile* to *across reconciles*:

  - the per-config artifact pins the canonical expression trees and the
    set of regex patterns the config lowers, so a config seen before is
    never re-lowered, re-interned, or re-determinized — the cache counters
    are the proof obligation ISSUE 8 states ("re-reconciling an unchanged
    corpus compiles zero configs; changing one compiles exactly that one")
  - the persistent ``StringInterner`` keeps constant ids STABLE across
    reconciles, which is what makes both delta device uploads (unchanged
    rows byte-identical ⇒ nothing to ship) and verdict-cache survival
    (unchanged rows produce unchanged row keys) possible at all
  - the persistent ``dfa_cache`` is the cross-reconcile face of
    compiler/redfa.py's process-wide determinization memo: a regex pattern
    determinizes once per process, ever

The cache itself is bounded LRU over fingerprints.  Two configs with
identical rules (common in templated fleets) share ONE artifact —
structural sharing at the source level, mirroring the compiler's circuit
and DFA sharing at the tensor level."""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..compiler.compile import CompiledPolicy, ConfigRules, compile_corpus
from ..compiler.intern import StringInterner
from ..expressions.ast import Expression, Operator, Pattern
from .fingerprint import rules_fingerprint

__all__ = ["ConfigArtifact", "CompileCache", "CompileReport"]


@dataclass(frozen=True)
class ConfigArtifact:
    """One config's compiled artifact: the canonical evaluator trees (the
    unit compile_corpus consumes) plus the regex patterns it determinizes.
    Name-free — shared by every config with identical rules."""

    fingerprint: str
    evaluators: Tuple[Tuple[Optional[Expression], Expression], ...]
    patterns: Tuple[str, ...]          # valid-regex MATCHES patterns lowered
    n_patterns: int = 0


@dataclass
class CompileReport:
    """What one incremental compile actually did (the churn evidence that
    lands on /debug/vars, the reconcile metrics, and bench --churn)."""

    total: int = 0            # rules-bearing configs in the corpus
    compiled: int = 0         # artifacts built this reconcile (cache misses)
    cached: int = 0           # artifacts served from the cache
    fingerprints: "OrderedDict[str, str]" = field(default_factory=OrderedDict)
    compiled_names: List[str] = field(default_factory=list)
    unchanged: bool = False   # corpus fingerprint-identical to the previous
    reused_policy: bool = False  # previous CompiledPolicy object reused as-is

    def to_json(self) -> Dict[str, Any]:
        return {
            "total": self.total,
            "compiled": self.compiled,
            "cached": self.cached,
            "hit_ratio": round(self.cached / self.total, 4) if self.total else None,
            "compiled_names": self.compiled_names[:32],
            "unchanged": self.unchanged,
            "reused_policy": self.reused_policy,
        }


def _collect_patterns(expr: Expression, acc: set) -> None:
    if isinstance(expr, Pattern):
        if (expr.operator is Operator.MATCHES
                and getattr(expr, "_regex", None) is not None):
            acc.add(expr.value)
        return
    for c in getattr(expr, "children", ()):  # InGroup: leaf, no regexes
        _collect_patterns(c, acc)


class CompileCache:
    """Thread-safe; one per PolicyEngine (members_k and the DFA toggle are
    engine constants, so they need not ride the key)."""

    def __init__(self, max_entries: int = 65536):
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        # serializes whole-corpus compiles: compile_corpus and artifact
        # builds both mutate the SHARED interner/DFA memo, and
        # StringInterner.intern is an unlocked read-modify-write — two
        # concurrent compiles could hand one id to two different strings
        # (an exact-match comparator would then equate them on device).
        # Reconcile-path only; request-path interner access is read-only.
        self._compile_lock = threading.RLock()
        self._artifacts: "OrderedDict[str, ConfigArtifact]" = OrderedDict()
        # cross-reconcile faces of PR 3's compile-time memos
        self.dfa_cache: Dict[str, Any] = {}
        self.interner = StringInterner()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._artifacts)

    def stats(self) -> Dict[str, Any]:
        total = self.hits + self.misses
        return {
            "entries": len(self._artifacts),
            "hits": self.hits,
            "misses": self.misses,
            "hit_ratio": round(self.hits / total, 4) if total else None,
            "dfa_patterns": len(self.dfa_cache),
            "interned_strings": len(self.interner),
            "interner_serial": self.interner.serial,
        }

    # ------------------------------------------------------------------

    def artifact_for(self, cfg: ConfigRules) -> Tuple[ConfigArtifact, bool]:
        """Get-or-build the artifact for one config.  The build IS the
        per-config compile work: canonicalize the trees, intern every
        comparison constant (id stability across reconciles), and
        determinize every device-lane regex into the persistent memo."""
        fp = rules_fingerprint(cfg)
        with self._lock:
            hit = self._artifacts.get(fp)
            if hit is not None:
                self._artifacts.move_to_end(fp)
                self.hits += 1
                return hit, True
        # build under the (re-entrant) COMPILE lock — compile() already
        # holds it, direct callers take it here: _build mutates the shared
        # interner and DFA memo, which must never race another build or a
        # corpus compile
        with self._compile_lock:
            art = self._build(fp, cfg)
        with self._lock:
            self._artifacts[fp] = art
            self._artifacts.move_to_end(fp)
            self.misses += 1
            while len(self._artifacts) > self.max_entries:
                self._artifacts.popitem(last=False)
        return art, False

    def _build(self, fp: str, cfg: ConfigRules) -> ConfigArtifact:
        from ..compiler.compile import _has_invalid_regex
        from ..compiler.redfa import compile_regex_dfa

        patterns: set = set()
        for cond, rule in cfg.evaluators:
            for expr in (cond, rule):
                if expr is None:
                    continue
                if _has_invalid_regex(expr):
                    # the whole tree rides the CPU-fallback leaf; none of
                    # its regexes are lowered to the device lane
                    continue
                _collect_patterns(expr, patterns)
                self._intern_consts(expr)
        for pat in patterns:
            if pat not in self.dfa_cache:
                try:
                    self.dfa_cache[pat] = compile_regex_dfa(pat)
                except Exception:
                    self.dfa_cache[pat] = None  # CPU regex lane
        return ConfigArtifact(
            fingerprint=fp,
            evaluators=tuple((cond, rule) for cond, rule in cfg.evaluators),
            patterns=tuple(sorted(patterns)),
            n_patterns=len(patterns),
        )

    def _intern_consts(self, expr: Expression) -> None:
        if isinstance(expr, Pattern):
            from ..expressions.ast import NUMERIC_OPERATORS

            # numeric constants fold to raw int32 at compile time — they
            # never enter the interner (and must not churn its serial)
            if expr.operator is not Operator.MATCHES and \
                    expr.operator not in NUMERIC_OPERATORS:
                self.interner.intern(expr.value)
            return
        for c in getattr(expr, "children", ()):  # InGroup: no string consts
            self._intern_consts(c)

    # ------------------------------------------------------------------

    def compile(
        self,
        rules: List[ConfigRules],
        members_k: int = 16,
        prev_fps: Optional["OrderedDict[str, str]"] = None,
        prev_policy: Optional[CompiledPolicy] = None,
        enable_dfa: bool = True,
        ovf_assist: Optional[bool] = None,
    ) -> Tuple[CompiledPolicy, CompileReport]:
        """Incremental corpus compile.  Unchanged configs (fingerprint hit)
        reuse their artifact; a corpus whose ordered fingerprint map equals
        the previous snapshot's reuses the previous CompiledPolicy object
        outright — zero configs compiled, zero tensors rebuilt, and the
        caller can skip re-verification and the device upload entirely."""
        report = CompileReport(total=len(rules))
        with self._compile_lock:
            arts: List[Tuple[str, ConfigArtifact]] = []
            for cfg in rules:
                art, hit = self.artifact_for(cfg)
                arts.append((cfg.name, art))
                report.fingerprints[cfg.name] = art.fingerprint
                if hit:
                    report.cached += 1
                else:
                    report.compiled += 1
                    report.compiled_names.append(cfg.name)
            if (prev_fps is not None and prev_policy is not None
                    and list(prev_fps.items())
                    == list(report.fingerprints.items())):
                report.unchanged = True
                report.reused_policy = True
                return prev_policy, report
            cfgs = [ConfigRules(name=name, evaluators=list(art.evaluators))
                    for name, art in arts]
            policy = compile_corpus(
                cfgs, members_k=members_k, interner=self.interner,
                enable_dfa=enable_dfa, dfa_cache=self.dfa_cache,
                ovf_assist=ovf_assist)
        return policy, report
