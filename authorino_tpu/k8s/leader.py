"""Lease-based leader election.

The reference elects one replica to write AuthConfig statuses through
controller-runtime's leaderelection on a coordination.k8s.io/v1 Lease
(ref: main.go:308-314 enableLeaderElection, RBAC
controllers/auth_config_status_updater.go:31).  This is the same algorithm
implemented against our minimal REST client: acquire the Lease if unheld or
expired, renew every ``renew_interval``, step down when renewal fails.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Protocol

__all__ = ["Lease", "LeaseClient", "InMemoryLeases", "LeaderElector",
           "leader_election_id"]

log = logging.getLogger("authorino_tpu.leader")


def leader_election_id(auth_config_label_selector: str = "") -> str:
    """Lease name derived from the watched AuthConfig label selector, so two
    label-sharded instances in one namespace elect independent leaders and
    both shards' statuses get written (ref: main.go LeaderElectionID =
    sha256(watchedAuthConfigLabelSelector)[:8 hex] + suffix)."""
    digest = hashlib.sha256(auth_config_label_selector.encode("utf-8")).hexdigest()
    return f"{digest[:8]}.authorino.kuadrant.io"


@dataclass
class Lease:
    holder: str
    acquire_time: float
    renew_time: float
    duration_s: float
    transitions: int = 0
    released: bool = False  # voluntary give-up: backends persist it as expired

    def expired(self, now: float) -> bool:
        return self.released or now - self.renew_time > self.duration_s


class LeaseClient(Protocol):
    async def get_lease(self, namespace: str, name: str) -> Optional[Lease]: ...
    async def put_lease(self, namespace: str, name: str, lease: Lease) -> bool:
        """Create-or-replace; returns False on conflict (someone else won)."""
        ...


class InMemoryLeases:
    """Test/standalone lease store with compare-and-swap semantics."""

    def __init__(self):
        self._leases: Dict[tuple, Lease] = {}
        self._lock = asyncio.Lock()

    async def get_lease(self, namespace: str, name: str) -> Optional[Lease]:
        return self._leases.get((namespace, name))

    async def put_lease(self, namespace: str, name: str, lease: Lease) -> bool:
        async with self._lock:
            cur = self._leases.get((namespace, name))
            now = time.monotonic()
            if cur is not None and cur.holder != lease.holder and not cur.expired(now):
                return False
            self._leases[(namespace, name)] = lease
            return True


class LeaderElector:
    """Run loop: try to acquire/renew the lease; fire callbacks on
    transitions.  ``is_leader()`` gates status writes."""

    def __init__(
        self,
        leases: LeaseClient,
        identity: str,
        namespace: str = "default",
        name: Optional[str] = None,
        duration_s: float = 15.0,
        renew_interval: Optional[float] = None,
        renew_deadline_s: Optional[float] = None,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.leases = leases
        self.identity = identity
        self.namespace = namespace
        self.name = name if name is not None else leader_election_id()
        self.duration_s = duration_s
        # client-go defaults: renewDeadline (10s) strictly inside
        # leaseDuration (15s) and retryPeriod (2s) inside renewDeadline —
        # so a partitioned leader demotes itself before any follower can
        # legally acquire the expired lease.  Defaults scale with
        # duration_s so short test leases stay valid without extra args.
        self.renew_interval = (
            renew_interval if renew_interval is not None else duration_s * 2.0 / 15.0
        )
        self.renew_deadline_s = (
            renew_deadline_s if renew_deadline_s is not None else duration_s * 2.0 / 3.0
        )
        # client-go rejects these at construction (leaderelection.go config
        # validation): a deadline at/after lease expiry voids the "demote
        # strictly before a follower can acquire" invariant
        if self.renew_deadline_s >= duration_s:
            raise ValueError(
                f"renew_deadline_s ({self.renew_deadline_s}) must be < "
                f"duration_s ({duration_s})"
            )
        if self.renew_interval >= self.renew_deadline_s:
            raise ValueError(
                f"renew_interval ({self.renew_interval}) must be < "
                f"renew_deadline_s ({self.renew_deadline_s})"
            )
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._last_renew = 0.0
        self._task: Optional[asyncio.Task] = None

    def is_leader(self) -> bool:
        return self._leading

    async def try_acquire_or_renew(self) -> bool:
        now = time.monotonic()
        try:
            cur = await self.leases.get_lease(self.namespace, self.name)
            if cur is not None and cur.holder != self.identity and not cur.expired(now):
                self._set_leading(False)
                return False
            lease = Lease(
                holder=self.identity,
                acquire_time=cur.acquire_time if cur and cur.holder == self.identity else now,
                renew_time=now,
                duration_s=self.duration_s,
                transitions=(cur.transitions + 1) if cur and cur.holder != self.identity else (cur.transitions if cur else 0),
            )
            if cur is not None:
                # optimistic concurrency: the PUT must CAS on the version we
                # read, or two candidates racing an expired lease both win
                rv = getattr(cur, "_resource_version", None)
                if rv is not None:
                    lease._resource_version = rv  # type: ignore[attr-defined]
            ok = await self.leases.put_lease(self.namespace, self.name, lease)
            if ok:
                self._last_renew = now
            self._set_leading(bool(ok))
            return bool(ok)
        except Exception as e:  # API unreachable — retryable while leading
            log.warning("lease renew failed: %s", e)
            # renew-deadline semantics (client-go): a transient API error
            # does not demote the leader — no other replica can take the
            # still-unexpired lease, and demoting leaves zero status
            # writers.  Step down at the renew deadline, strictly before
            # lease expiry, so a partitioned leader never overlaps a
            # follower that legally acquires the expired lease.
            # re-read the clock: time blocked inside the failed API call
            # counts against the deadline (a request that hangs past lease
            # expiry must demote NOW, not one cycle later)
            if (
                self._leading
                and time.monotonic() - self._last_renew <= self.renew_deadline_s
            ):
                return True
            self._set_leading(False)
            return False

    def _set_leading(self, leading: bool) -> None:
        if leading and not self._leading:
            log.info("leader election: %s started leading", self.identity)
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leading:
            log.info("leader election: %s stopped leading", self.identity)
            if self.on_stopped_leading:
                self.on_stopped_leading()
        self._leading = leading

    async def run(self) -> None:
        try:
            while True:
                await self.try_acquire_or_renew()
                await asyncio.sleep(self.renew_interval)
        finally:
            await self.release()

    def start(self) -> "LeaderElector":
        self._task = asyncio.get_event_loop().create_task(self.run())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def release(self) -> None:
        """Voluntarily give up the lease (fast failover on clean shutdown)."""
        if not self._leading:
            return
        try:
            cur = await self.leases.get_lease(self.namespace, self.name)
            if cur is not None and cur.holder == self.identity:
                # mark expired so the next candidate can take it immediately
                cur.renew_time = time.monotonic() - cur.duration_s - 1
                cur.released = True
                await self.leases.put_lease(self.namespace, self.name, cur)
        except Exception:
            pass
        self._set_leading(False)
