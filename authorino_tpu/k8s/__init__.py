"""Kubernetes access seam: cluster reader protocol + in-memory/REST impls."""

from .client import ClusterReader, InMemoryCluster, LabelSelector, RestCluster, Secret  # noqa: F401
from .leader import InMemoryLeases, LeaderElector, Lease  # noqa: F401
