"""Cluster abstraction: the narrow seam every Kubernetes-touching evaluator
and controller goes through (the analog of the reference's injected
controller-runtime client / typed clientsets — SURVEY.md §4 notes the
narrow-interface style is what makes its fakes easy).

Implementations:
  - InMemoryCluster — tests and standalone mode (secrets loaded from YAML)
  - RestCluster    — real Kubernetes over its REST API with aiohttp
    (in-cluster service account or kubeconfig token); built without the
    `kubernetes` pip package, which is not in the image
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import ssl
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Protocol, Tuple

__all__ = ["Secret", "LabelSelector", "ClusterReader", "InMemoryCluster", "RestCluster"]


@dataclass
class Secret:
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    data: Dict[str, bytes] = field(default_factory=dict)
    uid: str = ""

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)

    def to_identity_object(self) -> Dict[str, Any]:
        """K8s-Secret-shaped JSON: what the API-key evaluator resolves as the
        identity object (ref: pkg/evaluators/identity/api_key.go:79-82 returns
        the Secret resource)."""
        return {
            "apiVersion": "v1",
            "kind": "Secret",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "labels": dict(self.labels),
                "annotations": dict(self.annotations),
                "uid": self.uid,
            },
            "data": {k: base64.b64encode(v).decode() for k, v in self.data.items()},
        }


@dataclass(frozen=True)
class LabelSelector:
    """matchLabels + a subset of string-form expressions ("k=v,k2 in (a,b),!k3")."""

    match_labels: Tuple[Tuple[str, str], ...] = ()
    expressions: Tuple[Tuple[str, str, Tuple[str, ...]], ...] = ()  # (key, op, values)

    @classmethod
    def parse(cls, selector: str) -> "LabelSelector":
        match_labels: List[Tuple[str, str]] = []
        expressions: List[Tuple[str, str, Tuple[str, ...]]] = []
        s = selector.strip()
        i = 0
        parts: List[str] = []
        depth = 0
        buf = []
        for ch in s:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            if ch == "," and depth == 0:
                parts.append("".join(buf))
                buf = []
            else:
                buf.append(ch)
        if buf:
            parts.append("".join(buf))
        for part in parts:
            part = part.strip()
            if not part:
                continue
            if " in " in part or " notin " in part:
                op = "in" if " in " in part else "notin"
                key, _, rest = part.partition(f" {op} ")
                vals = tuple(v.strip() for v in rest.strip().strip("()").split(","))
                expressions.append((key.strip(), op, vals))
            elif part.startswith("!"):
                expressions.append((part[1:].strip(), "!", ()))
            elif "!=" in part:
                k, _, v = part.partition("!=")
                expressions.append((k.strip(), "!=", (v.strip(),)))
            elif "=" in part:
                k, _, v = part.partition("==") if "==" in part else part.partition("=")
                match_labels.append((k.strip(), v.strip()))
            else:
                expressions.append((part, "exists", ()))
        return cls(tuple(match_labels), tuple(expressions))

    @classmethod
    def from_spec(cls, spec: Optional[dict]) -> "LabelSelector":
        """From a K8s LabelSelector object ({matchLabels, matchExpressions})."""
        if not spec:
            return cls()
        ml = tuple(sorted((spec.get("matchLabels") or {}).items()))
        exprs = []
        for e in spec.get("matchExpressions") or []:
            op = {"In": "in", "NotIn": "notin", "Exists": "exists", "DoesNotExist": "!"}.get(
                e.get("operator", ""), "exists"
            )
            exprs.append((e.get("key", ""), op, tuple(e.get("values") or ())))
        return cls(ml, tuple(exprs))

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for key, op, values in self.expressions:
            if op == "in" and labels.get(key) not in values:
                return False
            if op == "notin" and labels.get(key) in values:
                return False
            if op == "exists" and key not in labels:
                return False
            if op == "!" and key in labels:
                return False
            if op == "!=" and labels.get(key) == values[0]:
                return False
        return True

    def to_string(self) -> str:
        out = [f"{k}={v}" for k, v in self.match_labels]
        for key, op, values in self.expressions:
            if op == "in":
                out.append(f"{key} in ({','.join(values)})")
            elif op == "notin":
                out.append(f"{key} notin ({','.join(values)})")
            elif op == "exists":
                out.append(key)
            elif op == "!":
                out.append(f"!{key}")
            elif op == "!=":
                out.append(f"{key}!={values[0]}")
        return ",".join(out)

    def empty(self) -> bool:
        return not self.match_labels and not self.expressions


class ClusterReader(Protocol):
    async def list_secrets(self, selector: LabelSelector, namespace: Optional[str] = None) -> List[Secret]: ...
    async def get_secret(self, namespace: str, name: str) -> Optional[Secret]: ...
    async def token_review(self, token: str, audiences: List[str]) -> Dict[str, Any]: ...
    async def subject_access_review(self, spec: Dict[str, Any]) -> Dict[str, Any]: ...


class InMemoryCluster:
    """Fake cluster for tests/standalone mode; secret mutations notify
    subscribers (drives the secret reconciler like a watch stream)."""

    def __init__(self):
        self._secrets: Dict[Tuple[str, str], Secret] = {}
        self._secret_listeners: List[Callable[[str, Secret], None]] = []
        self.token_reviews: Dict[str, Dict[str, Any]] = {}
        self.access_reviews: Callable[[Dict[str, Any]], Dict[str, Any]] = lambda spec: {
            "status": {"allowed": False}
        }

    # --- secrets ---
    def put_secret(self, secret: Secret) -> None:
        self._secrets[secret.key] = secret
        for fn in self._secret_listeners:
            fn("upsert", secret)

    def remove_secret(self, namespace: str, name: str) -> None:
        secret = self._secrets.pop((namespace, name), None)
        if secret is not None:
            for fn in self._secret_listeners:
                fn("delete", secret)

    def on_secret_event(self, fn: Callable[[str, Secret], None]) -> None:
        self._secret_listeners.append(fn)

    async def list_secrets(self, selector: LabelSelector, namespace: Optional[str] = None) -> List[Secret]:
        return [
            s
            for s in self._secrets.values()
            if (namespace is None or s.namespace == namespace) and selector.matches(s.labels)
        ]

    async def get_secret(self, namespace: str, name: str) -> Optional[Secret]:
        return self._secrets.get((namespace, name))

    # --- reviews ---
    async def token_review(self, token: str, audiences: List[str]) -> Dict[str, Any]:
        hit = self.token_reviews.get(token)
        if hit is None:
            return {"status": {"authenticated": False, "error": "invalid token"}}
        return hit

    async def subject_access_review(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        return self.access_reviews(spec)


class RestCluster:
    """Kubernetes REST client over aiohttp (no `kubernetes` pip dependency).

    In-cluster: reads the service-account token + CA from
    /var/run/secrets/kubernetes.io/serviceaccount (like client-go's
    InClusterConfig the reference relies on through controller-runtime)."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, base_url: Optional[str] = None, token: Optional[str] = None, ca_file: Optional[str] = None):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError("not running in-cluster and no base_url given")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        self._token = token
        self._ca_file = ca_file or os.path.join(self.SA_DIR, "ca.crt")
        self._ssl: Optional[ssl.SSLContext] = None

    def _auth_headers(self) -> Dict[str, str]:
        token = self._token
        if token is None:
            try:
                with open(os.path.join(self.SA_DIR, "token")) as f:
                    token = f.read().strip()
            except OSError:
                token = ""
        return {"Authorization": f"Bearer {token}"} if token else {}

    def _ssl_ctx(self):
        if self._ssl is None and os.path.exists(self._ca_file):
            self._ssl = ssl.create_default_context(cafile=self._ca_file)
        return self._ssl

    async def _request(self, method: str, path: str, **kw) -> Any:
        from ..utils import http as http_util

        sess = http_util.get_session()
        headers = {**self._auth_headers(), **kw.pop("headers", {})}
        async with sess.request(
            method, f"{self.base_url}{path}", headers=headers, ssl=self._ssl_ctx(), **kw
        ) as resp:
            body = await resp.text()
            if resp.status >= 300:
                raise RuntimeError(f"k8s api {method} {path}: {resp.status} {body[:200]}")
            return json.loads(body) if body else {}

    @staticmethod
    def _secret_from_obj(obj: Dict[str, Any]) -> Secret:
        meta = obj.get("metadata", {})
        return Secret(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            labels=meta.get("labels", {}) or {},
            annotations=meta.get("annotations", {}) or {},
            uid=meta.get("uid", ""),
            data={k: base64.b64decode(v) for k, v in (obj.get("data") or {}).items()},
        )

    async def list_secrets(self, selector: LabelSelector, namespace: Optional[str] = None) -> List[Secret]:
        path = f"/api/v1/namespaces/{namespace}/secrets" if namespace else "/api/v1/secrets"
        params = {}
        sel = selector.to_string()
        if sel:
            params["labelSelector"] = sel
        payload = await self._request("GET", path, params=params)
        return [self._secret_from_obj(o) for o in payload.get("items", [])]

    async def get_secret(self, namespace: str, name: str) -> Optional[Secret]:
        try:
            obj = await self._request("GET", f"/api/v1/namespaces/{namespace}/secrets/{name}")
        except RuntimeError:
            return None
        return self._secret_from_obj(obj)

    async def token_review(self, token: str, audiences: List[str]) -> Dict[str, Any]:
        body = {
            "apiVersion": "authentication.k8s.io/v1",
            "kind": "TokenReview",
            "spec": {"token": token, "audiences": audiences},
        }
        return await self._request(
            "POST", "/apis/authentication.k8s.io/v1/tokenreviews", json=body
        )

    async def subject_access_review(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        body = {
            "apiVersion": "authorization.k8s.io/v1",
            "kind": "SubjectAccessReview",
            "spec": spec,
        }
        return await self._request(
            "POST", "/apis/authorization.k8s.io/v1/subjectaccessreviews", json=body
        )
