"""Cluster abstraction: the narrow seam every Kubernetes-touching evaluator
and controller goes through (the analog of the reference's injected
controller-runtime client / typed clientsets — SURVEY.md §4 notes the
narrow-interface style is what makes its fakes easy).

Implementations:
  - InMemoryCluster — tests and standalone mode (secrets loaded from YAML)
  - RestCluster    — real Kubernetes over its REST API with aiohttp
    (in-cluster service account or kubeconfig token); built without the
    `kubernetes` pip package, which is not in the image
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import ssl
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Protocol, Tuple

__all__ = ["Secret", "LabelSelector", "ClusterReader", "InMemoryCluster", "RestCluster"]


@dataclass
class Secret:
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    data: Dict[str, bytes] = field(default_factory=dict)
    uid: str = ""

    @property
    def key(self) -> Tuple[str, str]:
        return (self.namespace, self.name)

    def to_identity_object(self) -> Dict[str, Any]:
        """K8s-Secret-shaped JSON: what the API-key evaluator resolves as the
        identity object (ref: pkg/evaluators/identity/api_key.go:79-82 returns
        the Secret resource)."""
        return {
            "apiVersion": "v1",
            "kind": "Secret",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "labels": dict(self.labels),
                "annotations": dict(self.annotations),
                "uid": self.uid,
            },
            "data": {k: base64.b64encode(v).decode() for k, v in self.data.items()},
        }


@dataclass(frozen=True)
class LabelSelector:
    """matchLabels + a subset of string-form expressions ("k=v,k2 in (a,b),!k3")."""

    match_labels: Tuple[Tuple[str, str], ...] = ()
    expressions: Tuple[Tuple[str, str, Tuple[str, ...]], ...] = ()  # (key, op, values)

    @classmethod
    def parse(cls, selector: str) -> "LabelSelector":
        match_labels: List[Tuple[str, str]] = []
        expressions: List[Tuple[str, str, Tuple[str, ...]]] = []
        s = selector.strip()
        i = 0
        parts: List[str] = []
        depth = 0
        buf = []
        for ch in s:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            if ch == "," and depth == 0:
                parts.append("".join(buf))
                buf = []
            else:
                buf.append(ch)
        if buf:
            parts.append("".join(buf))
        for part in parts:
            part = part.strip()
            if not part:
                continue
            if " in " in part or " notin " in part:
                op = "in" if " in " in part else "notin"
                key, _, rest = part.partition(f" {op} ")
                vals = tuple(v.strip() for v in rest.strip().strip("()").split(","))
                expressions.append((key.strip(), op, vals))
            elif part.startswith("!"):
                expressions.append((part[1:].strip(), "!", ()))
            elif "!=" in part:
                k, _, v = part.partition("!=")
                expressions.append((k.strip(), "!=", (v.strip(),)))
            elif "=" in part:
                k, _, v = part.partition("==") if "==" in part else part.partition("=")
                match_labels.append((k.strip(), v.strip()))
            else:
                expressions.append((part, "exists", ()))
        return cls(tuple(match_labels), tuple(expressions))

    @classmethod
    def from_spec(cls, spec: Optional[dict]) -> "LabelSelector":
        """From a K8s LabelSelector object ({matchLabels, matchExpressions})."""
        if not spec:
            return cls()
        ml = tuple(sorted((spec.get("matchLabels") or {}).items()))
        exprs = []
        for e in spec.get("matchExpressions") or []:
            op = {"In": "in", "NotIn": "notin", "Exists": "exists", "DoesNotExist": "!"}.get(
                e.get("operator", ""), "exists"
            )
            exprs.append((e.get("key", ""), op, tuple(e.get("values") or ())))
        return cls(ml, tuple(exprs))

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        for key, op, values in self.expressions:
            if op == "in" and labels.get(key) not in values:
                return False
            if op == "notin" and labels.get(key) in values:
                return False
            if op == "exists" and key not in labels:
                return False
            if op == "!" and key in labels:
                return False
            if op == "!=" and labels.get(key) == values[0]:
                return False
        return True

    def to_string(self) -> str:
        out = [f"{k}={v}" for k, v in self.match_labels]
        for key, op, values in self.expressions:
            if op == "in":
                out.append(f"{key} in ({','.join(values)})")
            elif op == "notin":
                out.append(f"{key} notin ({','.join(values)})")
            elif op == "exists":
                out.append(key)
            elif op == "!":
                out.append(f"!{key}")
            elif op == "!=":
                out.append(f"{key}!={values[0]}")
        return ",".join(out)

    def empty(self) -> bool:
        return not self.match_labels and not self.expressions


class ClusterReader(Protocol):
    async def list_secrets(self, selector: LabelSelector, namespace: Optional[str] = None) -> List[Secret]: ...
    async def get_secret(self, namespace: str, name: str) -> Optional[Secret]: ...
    async def token_review(self, token: str, audiences: List[str]) -> Dict[str, Any]: ...
    async def subject_access_review(self, spec: Dict[str, Any]) -> Dict[str, Any]: ...


class InMemoryCluster:
    """Fake cluster for tests/standalone mode; secret/authconfig mutations
    notify subscribers (drives the reconcilers like watch streams)."""

    def __init__(self):
        self._secrets: Dict[Tuple[str, str], Secret] = {}
        self._secret_listeners: List[Callable[[str, Secret], None]] = []
        self._auth_configs: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._auth_config_listeners: List[Callable[[str, Dict[str, Any]], None]] = []
        self.statuses: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.token_reviews: Dict[str, Dict[str, Any]] = {}
        self.access_reviews: Callable[[Dict[str, Any]], Dict[str, Any]] = lambda spec: {
            "status": {"allowed": False}
        }

    # --- authconfigs ---
    @staticmethod
    def _ac_key(obj: Dict[str, Any]) -> Tuple[str, str]:
        meta = obj.get("metadata") or {}
        return (meta.get("namespace", "default"), meta.get("name", ""))

    def put_auth_config(self, obj: Dict[str, Any]) -> None:
        self._auth_configs[self._ac_key(obj)] = obj
        for fn in self._auth_config_listeners:
            fn("upsert", obj)

    def remove_auth_config(self, namespace: str, name: str) -> None:
        obj = self._auth_configs.pop((namespace, name), None)
        if obj is not None:
            for fn in self._auth_config_listeners:
                fn("delete", obj)

    def on_auth_config_event(self, fn: Callable[[str, Dict[str, Any]], None]) -> None:
        self._auth_config_listeners.append(fn)

    async def list_auth_configs(self, selector: Optional["LabelSelector"] = None) -> List[Dict[str, Any]]:
        out = []
        for obj in self._auth_configs.values():
            labels = (obj.get("metadata") or {}).get("labels") or {}
            if selector is None or selector.matches(labels):
                out.append(obj)
        return out

    async def patch_auth_config_status(self, namespace: str, name: str, status: Dict[str, Any]) -> None:
        self.statuses[(namespace, name)] = status
        obj = self._auth_configs.get((namespace, name))
        if obj is not None:
            obj["status"] = status

    # --- secrets ---
    def put_secret(self, secret: Secret) -> None:
        self._secrets[secret.key] = secret
        for fn in self._secret_listeners:
            fn("upsert", secret)

    def remove_secret(self, namespace: str, name: str) -> None:
        secret = self._secrets.pop((namespace, name), None)
        if secret is not None:
            for fn in self._secret_listeners:
                fn("delete", secret)

    def on_secret_event(self, fn: Callable[[str, Secret], None]) -> None:
        self._secret_listeners.append(fn)

    async def list_secrets(self, selector: LabelSelector, namespace: Optional[str] = None) -> List[Secret]:
        return [
            s
            for s in self._secrets.values()
            if (namespace is None or s.namespace == namespace) and selector.matches(s.labels)
        ]

    async def get_secret(self, namespace: str, name: str) -> Optional[Secret]:
        return self._secrets.get((namespace, name))

    # --- reviews ---
    async def token_review(self, token: str, audiences: List[str]) -> Dict[str, Any]:
        hit = self.token_reviews.get(token)
        if hit is None:
            return {"status": {"authenticated": False, "error": "invalid token"}}
        return hit

    async def subject_access_review(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        return self.access_reviews(spec)

    # --- leases (delegated to an in-memory CAS store) ---
    @property
    def _lease_store(self):
        if not hasattr(self, "_leases_impl"):
            from .leader import InMemoryLeases

            self._leases_impl = InMemoryLeases()
        return self._leases_impl

    async def get_lease(self, namespace: str, name: str):
        return await self._lease_store.get_lease(namespace, name)

    async def put_lease(self, namespace: str, name: str, lease) -> bool:
        return await self._lease_store.put_lease(namespace, name, lease)


class RestCluster:
    """Kubernetes REST client over aiohttp (no `kubernetes` pip dependency).

    In-cluster: reads the service-account token + CA from
    /var/run/secrets/kubernetes.io/serviceaccount (like client-go's
    InClusterConfig the reference relies on through controller-runtime)."""

    SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

    def __init__(self, base_url: Optional[str] = None, token: Optional[str] = None, ca_file: Optional[str] = None):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError("not running in-cluster and no base_url given")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        self._token = token
        self._ca_file = ca_file or os.path.join(self.SA_DIR, "ca.crt")
        self._ssl: Optional[ssl.SSLContext] = None

    def _auth_headers(self) -> Dict[str, str]:
        token = self._token
        if token is None:
            try:
                with open(os.path.join(self.SA_DIR, "token")) as f:
                    token = f.read().strip()
            except OSError:
                token = ""
        return {"Authorization": f"Bearer {token}"} if token else {}

    def _ssl_ctx(self):
        if self._ssl is None and os.path.exists(self._ca_file):
            self._ssl = ssl.create_default_context(cafile=self._ca_file)
        return self._ssl

    async def _request(self, method: str, path: str, **kw) -> Any:
        from ..utils import http as http_util

        sess = http_util.get_session()
        headers = {**self._auth_headers(), **kw.pop("headers", {})}
        async with sess.request(
            method, f"{self.base_url}{path}", headers=headers, ssl=self._ssl_ctx(), **kw
        ) as resp:
            body = await resp.text()
            if resp.status >= 300:
                raise RuntimeError(f"k8s api {method} {path}: {resp.status} {body[:200]}")
            return json.loads(body) if body else {}

    @staticmethod
    def _secret_from_obj(obj: Dict[str, Any]) -> Secret:
        meta = obj.get("metadata", {})
        return Secret(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            labels=meta.get("labels", {}) or {},
            annotations=meta.get("annotations", {}) or {},
            uid=meta.get("uid", ""),
            data={k: base64.b64decode(v) for k, v in (obj.get("data") or {}).items()},
        )

    async def list_secrets(self, selector: LabelSelector, namespace: Optional[str] = None) -> List[Secret]:
        secrets, _ = await self.list_secrets_rv(selector, namespace)
        return secrets

    async def list_secrets_rv(
        self, selector: LabelSelector, namespace: Optional[str] = None
    ) -> Tuple[List[Secret], Optional[str]]:
        path = f"/api/v1/namespaces/{namespace}/secrets" if namespace else "/api/v1/secrets"
        params = {}
        sel = selector.to_string()
        if sel:
            params["labelSelector"] = sel
        payload = await self._request("GET", path, params=params)
        rv = (payload.get("metadata") or {}).get("resourceVersion")
        return [self._secret_from_obj(o) for o in payload.get("items", [])], rv

    async def get_secret(self, namespace: str, name: str) -> Optional[Secret]:
        try:
            obj = await self._request("GET", f"/api/v1/namespaces/{namespace}/secrets/{name}")
        except RuntimeError:
            return None
        return self._secret_from_obj(obj)

    async def token_review(self, token: str, audiences: List[str]) -> Dict[str, Any]:
        body = {
            "apiVersion": "authentication.k8s.io/v1",
            "kind": "TokenReview",
            "spec": {"token": token, "audiences": audiences},
        }
        return await self._request(
            "POST", "/apis/authentication.k8s.io/v1/tokenreviews", json=body
        )

    async def subject_access_review(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        body = {
            "apiVersion": "authorization.k8s.io/v1",
            "kind": "SubjectAccessReview",
            "spec": spec,
        }
        return await self._request(
            "POST", "/apis/authorization.k8s.io/v1/subjectaccessreviews", json=body
        )

    # --- AuthConfig CRs (authorino.kuadrant.io) ---------------------------
    AC_GROUP = "authorino.kuadrant.io"
    AC_VERSION = "v1beta2"

    def _ac_path(self, namespace: Optional[str] = None, name: Optional[str] = None) -> str:
        base = f"/apis/{self.AC_GROUP}/{self.AC_VERSION}"
        if namespace:
            base += f"/namespaces/{namespace}"
        base += "/authconfigs"
        if name:
            base += f"/{name}"
        return base

    async def list_auth_configs(self, selector: Optional[LabelSelector] = None) -> List[Dict[str, Any]]:
        items, _ = await self.list_auth_configs_rv(selector)
        return items

    async def list_auth_configs_rv(
        self, selector: Optional[LabelSelector] = None
    ) -> Tuple[List[Dict[str, Any]], Optional[str]]:
        """List + the list's resourceVersion, so a watch can start exactly
        where this snapshot ends (no missed-delete gap between list and
        watch — ref: controller-runtime informer ListAndWatch)."""
        params = {}
        if selector is not None and not selector.empty():
            params["labelSelector"] = selector.to_string()
        payload = await self._request("GET", self._ac_path(), params=params)
        rv = (payload.get("metadata") or {}).get("resourceVersion")
        return payload.get("items", []), rv

    async def patch_auth_config_status(self, namespace: str, name: str, status: Dict[str, Any]) -> None:
        """Status subresource merge-patch (the leader-elected writer's
        operation — ref: controllers/auth_config_status_updater.go:35-103)."""
        await self._request(
            "PATCH",
            self._ac_path(namespace, name) + "/status",
            json={"status": status},
            headers={"Content-Type": "application/merge-patch+json"},
        )

    async def watch(self, path: str, params: Optional[Dict[str, str]] = None,
                    timeout_seconds: int = 300):
        """Yield (event_type, object) from a K8s watch stream (chunked JSON
        lines).  Caller re-lists + re-watches on stream end (the informer
        resync the reference gets from controller-runtime).  Bounded both
        server-side (timeoutSeconds) and client-side (sock_read) so a
        half-open TCP connection can't hang the watch forever."""
        import aiohttp

        from ..utils import http as http_util

        sess = http_util.get_session()
        q = dict(params or {})
        q["watch"] = "true"
        q["timeoutSeconds"] = str(timeout_seconds)
        headers = self._auth_headers()
        client_timeout = aiohttp.ClientTimeout(total=None, sock_read=timeout_seconds + 30)
        async with sess.request(
            "GET", f"{self.base_url}{path}", params=q, headers=headers,
            ssl=self._ssl_ctx(), timeout=client_timeout,
        ) as resp:
            if resp.status >= 300:
                raise RuntimeError(f"k8s watch {path}: {resp.status}")
            buf = b""
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    yield ev.get("type", ""), ev.get("object", {})

    # --- Leases (coordination.k8s.io/v1) ----------------------------------
    def _lease_path(self, namespace: str, name: Optional[str] = None) -> str:
        p = f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        return f"{p}/{name}" if name else p

    async def get_lease(self, namespace: str, name: str):
        from .leader import Lease

        try:
            obj = await self._request("GET", self._lease_path(namespace, name))
        except RuntimeError as e:
            # only not-found means "unheld"; transient API errors must NOT
            # look like a free lease or followers would seize leadership on
            # every apiserver blip
            if ": 404" in str(e):
                return None
            raise
        spec = obj.get("spec") or {}
        lease = Lease(
            holder=spec.get("holderIdentity", ""),
            acquire_time=0.0,
            renew_time=0.0,
            duration_s=float(spec.get("leaseDurationSeconds", 15)),
            transitions=int(spec.get("leaseTransitions", 0)),
        )
        # renewTime is RFC3339; convert to a monotonic-comparable age
        import datetime
        import time as _time

        rt = spec.get("renewTime")
        if rt:
            try:
                dt = datetime.datetime.fromisoformat(rt.replace("Z", "+00:00"))
                age = (datetime.datetime.now(datetime.timezone.utc) - dt).total_seconds()
                lease.renew_time = _time.monotonic() - age
            except ValueError:
                pass
        lease._resource_version = (obj.get("metadata") or {}).get("resourceVersion")  # type: ignore[attr-defined]
        return lease

    async def put_lease(self, namespace: str, name: str, lease) -> bool:
        import datetime

        now = datetime.datetime.now(datetime.timezone.utc)
        if getattr(lease, "released", False):
            # voluntary release: persist a renewTime already past the lease
            # duration so the next candidate can take over immediately
            now -= datetime.timedelta(seconds=lease.duration_s + 1)
        now_iso = now.isoformat().replace("+00:00", "Z")
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": name, "namespace": namespace},
            "spec": {
                "holderIdentity": lease.holder,
                "leaseDurationSeconds": int(lease.duration_s),
                "renewTime": now_iso,
                "leaseTransitions": lease.transitions,
            },
        }
        rv = getattr(lease, "_resource_version", None)
        if rv:
            body["metadata"]["resourceVersion"] = rv
        try:
            try:
                await self._request("PUT", self._lease_path(namespace, name), json=body)
            except RuntimeError as e:
                if "404" in str(e):
                    await self._request("POST", self._lease_path(namespace), json=body)
                else:
                    raise
            return True
        except RuntimeError:
            return False  # conflict: another holder updated first
