"""Ancestor closure of entity→group edges — the host-side half of the
compiled relation tables (ISSUE 14, the Cedar move from PAPERS.md
arXiv 2403.04651: hierarchical membership is *data*, sliced and closed at
reconcile time, so request-time evaluation is a single table lookup).

An edge ``(child, parent)`` asserts direct membership of ``child`` in
``parent``.  ``contains(entity, group)`` is reachability through one or
more edges — the transitive ancestor closure — computed once by a
monotone bitset fixpoint (cycle-safe: membership only ever grows), so
diamond graphs and deep hierarchies cost the same lookup as flat ones.

The closure is FROZEN after construction and identified by a canonical
digest over its sorted edge set: two configs declaring identical edges
share one compiled table, fingerprints fold the digest (a changed edge
re-certifies exactly the configs reading that relation), and the replica
deserializer rebuilds an identical closure from the serialized edges.

Import-light: stdlib + hashlib only.
"""

from __future__ import annotations

import hashlib
from typing import Dict, FrozenSet, Iterable, List, Sequence, Tuple

__all__ = ["RelationClosure"]


class RelationClosure:
    """Immutable transitive closure of a (child, parent) edge set."""

    __slots__ = ("edges", "digest", "_groups_of", "_entities", "_groups")

    def __init__(self, edges: Iterable[Sequence[str]]):
        canon: List[Tuple[str, str]] = sorted(
            {(str(c), str(p)) for c, p in edges})
        self.edges: Tuple[Tuple[str, str], ...] = tuple(canon)
        h = hashlib.sha256()
        for c, p in canon:
            h.update(c.encode("utf-8", "replace"))
            h.update(b"\x00")
            h.update(p.encode("utf-8", "replace"))
            h.update(b"\x01")
        self.digest: str = h.hexdigest()

        parents: Dict[str, set] = {}
        nodes: set = set()
        for c, p in canon:
            parents.setdefault(c, set()).add(p)
            nodes.add(c)
            nodes.add(p)
        # monotone fixpoint: groups_of[n] ∪= groups_of[parent] until stable.
        # Monotonicity makes cycles harmless (a cycle's members converge on
        # the cycle's union) and diamonds free (sets dedupe the two paths).
        acc: Dict[str, set] = {n: set(parents.get(n, ())) for n in nodes}
        changed = True
        while changed:
            changed = False
            for n in nodes:
                mine = acc[n]
                before = len(mine)
                for p in tuple(mine):
                    up = acc.get(p)
                    if up:
                        mine |= up
                if len(mine) != before:
                    changed = True
        self._groups_of: Dict[str, FrozenSet[str]] = {
            n: frozenset(s) for n, s in acc.items() if s}
        # entities: every node (any node can be queried as an entity);
        # groups: every node that is some edge's parent (a column target)
        self._entities: Tuple[str, ...] = tuple(sorted(nodes))
        self._groups: Tuple[str, ...] = tuple(
            sorted({p for _, p in canon}))

    # -- queries -----------------------------------------------------------

    def groups_of(self, entity: str) -> FrozenSet[str]:
        """All groups ``entity`` belongs to, transitively (empty for
        unknown entities — an unknown principal is in no groups)."""
        return self._groups_of.get(entity, frozenset())

    def contains(self, entity: str, group: str) -> bool:
        return group in self._groups_of.get(entity, ())

    @property
    def entities(self) -> Tuple[str, ...]:
        return self._entities

    @property
    def groups(self) -> Tuple[str, ...]:
        return self._groups

    @property
    def n_edges(self) -> int:
        return len(self.edges)

    def depth(self) -> int:
        """Longest ancestor chain (levels of hierarchy) — reporting only."""
        memo: Dict[str, int] = {}

        def d(n: str, seen: frozenset) -> int:
            if n in memo:
                return memo[n]
            if n in seen:
                return 0  # cycle: bounded
            best = 0
            for c, p in self.edges:
                if c == n:
                    best = max(best, 1 + d(p, seen | {n}))
            memo[n] = best
            return best

        return max((d(e, frozenset()) for e in self._entities), default=0)

    # -- identity ----------------------------------------------------------

    def __eq__(self, other) -> bool:
        return isinstance(other, RelationClosure) and \
            other.digest == self.digest

    def __hash__(self) -> int:
        return hash(self.digest)

    def __repr__(self) -> str:
        return (f"RelationClosure({len(self.edges)} edges, "
                f"{len(self._entities)} entities, "
                f"{len(self._groups)} groups, {self.digest[:12]})")
