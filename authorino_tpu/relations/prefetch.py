"""Metadata prefetch cache (ISSUE 14): pin request-independent external
metadata/OIDC documents at reconcile cadence so metadata-dependent configs
stop being automatic slow-lane residents.

The microservice-auth survey (PAPERS.md arXiv 2009.02114) frames the
problem: a shared PDP must not pay a per-request external-document fetch on
its hot path.  Most real metadata evaluators fetch a REQUEST-INDEPENDENT
document (a static JWKS/OIDC discovery doc, a feature-flag set, an org
policy blob): their endpoint/body/params/headers templates reference no
selectors, so the document is a pure function of the reconcile-time config.
Those are *prefetchable*: a background refresher snapshots them once per
refresh interval and the serving path reads the pinned copy.

Exactness/staleness contract:

  - a PINNED document within ``max_age_s`` serves with zero network I/O
    (counted ``hit``); the pipeline's metadata phase sees exactly what a
    live fetch at pin time returned
  - a stale or never-fetched document falls through, TYPED, to the live
    evaluator call (counted ``stale``/``miss``) — the slow lane remains
    the correctness backstop, prefetch is purely a latency/lane dial
  - request-DEPENDENT evaluators (UserInfo, UMA, templated endpoints,
    per-request conditions/caches) are never prefetchable and keep the
    metadata-dependency slow-lane classification

Each pinned document carries a canonical sha256 digest; the capture log
stamps it per decision (``metadata_doc_digest``) so replays are
reproducible (docs/replay.md).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["MetadataPrefetcher", "PrefetchedDoc", "mark_prefetchable",
           "is_prefetchable", "doc_digest"]

log = logging.getLogger("authorino_tpu.prefetch")


def doc_digest(doc: Any) -> str:
    """Canonical digest of one (JSON-safe) metadata document set."""
    try:
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":"),
                             default=str)
    except Exception:
        payload = repr(doc)
    return hashlib.sha256(payload.encode("utf-8", "replace")).hexdigest()


def _static_value(v: Any) -> bool:
    """True when a JSONValue-shaped object resolves independently of the
    request document (no selector pattern)."""
    return v is None or not getattr(v, "pattern", "")


def is_prefetchable(conf: Any) -> bool:
    """A MetadataConfig is prefetchable iff its evaluator is a GenericHttp
    whose request is a pure function of the reconcile-time config: static
    endpoint/body, static parameters and headers, no `when` conditions and
    no per-request evaluator cache key.  OAuth2 client-credentials and
    shared-secret auth are request-independent and allowed.  Duck-typed on
    shape, not class, so the analysis layer stays import-light."""
    if getattr(conf, "conditions", None) is not None:
        return False
    if getattr(conf, "cache", None) is not None:
        return False
    ev = getattr(conf, "evaluator", None)
    if ev is None or getattr(conf, "type", "") != "METADATA_GENERIC_HTTP":
        return False
    if not _static_value(getattr(ev, "endpoint", None)):
        return False
    if not _static_value(getattr(ev, "body", None)):
        return False
    for p in list(getattr(ev, "parameters", None) or ()) + list(
            getattr(ev, "headers", None) or ()):
        if not _static_value(getattr(p, "value", None)):
            return False
    return True


def mark_prefetchable(conf: Any) -> bool:
    """Stamp the prefetchability bit on a MetadataConfig at translate time
    (the lowerability classifier and the engine's prefetcher both read the
    plain attribute — no imports on their side)."""
    ok = is_prefetchable(conf)
    conf.prefetchable = ok
    conf.prefetch_pinned = False  # set by MetadataPrefetcher.reconcile
    return ok


class PrefetchedDoc:
    __slots__ = ("doc", "digest", "fetched_at", "error")

    def __init__(self, doc: Any, fetched_at: float,
                 error: Optional[str] = None):
        self.doc = doc
        self.digest = doc_digest(doc) if error is None else ""
        self.fetched_at = fetched_at
        self.error = error


class _StubPipeline:
    """The document context a prefetch fetch runs against: an EMPTY
    authorization JSON — prefetchable evaluators never read it (that is
    the definition), a misclassified one would resolve selectors to ""
    and produce a wrong pin, which is why is_prefetchable is conservative."""

    def __init__(self):
        self._doc: Dict[str, Any] = {"auth": {"identity": None,
                                              "metadata": {}}}
        self.span = None

    def authorization_json(self) -> Dict[str, Any]:
        return self._doc


class MetadataPrefetcher:
    """Background refresher + pinned-document cache.

    ``reconcile(entries)`` (engine swap path) registers every prefetchable
    metadata evaluator of the snapshot, binds the serving-side lookup onto
    the MetadataConfig (``conf.prefetch = (self, key)``), and triggers an
    asynchronous refresh; ``refresh_s`` re-pins on a cadence after that.
    ``fetcher`` is injectable for tests (default: run the evaluator's own
    ``call`` on a private asyncio loop thread)."""

    def __init__(self, max_age_s: float = 300.0, refresh_s: float = 60.0,
                 fetcher=None, fetch_timeout_s: float = 10.0):
        self.max_age_s = float(max_age_s)
        self.refresh_s = float(refresh_s)
        self.fetch_timeout_s = float(fetch_timeout_s)
        self._fetcher = fetcher
        self._lock = threading.Lock()
        self._registry: Dict[Tuple[str, str], Any] = {}   # key -> evaluator
        self._docs: Dict[Tuple[str, str], PrefetchedDoc] = {}
        self._counters = {"hit": 0, "miss": 0, "stale": 0,
                          "refresh": 0, "error": 0}
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stop = False

    # -- registration ------------------------------------------------------

    def reconcile(self, entries) -> int:
        """Register the snapshot's prefetchable metadata evaluators and
        wake the refresher.  Returns the number of registered documents;
        stamps ``prefetch_pinned`` on each registered MetadataConfig (the
        bit the lowerability classifier lifts the exile on)."""
        registry: Dict[Tuple[str, str], Any] = {}
        for entry in entries:
            runtime = getattr(entry, "runtime", None)
            for conf in (getattr(runtime, "metadata", None) or ()):
                if not getattr(conf, "prefetchable", False):
                    continue
                key = (str(getattr(entry, "id", "")), str(conf.name))
                registry[key] = conf.evaluator
                conf.prefetch = (self, key)
                conf.prefetch_pinned = True
        with self._lock:
            self._registry = registry
            self._docs = {k: v for k, v in self._docs.items()
                          if k in registry}
        if registry:
            self._ensure_thread()
            self._wake.set()
        return len(registry)

    # -- serving -----------------------------------------------------------

    def lookup(self, key: Tuple[str, str]) -> Optional[PrefetchedDoc]:
        """The hot-path read: the pinned document, or None (miss/stale/
        failed pin) — the caller falls through to the live fetch."""
        from ..utils import metrics as metrics_mod

        with self._lock:
            rec = self._docs.get(key)
        if rec is None or rec.error is not None:
            self._count("miss")
            metrics_mod.metadata_prefetch.labels("miss").inc()
            return None
        if time.monotonic() - rec.fetched_at > self.max_age_s:
            self._count("stale")
            metrics_mod.metadata_prefetch.labels("stale").inc()
            return None
        self._count("hit")
        metrics_mod.metadata_prefetch.labels("hit").inc()
        return rec

    def digest_for(self, config_id: str) -> Optional[str]:
        """Combined digest of every pinned document of one config — the
        ``metadata_doc_digest`` stamped into capture records."""
        with self._lock:
            parts = sorted(
                (k[1], rec.digest) for k, rec in self._docs.items()
                if k[0] == config_id and rec.error is None)
        if not parts:
            return None
        return hashlib.sha256(repr(parts).encode()).hexdigest()

    # -- refresh -----------------------------------------------------------

    def refresh(self) -> Dict[str, int]:
        """Fetch every registered document once, synchronously (callers:
        the refresher thread, tests, and the analysis CLI)."""
        from ..utils import metrics as metrics_mod

        with self._lock:
            items = list(self._registry.items())
        ok = err = 0
        for key, evaluator in items:
            now = time.monotonic()
            try:
                doc = self._fetch(evaluator)
                rec = PrefetchedDoc(doc, now)
                ok += 1
                metrics_mod.metadata_prefetch.labels("refresh").inc()
            except Exception as e:  # typed miss at serve time, never a raise
                err += 1
                self._count("error")
                metrics_mod.metadata_prefetch.labels("error").inc()
                log.warning("metadata prefetch of %s failed: %s", key, e)
                with self._lock:
                    prev = self._docs.get(key)
                    if prev is not None and prev.error is None:
                        # a transient re-pin failure must NOT evict a
                        # still-healthy pin: it keeps serving (with its
                        # original fetched_at) until the staleness bound —
                        # the contract the error metric documents
                        continue
                    rec = PrefetchedDoc(None, now, error=str(e))
                    if key in self._registry:
                        self._docs[key] = rec
                continue
            with self._lock:
                if key in self._registry:
                    self._docs[key] = rec
        self._count("refresh")
        with self._lock:
            metrics_mod.metadata_prefetch_docs.set(
                sum(1 for r in self._docs.values() if r.error is None))
        return {"ok": ok, "error": err}

    def _fetch(self, evaluator) -> Any:
        if self._fetcher is not None:
            return self._fetcher(evaluator)
        # run the evaluator's own async call on a private loop: the
        # refresher thread owns it, nothing here touches the serving loops
        return asyncio.run(asyncio.wait_for(
            evaluator.call(_StubPipeline()), self.fetch_timeout_s))

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="atpu-md-prefetch", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=self.refresh_s)
            self._wake.clear()
            if self._stop:
                return
            try:
                self.refresh()
            except Exception:
                log.exception("metadata prefetch refresh failed")

    def stop(self, timeout_s: float = 2.0) -> None:
        self._stop = True
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)

    # -- reporting ---------------------------------------------------------

    def _count(self, key: str) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + 1

    def export_docs(self) -> Dict[str, Dict[str, Any]]:
        """{config_id: {metadata_name: document}} of every healthy pin —
        what `analysis --replay ... --metadata-docs` consumes to un-blind
        the replay oracle for metadata-dependent configs."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for (cfg, name), rec in self._docs.items():
                if rec.error is None:
                    out.setdefault(cfg, {})[name] = rec.doc
        return out

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            docs = {
                f"{cfg}/{name}": {
                    "digest": rec.digest[:16],
                    "age_s": round(time.monotonic() - rec.fetched_at, 3),
                    "error": rec.error,
                }
                for (cfg, name), rec in sorted(self._docs.items())[:64]
            }
            return {
                "registered": len(self._registry),
                "pinned": sum(1 for r in self._docs.values()
                              if r.error is None),
                "max_age_s": self.max_age_s,
                "refresh_s": self.refresh_s,
                "counters": dict(self._counters),
                "docs": docs,
            }
