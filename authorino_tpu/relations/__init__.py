"""Compiled relations (ISSUE 14): Cedar-style hierarchical entity/group
membership precomputed at reconcile time into per-snapshot bitmatrix
relation tables (closure.py), plus the metadata prefetch cache that lets
metadata-dependent configs evaluate against pinned documents on the fast
lane (prefetch.py)."""

from .closure import RelationClosure

__all__ = ["RelationClosure"]
