"""Policy CI decision corpus (ISSUE 19, docs/policy_ci.md).

PR 13's replay pregate judges a reconcile against *yesterday's traffic* —
a policy edit on a rule traffic never exercises sails through unchecked.
This package closes that hole with a coverage-guided decision corpus:

- ``store``      — the long-retention corpus container (PR 8 pickle-free
                   checksummed format, ``.atpucorp`` suffix) and the pinned
                   corpus-row shape;
- ``distill``    — fold capture segments / the live capture ring into
                   distinct decision rows deduplicated by the PR 3
                   canonical row key, each carrying a frequency weight,
                   first/last-seen, and PR 9 firing attribution;
- ``synthesize`` — per-(config, rule, evaluator-column) coverage against
                   the corpus's fired set, then truth-table inversion of
                   the PR 4 bounded atom model into concrete request
                   documents that make each never-fired rule the
                   first-false attributed column (sound-not-complete;
                   uncoverable rules carry typed reason codes);
- ``pregate``    — the frequency-weighted corpus replay judged against the
                   PR 10/13 GuardThresholds (engine ``--corpus-pregate``);
- ``bisect``     — re-decide the corpus across a published snapshot chain
                   and name the exact generation that introduced each flip
                   (``analysis --corpus-diff``).
"""

from .store import (  # noqa: F401
    CORPUS_FIELDS,
    CORPUS_SCHEMA,
    CORPUS_SUFFIX,
    CorpusFormatError,
    read_corpus,
    read_corpus_file,
    write_corpus,
)
from .distill import distill_records  # noqa: F401
from .synthesize import (  # noqa: F401
    SYNTH_REASONS,
    coverage_report,
    synthesize_rows,
)
from .pregate import (  # noqa: F401
    CORPUS_PREGATE_ANOMALY,
    corpus_preflight,
    replay_corpus,
)
from .bisect import corpus_diff, load_generation_chain  # noqa: F401
