"""Distillation: millions of captured requests → thousands of distinct
decision rows.

Two-stage dedup keeps the fold linear in captured records:

1. cheap grouping by (authconfig, canonical doc JSON) — only DISTINCT
   documents pay any encode work, so a 100k-record capture with a few
   hundred distinct requests costs a few hundred encodes;
2. canonical identity by the PR 3 row key (``batch_row_keys`` over the
   packed operands) against the distilling snapshot — two documents that
   encode to the same device row ARE the same decision, whatever their
   JSON spelling, so they merge into one corpus row whose ``weight``
   carries the combined frequency.

Every distilled row is re-decided through the PR 9 host oracle so the
stored (verdict, firing rule) is attribution evidence, not a trust-the-log
copy.  Counters land in ``auth_server_corpus_records_total`` (distilled /
deduped / dropped-unparseable): a segment-pruning byte budget that eats
coverage shows up here, never silently.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .store import CORPUS_SCHEMA

__all__ = ["distill_records"]

# canonical-key encode chunk: bounds peak batch memory, amortizes the
# per-call numpy setup
_ENCODE_CHUNK = 512


def _doc_json(doc: Any) -> str:
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def _fallback_key(name: str, doc_json: str) -> str:
    return "doc:" + hashlib.sha256(
        (name + "\x00" + doc_json).encode("utf-8")).hexdigest()


def _canonical_keys(oracle, name: str,
                    docs: Sequence[Any]) -> Optional[List[str]]:
    """PR 3 canonical row keys for ``docs`` of one config, or None when
    the snapshot cannot encode them (missing config, encoder error) —
    the caller falls back to the doc-JSON digest."""
    from ..compiler.encode import encode_batch_py
    from ..compiler.pack import batch_row_keys, pack_batch

    try:
        pol, row = oracle._locate(name)
    except Exception:
        return None
    keys: List[str] = []
    try:
        for i in range(0, len(docs), _ENCODE_CHUNK):
            chunk = docs[i:i + _ENCODE_CHUNK]
            enc = encode_batch_py(pol, chunk, [row] * len(chunk))
            db = pack_batch(pol, enc)
            keys.extend(k.hex() for k in batch_row_keys(db, len(chunk)))
    except Exception:
        return None
    return keys


def distill_records(records: Sequence[Dict[str, Any]], snapshot: Any,
                    *, now: Optional[float] = None) -> Dict[str, Any]:
    """Fold captured records into the distilled corpus against one
    reference snapshot (anything :meth:`SnapshotOracle.of` accepts).

    Returns ``{"rows", "counters", "dedup_ratio"}`` — ``rows`` in the
    pinned store.CORPUS_FIELDS shape, ``counters`` with the distilled /
    deduped / dropped_unparseable accounting the metrics mirror."""
    from ..ops.pattern_eval import firing_columns
    from ..replay.replay import SnapshotOracle
    from ..runtime.provenance import rule_label
    from ..utils import metrics as metrics_mod

    oracle = (snapshot if isinstance(snapshot, SnapshotOracle)
              else SnapshotOracle.of(snapshot))
    now = time.time() if now is None else float(now)

    # stage 1: cheap grouping by (authconfig, canonical doc JSON)
    groups: Dict[Tuple[str, str], Dict[str, Any]] = {}
    dropped = 0
    for rec in records:
        name = rec.get("authconfig")
        doc = rec.get("doc")
        if not name or not isinstance(doc, dict):
            dropped += 1
            continue
        try:
            dj = _doc_json(doc)
        except Exception:
            dropped += 1
            continue
        t = rec.get("t")
        t = float(t) if isinstance(t, (int, float)) else now
        g = groups.get((name, dj))
        if g is None:
            groups[(name, dj)] = {
                "doc": doc, "weight": 1, "first": t, "last": t,
                "verdict": rec.get("verdict"),
                "rule_index": rec.get("rule_index", -1),
            }
        else:
            g["weight"] += 1
            g["first"] = min(g["first"], t)
            g["last"] = max(g["last"], t)

    # stage 2: canonical PR 3 row keys per config, merging JSON-distinct
    # documents that encode to the same device row
    by_config: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
    for (name, dj), g in groups.items():
        by_config.setdefault(name, []).append((dj, g))
    fallback_keys = 0
    merged: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for name, items in by_config.items():
        keys = _canonical_keys(oracle, name, [g["doc"] for _, g in items])
        if keys is None:
            keys = [_fallback_key(name, dj) for dj, _ in items]
            fallback_keys += len(items)
        for (dj, g), key in zip(items, keys):
            m = merged.get((name, key))
            if m is None:
                g["row_key"] = key
                merged[(name, key)] = g
            else:
                m["weight"] += g["weight"]
                m["first"] = min(m["first"], g["first"])
                m["last"] = max(m["last"], g["last"])

    # re-decide every distinct row through the host oracle (PR 9
    # attribution — never trust the log's verdict copy); a config the
    # snapshot no longer carries keeps its captured verdict so the row
    # stays bisectable across OLDER generations that did carry it
    rows: List[Dict[str, Any]] = []
    for (name, key), g in sorted(merged.items()):
        verdict, rule_index, rule = g.get("verdict") or "allow", -1, ""
        cap_idx = g.get("rule_index")
        if verdict == "deny" and isinstance(cap_idx, int):
            rule_index = cap_idx
        try:
            rule_res, skipped = oracle.decide(name, g["doc"])
            fire = int(firing_columns(
                np.asarray(rule_res, dtype=bool)[None, :],
                np.asarray(skipped, dtype=bool)[None, :])[0])
            verdict = "allow" if fire < 0 else "deny"
            rule_index = fire
            rule = ("" if fire < 0 else
                    rule_label(fire, oracle.rule_source(name, fire)))
        except Exception:
            pass
        rows.append({
            "schema": CORPUS_SCHEMA,
            "authconfig": name,
            "doc": g["doc"],
            "verdict": verdict,
            "rule_index": rule_index,
            "rule": rule,
            "weight": int(g["weight"]),
            "first_seen": g["first"],
            "last_seen": g["last"],
            "origin": "captured",
            "row_key": g["row_key"],
            "generation": oracle.generation,
        })

    parsed = len(records) - dropped
    counters = {
        "records_in": len(records),
        "distilled": len(rows),
        "deduped": max(0, parsed - len(rows)),
        "dropped_unparseable": dropped,
        "fallback_keys": fallback_keys,
    }
    try:
        metrics_mod.corpus_records.labels("distilled").inc(len(rows))
        metrics_mod.corpus_records.labels("deduped").inc(counters["deduped"])
        metrics_mod.corpus_records.labels("dropped-unparseable").inc(dropped)
    except Exception:
        pass  # metrics are telemetry, never a distillation failure
    return {
        "rows": rows,
        "counters": counters,
        "dedup_ratio": round(parsed / len(rows), 4) if rows else 0.0,
    }
