"""Corpus container: the long-retention decision-corpus store.

Same pickle-free checksummed layout as the PR 8 snapshot container and the
PR 13 capture segment — MAGIC + u64 header length + JSON header +
JSON-lines payload + sha256 trailer — under its own magic and suffix so a
corpus can never be misread as a capture log (and vice versa).  Every
read-side failure is a typed :class:`CorpusFormatError`; a corrupted or
version-skewed blob is rejected before any row is parsed.

Row shape (pinned, tests/test_corpus.py): one distilled-or-synthesized
decision per row —

  schema       CORPUS_SCHEMA stamp (skew is rejected typed)
  authconfig   the deciding config's id
  doc          the full request document (re-decidable forever)
  verdict      "allow" | "deny" under the distilling snapshot
  rule_index   PR 9 firing column (-1 = allow)
  rule         firing rule source label ("" on allow)
  weight       frequency weight: how many captured requests collapsed
               into this row (1 for synthetic rows)
  first_seen   earliest captured timestamp (synthesis time for synthetic)
  last_seen    latest captured timestamp
  origin       "captured" | "synthetic" — the pregate proof that a
               zero-traffic breach was caught WITHOUT live evidence
               hinges on this flag being trustworthy
  row_key      hex canonical identity (PR 3 batch_row_keys digest when
               the distilling snapshot could encode the doc; a doc-JSON
               digest fallback otherwise, prefixed "doc:")
  generation   the snapshot generation the row was decided under
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils.atomicio import atomic_write_bytes

__all__ = ["CORPUS_SCHEMA", "CORPUS_FORMAT_VERSION", "CORPUS_SUFFIX",
           "CORPUS_FIELDS", "CorpusFormatError", "encode_row",
           "write_corpus", "read_corpus_file", "read_corpus"]

CORPUS_SCHEMA = 1
CORPUS_FORMAT_VERSION = 1
MAGIC = b"ATPUCORP1\x00"
_DIGEST_LEN = 32
CORPUS_SUFFIX = ".atpucorp"

CORPUS_FIELDS = ("schema", "authconfig", "doc", "verdict", "rule_index",
                 "rule", "weight", "first_seen", "last_seen", "origin",
                 "row_key", "generation")


class CorpusFormatError(ValueError):
    """The blob is not a valid corpus container (bad magic, truncated,
    checksum mismatch, unsupported container version, or row-schema
    skew).  Read-time only — typed so callers distinguish 'not a corpus'
    from an empty or clean one."""


def encode_row(row: Dict[str, Any]) -> bytes:
    """One row → one canonical JSON line (sort_keys: byte-testable)."""
    return json.dumps(row, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8") + b"\n"


def write_corpus(path: str, rows: Sequence[Dict[str, Any]],
                 meta: Optional[Dict[str, Any]] = None) -> str:
    """Serialize ``rows`` into one checksummed corpus container at
    ``path`` (tmp + atomic rename — a torn write is unreachable)."""
    payload = b"".join(encode_row(r) for r in rows)
    header = {
        "version": CORPUS_FORMAT_VERSION,
        "schema": CORPUS_SCHEMA,
        "count": len(rows),
        "created_unix": time.time(),
        "meta": meta or {},
    }
    hb = json.dumps(header, sort_keys=True,
                    separators=(",", ":")).encode("utf-8")
    body = MAGIC + struct.pack("<Q", len(hb)) + hb + payload
    blob = body + hashlib.sha256(body).digest()
    return atomic_write_bytes(path, blob, artifact="corpus")


def read_corpus_file(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """One corpus file → (header, rows).  Verifies magic + sha256 +
    container version + row schema BEFORE parsing any row."""
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(MAGIC) + 8 + _DIGEST_LEN:
        raise CorpusFormatError(f"corpus container truncated: {path}")
    if blob[:len(MAGIC)] != MAGIC:
        raise CorpusFormatError(f"bad corpus magic: {path}")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if hashlib.sha256(body).digest() != digest:
        raise CorpusFormatError(
            f"corpus checksum mismatch (corrupt or tampered): {path}")
    (hlen,) = struct.unpack_from("<Q", blob, len(MAGIC))
    start = len(MAGIC) + 8
    if start + hlen > len(body):
        raise CorpusFormatError(f"corpus header overruns the blob: {path}")
    try:
        header = json.loads(body[start:start + hlen].decode("utf-8"))
    except Exception as e:
        raise CorpusFormatError(f"unparseable corpus header ({e}): {path}")
    if header.get("version") != CORPUS_FORMAT_VERSION:
        raise CorpusFormatError(
            f"unsupported corpus container version "
            f"{header.get('version')!r} (reader supports "
            f"{CORPUS_FORMAT_VERSION}): {path}")
    if header.get("schema") != CORPUS_SCHEMA:
        raise CorpusFormatError(
            f"corpus row schema skew: container {header.get('schema')!r} "
            f"!= reader {CORPUS_SCHEMA} — refusing to misparse: {path}")
    rows: List[Dict[str, Any]] = []
    for line in body[start + hlen:].splitlines():
        if not line:
            continue
        try:
            rows.append(json.loads(line.decode("utf-8")))
        except Exception as e:
            raise CorpusFormatError(f"malformed corpus row ({e}): {path}")
    return header, rows


def read_corpus(source: str) -> List[Dict[str, Any]]:
    """A corpus file OR a directory of ``*.atpucorp`` containers → every
    row, oldest container first (names sort chronologically)."""
    if os.path.isdir(source):
        names = sorted(n for n in os.listdir(source)
                       if n.endswith(CORPUS_SUFFIX))
        if not names:
            raise CorpusFormatError(
                f"no *{CORPUS_SUFFIX} containers in {source}")
        out: List[Dict[str, Any]] = []
        for n in names:
            out.extend(read_corpus_file(os.path.join(source, n))[1])
        return out
    return read_corpus_file(source)[1]
