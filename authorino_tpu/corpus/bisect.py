"""History bisect (ISSUE 19 layer 4): which generation introduced a flip?

``analysis --corpus-diff`` re-decides the whole corpus across the PR 8
published-snapshot chain (the publish directory's
``snapshot-{generation:012d}.atpusnap`` blobs — names sort in generation
order, so the chain IS the bounded history the publisher retains) and, for
every row whose verdict changed anywhere along the chain, names the exact
generation that introduced the flip, with PR 9 firing attribution on both
sides.  A row may flip more than once (edit → revert → re-edit); every
transition is reported, oldest first, never just the net diff.
"""

from __future__ import annotations

import os
import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["load_generation_chain", "corpus_diff"]

_BLOB_RE = re.compile(r"^snapshot-(\d{12})\.atpusnap$")


def load_generation_chain(publish_dir: str) -> List[Any]:
    """Load every published snapshot blob in ``publish_dir``, oldest
    generation first.  Blobs that fail to load are skipped (a pruned or
    corrupt blob must not hide the diffable rest of the chain) — the
    caller sees the surviving generations only."""
    from ..snapshots.distribution import load_snapshot_blob

    chain: List[Any] = []
    names = []
    for n in os.listdir(publish_dir):
        m = _BLOB_RE.match(n)
        if m:
            names.append((int(m.group(1)), n))
    for _gen, n in sorted(names):
        try:
            with open(os.path.join(publish_dir, n), "rb") as f:
                chain.append(load_snapshot_blob(f.read()))
        except Exception:
            continue
    return chain


def _decide_all(oracle: Any, rows: Sequence[Dict[str, Any]],
                ) -> List[Optional[int]]:
    """Firing column per row under one oracle (-1 allow, None when the
    config is missing from / errors under this generation)."""
    from ..ops.pattern_eval import firing_columns

    out: List[Optional[int]] = []
    for row in rows:
        name = row.get("authconfig")
        doc = row.get("doc")
        if not name or doc is None or not oracle.has(name):
            out.append(None)
            continue
        try:
            rr, sk = oracle.decide(name, doc)
            out.append(int(firing_columns(
                np.asarray(rr, dtype=bool)[None, :],
                np.asarray(sk, dtype=bool)[None, :])[0]))
        except Exception:
            out.append(None)
    return out


def corpus_diff(chain: Sequence[Any], rows: Sequence[Dict[str, Any]],
                max_examples: int = 5) -> Dict[str, Any]:
    """Re-decide ``rows`` under every generation in ``chain`` (oldest
    first; anything :meth:`SnapshotOracle.of` accepts) and attribute each
    verdict flip to the exact generation that introduced it.

    Returns ``{"generations", "rows", "flips": [...], "by_generation"}`` —
    each flip entry names the introducing generation, the direction, the
    firing (authconfig, rule) on the deny side, weighted row counts, and
    up to ``max_examples`` row keys as evidence."""
    from ..replay.replay import SnapshotOracle
    from ..runtime.provenance import rule_label

    t0 = time.monotonic()
    oracles = [(o if isinstance(o, SnapshotOracle) else SnapshotOracle.of(o))
               for o in chain]
    gens = [o.generation for o in oracles]
    fires = [_decide_all(o, rows) for o in oracles]

    # group transitions by (introducing generation, config, direction,
    # deny-side firing column) — the bisect verdict the CLI prints
    groups: Dict[Tuple[int, str, str, int], Dict[str, Any]] = {}
    flipped_rows = 0
    for ri, row in enumerate(rows):
        name = row.get("authconfig") or ""
        w = max(1, int(row.get("weight", 1)))
        prev_fire: Optional[int] = None
        prev_gi: Optional[int] = None
        row_flipped = False
        for gi in range(len(oracles)):
            f = fires[gi][ri]
            if f is None:
                continue             # config absent here: not a verdict
            if prev_fire is not None:
                old_allow, new_allow = prev_fire < 0, f < 0
                if old_allow != new_allow:
                    row_flipped = True
                    if new_allow:
                        direction, col, side = ("newly-allowed", prev_fire,
                                                oracles[prev_gi])
                    else:
                        direction, col, side = "newly-denied", f, oracles[gi]
                    key = (gens[gi], name, direction, col)
                    g = groups.get(key)
                    if g is None:
                        g = groups[key] = {
                            "generation": gens[gi],
                            "from_generation": gens[prev_gi],
                            "authconfig": name,
                            "direction": direction,
                            "rule_index": col,
                            "rule": rule_label(
                                col, side.rule_source(name, col)),
                            "count": 0,
                            "rows": 0,
                            "origins": [],
                            "examples": [],
                        }
                    g["count"] += w
                    g["rows"] += 1
                    org = row.get("origin")
                    if org and org not in g["origins"]:
                        g["origins"].append(org)
                    if len(g["examples"]) < max_examples:
                        g["examples"].append(row.get("row_key") or "")
            prev_fire, prev_gi = f, gi
        flipped_rows += int(row_flipped)

    flips = sorted(groups.values(),
                   key=lambda g: (g["generation"], -g["count"]))
    by_generation: Dict[int, int] = {}
    for g in flips:
        by_generation[g["generation"]] = (
            by_generation.get(g["generation"], 0) + g["count"])
    return {
        "generations": gens,
        "rows": len(rows),
        "flipped_rows": flipped_rows,
        "flips": flips,
        "by_generation": {str(k): v
                          for k, v in sorted(by_generation.items())},
        "elapsed_ms": round((time.monotonic() - t0) * 1e3, 3),
    }
