"""Corpus pregate (ISSUE 19 layer 3): judge a reconcile on the
frequency-weighted decision corpus before the canary.

PR 13's replay pregate is ring-bounded — it can only re-test what recent
traffic exercised.  The corpus pregate replays the long-retention corpus
instead: every distinct decision ever captured (weighted by how often it
occurred) PLUS every synthesized witness row for never-fired rules.  Flip
rates are **weight-weighted** — a flip on a row 40k requests collapsed
into counts as 40k flips, a flip on a synthetic witness counts as 1 — and
the weighted report is judged by the SAME :func:`pregate_check` the PR 13
replay pregate uses (weights are integers, so the canary guard arithmetic
applies unchanged).  A breaching edit to a zero-traffic rule is caught by
its synthetic-origin row with zero live exposure; the report's
``origins`` block proves which kind of evidence fired.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["CORPUS_PREGATE_ANOMALY", "replay_corpus", "corpus_preflight"]

# flight-recorder anomaly kind for a corpus-pregate breach (registered in
# runtime/flight_recorder.py ANOMALY_KINDS)
CORPUS_PREGATE_ANOMALY = "corpus-pregate-breach"


def replay_corpus(old: Any, new: Any, rows: Sequence[Dict[str, Any]],
                  *, time_budget_s: Optional[float] = None,
                  max_examples: int = 3) -> Dict[str, Any]:
    """Re-decide every corpus row through BOTH snapshots' host oracles and
    diff the verdicts, **weighted by row frequency**.  The report is
    shaped exactly like :func:`replay.replay_records`' (``replayed`` /
    ``flips`` / ``per_config`` carry weighted integer counts) so
    :func:`pregate_check` judges it unchanged, plus an ``origins`` block
    splitting flips by captured/synthetic evidence."""
    from ..ops.pattern_eval import firing_columns
    from ..replay.replay import REPLAY_SCHEMA, SnapshotOracle, replay_platform
    from ..runtime.provenance import rule_label

    old_o = old if isinstance(old, SnapshotOracle) else SnapshotOracle.of(old)
    new_o = new if isinstance(new, SnapshotOracle) else SnapshotOracle.of(new)
    t0 = time.monotonic()

    kept: List[Dict[str, Any]] = []
    o_rules: List[np.ndarray] = []
    o_skips: List[np.ndarray] = []
    n_rules: List[np.ndarray] = []
    n_skips: List[np.ndarray] = []
    errors = 0
    missing_old: set = set()
    missing_new: set = set()
    missing_n = 0
    truncated = 0

    for i, row in enumerate(rows):
        if time_budget_s is not None and (i & 63) == 0 \
                and time.monotonic() - t0 > time_budget_s:
            truncated = len(rows) - i
            break
        name = row.get("authconfig")
        doc = row.get("doc")
        if not name or doc is None:
            errors += 1
            continue
        if not old_o.has(name):
            missing_old.add(name)
            missing_n += 1
            continue
        if not new_o.has(name):
            missing_new.add(name)
            missing_n += 1
            continue
        try:
            ro, so = old_o.decide(name, doc)
            rn, sn = new_o.decide(name, doc)
        except Exception:
            errors += 1
            continue
        kept.append(row)
        o_rules.append(np.asarray(ro, dtype=bool))
        o_skips.append(np.asarray(so, dtype=bool))
        n_rules.append(np.asarray(rn, dtype=bool))
        n_skips.append(np.asarray(sn, dtype=bool))

    if kept:
        fire_old = firing_columns(np.stack(o_rules), np.stack(o_skips))
        fire_new = firing_columns(np.stack(n_rules), np.stack(n_skips))
    else:
        fire_old = fire_new = np.zeros(0, dtype=np.int32)

    per_config: Dict[str, Dict[str, int]] = {}
    groups: Dict[Tuple[str, str, int], Dict[str, Any]] = {}
    origins = {
        "captured": {"rows": 0, "weight": 0, "flips": 0},
        "synthetic": {"rows": 0, "weight": 0, "flips": 0},
    }
    newly_denied = newly_allowed = 0
    replayed_weight = 0
    for row, fo, fn in zip(kept, fire_old, fire_new):
        name = row["authconfig"]
        w = max(1, int(row.get("weight", 1)))
        org = row.get("origin")
        ob = origins.setdefault(
            org if org in origins else "captured",
            {"rows": 0, "weight": 0, "flips": 0})
        ob["rows"] += 1
        ob["weight"] += w
        replayed_weight += w
        pc = per_config.setdefault(name, {
            "replayed": 0, "newly_denied": 0, "newly_allowed": 0,
            "old_allows": 0, "new_allows": 0})
        pc["replayed"] += w
        old_allow, new_allow = int(fo) < 0, int(fn) < 0
        pc["old_allows"] += w * int(old_allow)
        pc["new_allows"] += w * int(new_allow)
        if old_allow == new_allow:
            continue
        ob["flips"] += w
        if new_allow:
            direction, col, side = "newly-allowed", int(fo), old_o
            newly_allowed += w
            pc["newly_allowed"] += w
        else:
            direction, col, side = "newly-denied", int(fn), new_o
            newly_denied += w
            pc["newly_denied"] += w
        key = (name, direction, col)
        g = groups.get(key)
        if g is None:
            g = groups[key] = {
                "authconfig": name,
                "direction": direction,
                "rule_index": col,
                "rule": rule_label(col, side.rule_source(name, col)),
                "count": 0,
                "rows": 0,
                "origins": [],
                "examples": [],
            }
        g["count"] += w
        g["rows"] += 1
        if org and org not in g["origins"]:
            g["origins"].append(org)
        if len(g["examples"]) < max_examples:
            g["examples"].append(row.get("row_key") or "")

    by_rule = sorted(groups.values(), key=lambda g: -g["count"])
    return {
        "schema": REPLAY_SCHEMA,
        "platform": replay_platform(),
        "load_model": "corpus",
        "replayed": replayed_weight,
        "replayed_rows": len(kept),
        "flips": {
            "newly_denied": newly_denied,
            "newly_allowed": newly_allowed,
            "total": newly_denied + newly_allowed,
        },
        "flip_rate": round((newly_denied + newly_allowed) / replayed_weight,
                           6) if replayed_weight else 0.0,
        "by_rule": by_rule,
        "per_config": per_config,
        "origins": origins,
        "skipped": {
            "missing_config": missing_n,
            "configs_missing_old": sorted(missing_old)[:32],
            "configs_missing_new": sorted(missing_new)[:32],
            "errors": errors,
            "truncated": truncated,
        },
        "old_generation": old_o.generation,
        "new_generation": new_o.generation,
        "elapsed_ms": round((time.monotonic() - t0) * 1e3, 3),
        "evaluators": {"old": old_o.n_evaluators(),
                       "new": new_o.n_evaluators()},
    }


def corpus_preflight(baseline: Any, candidate: Any,
                     rows: Sequence[Dict[str, Any]], thresholds: Any = None,
                     changed: Optional[Iterable[str]] = None,
                     time_budget_s: Optional[float] = None,
                     ) -> Dict[str, Any]:
    """One-call corpus preflight: weighted-replay ``rows`` old-vs-new and
    judge the diff with the PR 13 :func:`pregate_check` (unchanged — the
    weighted counts are integers).  Returns ``{"report", "breach"}``; the
    engine's ``--corpus-pregate`` and the analysis CLI share this seam."""
    from ..replay.pregate import pregate_check

    report = replay_corpus(baseline, candidate, rows,
                           time_budget_s=time_budget_s)
    return {"report": report,
            "breach": pregate_check(report, thresholds, changed=changed)}
