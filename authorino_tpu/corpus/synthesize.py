"""Coverage analysis + truth-table row synthesis (ISSUE 19 layer 2).

Coverage: the corpus's fired set — (authconfig, firing evaluator column)
pairs its rows attribute under PR 9 semantics — against every registered
rule column, cross-referenced with the PR 4 static findings (a
constant-allow rule CANNOT fire) so the unexercised set separates
"needs a synthesized witness" from "statically impossible".

Synthesis inverts the PR 4 bounded atom model: for a target evaluator
``e`` of config ``g`` it enumerates the 2^n truth assignments over the
union atom support of evaluators 0..e (``policy_analysis._Circuit``, the
Cedar-style bounded symbolic evaluation), keeps the assignments where
evaluators 0..e-1 contribute true and e's condition holds while its rule
fails — exactly the assignments that make e the *first-false* attributed
column — and materializes one into a concrete request document:

- equality atoms     → the interned constant string (or a fresh unseen
                       string to falsify every value atom on the attr);
- membership atoms   → a list of exactly the desired member constants;
- regex atoms        → accept/reject witnesses from the PR 6 DFA witness
                       machinery (``_table_witnesses``) when the leaf
                       compiled to the device lane, pattern-derived
                       candidates otherwise;
- numeric atoms      → boundary values of the satisfying integer interval
                       (the PR 14 int lanes), or a non-integer string to
                       falsify all four comparators at once;
- relation atoms     → a closure-table entity whose group memberships
                       match the assignment (an unknown entity falsifies
                       every group atom).

Sound, not complete: every synthesized document is VERIFIED through the
PR 9 host oracle (``host_results`` + ``firing_columns``) before it is
admitted — a doc that does not make the target the first-false column is
discarded.  Rules no assignment or materialization can cover are reported
with a typed reason code from :data:`SYNTH_REASONS`, never silently
skipped.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .store import CORPUS_SCHEMA

__all__ = ["SYNTH_REASONS", "coverage_report", "synthesize_rows",
           "augment_corpus"]

# typed uncoverability reason codes (docs/policy_ci.md "Synthesis reason
# codes") — the full vocabulary, pinned so reports are machine-stable
SYNTH_REASONS = (
    "atom-budget-exceeded",    # union support of evaluators 0..e > MAX_ATOMS
    "statically-dead",         # PR 4 already proved the column cannot fire
    "unsatisfiable",           # no assignment makes e the first-false column
    "unsupported-selector",    # a support attr's selector is not a plain
                               # dot-path this materializer can set
    "selector-conflict",       # two support selectors collide (one a prefix
                               # of another) so no document carries both
    "opaque-cpu-tree",         # assignments hinge on OP_TREE_CPU atoms the
                               # materializer cannot steer
    "materialization-failed",  # candidates existed but none verified
)

# bounded search: how many candidate assignments to materialize+verify
# before giving up on a target column
_MAX_TRIES = 24


# ---------------------------------------------------------------------------
# coverage
# ---------------------------------------------------------------------------


def coverage_report(policy: Any, rows: Sequence[Dict[str, Any]],
                    analysis: Optional[Dict[str, Any]] = None,
                    lowerability: Optional[Dict[str, Any]] = None,
                    ) -> Dict[str, Any]:
    """Per-(config, rule, evaluator-column) exercised/unexercised coverage
    of ``rows`` over ``policy``, cross-referenced against the PR 4
    findings (``analysis`` = the /debug/vars policy_analysis block) and
    the PR 6 lowerability report (per-config lane + reasons)."""
    fired: Dict[str, set] = {}
    allow_seen: Dict[str, int] = {}
    for r in rows:
        name = r.get("authconfig")
        if not name:
            continue
        if r.get("verdict") == "deny":
            fired.setdefault(name, set()).add(int(r.get("rule_index", -1)))
        else:
            allow_seen[name] = allow_seen.get(name, 0) + 1
    static_by_rule: Dict[Tuple[str, int], List[str]] = {}
    for f in (analysis or {}).get("findings", []):
        kind = f.get("kind", "")
        if kind in ("constant-allow", "shadowed-rule"):
            d = f.get("detail") or {}
            ev = d.get("evaluator")
            if ev is not None:
                static_by_rule.setdefault(
                    (str(d.get("config", "")), int(ev)), []).append(kind)
    lower_cfg = (lowerability or {}).get("configs") or {}
    sources = policy.rule_sources()
    configs: Dict[str, Any] = {}
    total = exercised = 0
    for name, g in sorted(policy.config_ids.items()):
        n_real = len(policy.config_exprs[g])
        cols = []
        cfg_fired = fired.get(name, set())
        for e in range(n_real):
            total += 1
            hit = e in cfg_fired
            exercised += int(hit)
            cols.append({
                "evaluator": e,
                "rule": sources[g][e] if e < len(sources[g]) else "",
                "exercised": hit,
                "static_findings": static_by_rule.get((name, e), []),
            })
        entry: Dict[str, Any] = {
            "evaluators": n_real,
            "columns": cols,
            "unexercised": [c["evaluator"] for c in cols
                            if not c["exercised"]],
            "allow_rows": allow_seen.get(name, 0),
        }
        li = lower_cfg.get(name)
        if li:
            entry["lane"] = li.get("lane")
            entry["lowerability_reasons"] = li.get("reasons", [])
        configs[name] = entry
    return {
        "configs": configs,
        "columns_total": total,
        "columns_exercised": exercised,
        "fraction": round(exercised / total, 4) if total else 1.0,
    }


# ---------------------------------------------------------------------------
# materialization helpers
# ---------------------------------------------------------------------------

_PLAIN_SEG = __import__("re").compile(r"^[A-Za-z_][A-Za-z0-9_\-]*$")

# a string no corpus interner contains (NUL is unreachable through the
# JSON frontends) — falsifies every value/membership atom on its attr
_UNSEEN = "\x00unseen"


def _set_path(doc: Dict[str, Any], selector: str, value: Any) -> str:
    """Set ``value`` at the dot-path ``selector`` inside ``doc``.  Returns
    "" on success or a SYNTH_REASONS code on failure."""
    segs = selector.split(".")
    if not segs or any(not _PLAIN_SEG.match(s) for s in segs):
        return "unsupported-selector"
    cur = doc
    for s in segs[:-1]:
        nxt = cur.get(s)
        if nxt is None:
            nxt = cur[s] = {}
        elif not isinstance(nxt, dict):
            return "selector-conflict"
        cur = nxt
    leaf = segs[-1]
    if isinstance(cur.get(leaf), dict):
        return "selector-conflict"
    cur[leaf] = value
    return ""


class _AttrPlan:
    """Accumulated per-attr constraints for one candidate assignment."""

    def __init__(self) -> None:
        self.eq_true: List[int] = []
        self.eq_false: List[int] = []
        self.mem_true: List[int] = []
        self.mem_false: List[int] = []
        self.rx: List[Tuple[Any, bool, Optional[int]]] = []  # (rx, want, leaf)
        self.num: List[Tuple[int, int, bool]] = []           # (op, const, want)
        self.rel: List[Tuple[str, str, bool]] = []           # (digest, grp, want)


def _regex_candidates(policy: Any, rx: Any, leaf: Optional[int],
                      want: bool) -> List[str]:
    """Witness candidates for one regex atom: DFA-derived strings when the
    leaf compiled to the device lane (the PR 6 witness machinery —
    reaching + accepting/rejecting extensions per state), pattern-derived
    heuristics otherwise.  Candidates are CHECKED by the caller with
    ``rx.search``; wrong guesses cost a try, never soundness."""
    from ..compiler.compile import OP_REGEX_DFA

    out: List[str] = []
    if leaf is not None and int(policy.leaf_op[leaf]) == OP_REGEX_DFA \
            and policy.dfa_tables is not None and policy.dfa_tables.size:
        from ..analysis.translation_validate import _table_witnesses

        row = int(policy.leaf_dfa_row[leaf])
        if 0 <= row < policy.dfa_table_of_row.shape[0]:
            t = int(policy.dfa_table_of_row[row])
            wits, _ = _table_witnesses(policy.dfa_tables[t],
                                       policy.dfa_accept[t])
            for w in wits:
                try:
                    out.append(w.decode("utf-8"))
                except UnicodeDecodeError:
                    continue
    # pattern-derived heuristics: strip anchors, resolve the common
    # wildcard tails — cheap guesses the rx.search filter vets
    pat = rx.pattern
    lit = pat.strip("^$")
    for repl in ("a", "x", "0", ""):
        out.append(lit.replace(".*", repl).replace(".+", repl or "a")
                   .replace("\\", ""))
    out += ["", "a", "zz", "\x01\x01", "no-match-\x00"]
    return out


def _value_for_attr(policy: Any, plan: _AttrPlan) -> Tuple[bool, Any]:
    """(ok, value) satisfying every constraint in ``plan`` — best-effort:
    the host-oracle verification is the soundness gate, this only has to
    be right often enough that a few tries converge."""
    rev = policy.interner.reverse()

    def _check_str(v: str) -> bool:
        from ..expressions.ast import parse_int_value

        vid = policy.interner.lookup(v)
        for c in plan.eq_true:
            if vid != c:
                return False
        for c in plan.eq_false:
            if vid == c:
                return False
        for c in plan.mem_true:          # scalar attr: members == [v]
            if vid != c:
                return False
        for c in plan.mem_false:
            if vid == c:
                return False
        for rx, want, _leaf in plan.rx:
            if bool(rx.search(v)) != want:
                return False
        iv = parse_int_value(v)
        for op, c, want in plan.num:
            if _num_truth(op, iv, c) != want:
                return False
        return True

    if len(set(plan.eq_true)) > 1:
        return False, None               # one value equals at most one const
    if plan.eq_true:
        v = rev.get(plan.eq_true[0])
        if v is None:
            return False, None
        ok = _check_str(v) and not plan.rel
        return ok, v
    if plan.mem_true:
        # a list attr: exactly the desired member constants, none of the
        # undesired ones (distinct interned ids guarantee exclusion)
        if set(plan.mem_true) & set(plan.mem_false):
            return False, None
        vals = [rev.get(c) for c in sorted(set(plan.mem_true))]
        if any(v is None for v in vals):
            return False, None
        # numeric/regex/eq atoms on a list attr see the RENDERED value;
        # desired-true ones are out of this materializer's reach
        if any(want for _, want, _ in plan.rx) \
                or any(want for *_, want in [(0, 0, w) for _, _, w in plan.num] if want):
            return False, None
        return (not plan.rel), vals
    if plan.rel:
        return _relation_entity(policy, plan, _check_str)
    if plan.num:
        ok, v = _numeric_value(plan)
        if ok and _check_str(v):
            return True, v
        return False, None
    if plan.rx:
        want_order = sorted(plan.rx, key=lambda t: not t[1])
        for rx, want, leaf in want_order:
            for cand in _regex_candidates(policy, rx, leaf, want):
                if len(cand) <= 256 and _check_str(cand):
                    return True, cand
        return False, None
    # only negative value/membership constraints: a fresh unseen string
    if _check_str(_UNSEEN):
        return True, _UNSEEN
    return False, None


def _num_truth(op: int, value: Optional[int], const: int) -> bool:
    from ..compiler.compile import OP_NUM_GE, OP_NUM_GT, OP_NUM_LE, OP_NUM_LT

    if value is None:
        return False                     # non-integer: all four comparators
    return {OP_NUM_GT: value > const, OP_NUM_GE: value >= const,
            OP_NUM_LT: value < const, OP_NUM_LE: value <= const}[op]


def _numeric_value(plan: _AttrPlan) -> Tuple[bool, str]:
    """Boundary value of the satisfying integer interval (PR 14 int
    lanes), or a non-integer witness when every comparator must fail."""
    from ..compiler.compile import OP_NUM_GE, OP_NUM_GT, OP_NUM_LE, OP_NUM_LT

    LO, HI = -(2 ** 40), 2 ** 40
    lo, hi = LO, HI
    for op, c, want in plan.num:
        if want:
            if op == OP_NUM_GT:
                lo = max(lo, c + 1)
            elif op == OP_NUM_GE:
                lo = max(lo, c)
            elif op == OP_NUM_LT:
                hi = min(hi, c - 1)
            elif op == OP_NUM_LE:
                hi = min(hi, c)
        else:
            if op == OP_NUM_GT:
                hi = min(hi, c)
            elif op == OP_NUM_GE:
                hi = min(hi, c - 1)
            elif op == OP_NUM_LT:
                lo = max(lo, c)
            elif op == OP_NUM_LE:
                lo = max(lo, c + 1)
    if lo <= hi:
        # boundary-first: the tightest bound is the value most likely to
        # catch an off-by-one in a comparator lowering
        v = lo if lo != LO else (hi if hi != HI else 0)
        return True, str(v)
    if all(not want for *_, want in plan.num):
        return True, "not-an-int"
    return False, ""


def _relation_entity(policy: Any, plan: _AttrPlan, check_str) -> Tuple[bool, Any]:
    """An entity from the closure tables whose group memberships match the
    assignment (closure digests key which relation instance each atom
    queries); an unknown entity falsifies every group atom at once."""
    inst_of = {rel.digest: rel for rel in (policy.rel_instances or [])}
    cands: List[str] = []
    for digest, _g, _w in plan.rel:
        rel = inst_of.get(digest)
        if rel is not None:
            cands.extend(rel.entities)
    cands.append(_UNSEEN)
    for ent in cands:
        ok = True
        for digest, group, want in plan.rel:
            rel = inst_of.get(digest)
            got = bool(rel is not None and rel.contains(ent, group))
            if got != want:
                ok = False
                break
        if ok and check_str(ent):
            return True, ent
    return False, None


def _materialize(policy: Any, atoms: Sequence[tuple],
                 truth: Sequence[bool]) -> Tuple[Optional[Dict[str, Any]], str]:
    """One assignment → a request document, or (None, reason code)."""
    plans: Dict[int, _AttrPlan] = {}
    has_opaque = False
    for atom, want in zip(atoms, truth):
        kind = atom[0]
        if kind == "t":
            has_opaque = True            # uncontrollable: verification decides
            continue
        if kind == "v":
            _, attr, const = atom
            p = plans.setdefault(attr, _AttrPlan())
            (p.eq_true if want else p.eq_false).append(const)
        elif kind == "m":
            _, attr, const = atom
            p = plans.setdefault(attr, _AttrPlan())
            (p.mem_true if want else p.mem_false).append(const)
        elif kind == "r":
            _, attr, pat = atom
            leaf = rx = None
            for i, lrx in enumerate(policy.leaf_regex):
                if lrx is not None and int(policy.leaf_attr[i]) == attr \
                        and lrx.pattern == pat:
                    leaf, rx = i, lrx
                    break
            if rx is None:
                return None, "materialization-failed"
            plans.setdefault(attr, _AttrPlan()).rx.append((rx, want, leaf))
        elif kind == "n":
            _, op, attr, const = atom
            plans.setdefault(attr, _AttrPlan()).num.append((op, const, want))
        elif kind == "G":
            _, attr, digest, group = atom
            plans.setdefault(attr, _AttrPlan()).rel.append(
                (digest, group, want))
    doc: Dict[str, Any] = {}
    for attr, plan in sorted(plans.items()):
        ok, value = _value_for_attr(policy, plan)
        if not ok:
            return None, "materialization-failed"
        err = _set_path(doc, policy.attr_selectors[attr], value)
        if err:
            return None, err
    if has_opaque:
        return doc, "opaque-cpu-tree"    # best-effort doc; caller verifies
    return doc, ""


# ---------------------------------------------------------------------------
# synthesis driver
# ---------------------------------------------------------------------------


def synthesize_rows(policy: Any,
                    targets: Optional[Iterable[Tuple[str, int]]] = None,
                    analysis: Optional[Dict[str, Any]] = None,
                    now: Optional[float] = None,
                    max_tries: int = _MAX_TRIES,
                    ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Synthesize one verified corpus row per target (config, evaluator)
    column, making that column the first-false firing rule.  A target
    evaluator of ``-1`` requests an **allow witness** — a document every
    evaluator of the config passes (verdict allow): the row a future
    constant-deny edit to ANY of the config's rules must flip, which is
    what makes the corpus pregate's zero-traffic coverage claim real.
    Default targets: every registered column plus one allow witness per
    config.  Returns (rows, report); every uncovered target carries a
    typed reason from :data:`SYNTH_REASONS`."""
    from ..analysis.policy_analysis import MAX_ATOMS, _Circuit
    from ..models.policy_model import host_results
    from ..ops.pattern_eval import firing_columns
    from ..runtime.provenance import rule_label
    from ..utils import metrics as metrics_mod

    now = time.time() if now is None else float(now)
    circ = _Circuit(policy)
    smemo: Dict[int, Any] = {}
    static_by_rule: Dict[Tuple[str, int], List[str]] = {}
    for f in (analysis or {}).get("findings", []):
        if f.get("kind") in ("constant-allow", "shadowed-rule",
                             "constant-deny"):
            d = f.get("detail") or {}
            ev = d.get("evaluator")
            if ev is not None:
                static_by_rule.setdefault(
                    (str(d.get("config", "")), int(ev)),
                    []).append(f["kind"])
    if targets is None:
        targets = [(name, e) for name, g in sorted(policy.config_ids.items())
                   for e in range(-1, len(policy.config_exprs[g]))]
    targets = list(targets)
    rows: List[Dict[str, Any]] = []
    uncoverable: List[Dict[str, Any]] = []
    reasons: Dict[str, int] = {}
    sources = policy.rule_sources()

    def _fail(name: str, e: int, reason: str) -> None:
        reasons[reason] = reasons.get(reason, 0) + 1
        uncoverable.append({"config": name, "evaluator": e,
                            "reason": reason})
        try:
            metrics_mod.corpus_synth.labels(reason).inc()
        except Exception:
            pass

    for name, e in targets:
        g = policy.config_ids.get(name)
        if g is None or e >= len(policy.config_exprs[g]):
            _fail(name, e, "unsatisfiable")
            continue
        n_real = len(policy.config_exprs[g])
        # atom union over evaluators 0..e (all of them for an allow
        # witness): the prefix must contribute true for e to be the
        # FIRST false column
        last = n_real - 1 if e < 0 else e
        atoms: set = set()
        for k in range(last + 1):
            atoms |= circ.support(int(policy.eval_rule[g, k]), smemo)
            if bool(policy.eval_has_cond[g, k]):
                atoms |= circ.support(int(policy.eval_cond[g, k]), smemo)
        atoms = sorted(atoms)
        if len(atoms) > MAX_ATOMS:
            _fail(name, e, "atom-budget-exceeded")
            continue
        n = 1 << len(atoms)
        idx = np.arange(n)
        cols = {a: (idx >> i) & 1 != 0 for i, a in enumerate(atoms)}
        vmemo: Dict[int, np.ndarray] = {}
        sel = np.ones(n, dtype=bool)
        for k in range(last + 1 if e < 0 else e):
            contrib = circ.eval_over(int(policy.eval_rule[g, k]), cols, n,
                                     vmemo)
            if bool(policy.eval_has_cond[g, k]):
                contrib = contrib | ~circ.eval_over(
                    int(policy.eval_cond[g, k]), cols, n, vmemo)
            sel &= contrib
        if e >= 0:
            sel &= ~circ.eval_over(int(policy.eval_rule[g, e]), cols, n,
                                   vmemo)
            if bool(policy.eval_has_cond[g, e]):
                sel &= circ.eval_over(int(policy.eval_cond[g, e]), cols, n,
                                      vmemo)
        cand = np.nonzero(sel)[0]
        if cand.size == 0:
            static = static_by_rule.get((name, e), [])
            _fail(name, e,
                  "statically-dead" if static else "unsatisfiable")
            continue
        # simplest assignments first (fewest true atoms → smallest docs)
        order = sorted(cand.tolist(), key=lambda i: bin(i).count("1"))
        verified = None
        last_reason = "materialization-failed"
        saw_opaque = False
        for i in order[:max_tries]:
            truth = [bool((i >> b) & 1) for b in range(len(atoms))]
            doc, err = _materialize(policy, atoms, truth)
            if doc is None:
                last_reason = err
                continue
            if err == "opaque-cpu-tree":
                saw_opaque = True
            try:
                _own, rule_res, skipped = host_results(policy, doc, g)
                fire = int(firing_columns(rule_res[None, :],
                                          skipped[None, :])[0])
            except Exception:
                continue
            if fire == e:
                verified = doc
                break
        if verified is None:
            _fail(name, e,
                  "opaque-cpu-tree" if saw_opaque else last_reason)
            continue
        reasons["ok"] = reasons.get("ok", 0) + 1
        try:
            metrics_mod.corpus_synth.labels("ok").inc()
        except Exception:
            pass
        rows.append({
            "schema": CORPUS_SCHEMA,
            "authconfig": name,
            "doc": verified,
            "verdict": "allow" if e < 0 else "deny",
            "rule_index": e,
            "rule": "" if e < 0 else rule_label(
                e, sources[g][e] if e < len(sources[g]) else ""),
            "weight": 1,
            "first_seen": now,
            "last_seen": now,
            "origin": "synthetic",
            "row_key": "",               # stamped by callers that encode
            "generation": None,
        })
    return rows, {
        "targets": len(targets),
        "synthesized": len(rows),
        "uncoverable": uncoverable,
        "reasons": reasons,
    }


def augment_corpus(policy: Any, rows: Sequence[Dict[str, Any]],
                   analysis: Optional[Dict[str, Any]] = None,
                   lowerability: Optional[Dict[str, Any]] = None,
                   now: Optional[float] = None) -> Dict[str, Any]:
    """One-call coverage close: measure coverage of ``rows``, synthesize a
    verified witness row for every unexercised column, and report
    coverage before/after.  The engine pregate, the analysis CLI, and
    bench's corpus block all share this seam."""
    before = coverage_report(policy, rows, analysis=analysis,
                             lowerability=lowerability)
    # every unexercised deny column, plus an allow witness for configs the
    # corpus never saw allow — the row a constant-deny edit must flip
    targets = [(name, e) for name, c in before["configs"].items()
               for e in c["unexercised"]]
    targets += [(name, -1) for name, c in before["configs"].items()
                if not c["allow_rows"]]
    synth, rep = synthesize_rows(policy, targets=targets,
                                 analysis=analysis, now=now)
    after = coverage_report(policy, list(rows) + synth, analysis=analysis,
                            lowerability=lowerability)
    return {
        "rows": synth,
        "synthesis": rep,
        "coverage_before": before,
        "coverage_after": after,
    }
