"""Reconcile preflight gate (ISSUE 13 layer 3): decide a snapshot swap's
fate on REPLAYED traffic before any live request sees the candidate.

PR 10's canary detects a poison config after ~0.7–1.8 s of live exposure
(BENCH_r08's measured detection latency): real requests are served wrong
answers until the guard accumulates evidence.  The pregate moves that
evidence window to zero live exposure — the candidate snapshot is replayed
against the in-process capture ring (replay/capture.py) and the verdict
diff is judged against the SAME :class:`GuardThresholds` the canary would
apply, mapped onto replay semantics:

- ``deny_delta``     → net replayed deny-rate delta ((newly-denied −
  newly-allowed) / replayed) AND the total flip rate (a change that flips
  30% of traffic each way nets zero but is still not a change to serve
  blind);
- ``config_deny_delta`` / ``allow_collapse_ratio`` → per-config
  newly-denied rate, per-config TOTAL flip rate (a config-confined mass
  deny→allow loosening lowers every deny-side rate and would otherwise
  sail through), and allow-collapse over the replayed window, evaluated
  ONLY for the configs the reconcile changed (the PR 8 fingerprint diff)
  — unchanged configs share the baseline's artifacts and cannot flip;
- ``min_requests`` / ``min_config_requests`` → evidence floors: a
  near-empty capture ring yields a *skipped* preflight (recorded as such),
  never a false verdict.

A breach raises the engine's typed ``SnapshotRejected`` with the diff
attached and dumps a flight-recorder bundle (anomaly kind
``replay-pregate-breach`` with the top-N verdict-diff rows); a pass
annotates the canary phase so its guards tighten.  State machine:
docs/replay.md "Preflight gate".
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Optional, Sequence

__all__ = ["PREGATE_ANOMALY", "pregate_check", "preflight"]

# flight-recorder anomaly kind for a pregate breach (registered in
# runtime/flight_recorder.py ANOMALY_KINDS: recording it auto-dumps a
# diagnostic bundle with the verdict-diff evidence frozen inside)
PREGATE_ANOMALY = "replay-pregate-breach"


def pregate_check(report: Dict[str, Any], thresholds: Any = None,
                  changed: Optional[Iterable[str]] = None,
                  top_n: int = 10) -> Optional[Dict[str, Any]]:
    """Judge one verdict-diff report against canary guard thresholds.

    Returns the breach dict (guards, deltas, suspects, top-N diff rows) or
    None — None means EITHER a clean diff or not enough replayed evidence;
    the caller distinguishes via ``report['replayed']`` (the engine records
    a below-floor preflight as ``skipped``, not ``pass``)."""
    from ..runtime.change_safety import GuardThresholds

    th = thresholds or GuardThresholds()
    changed_set = set(changed) if changed is not None else None
    replayed = int(report.get("replayed", 0))
    if replayed < th.min_requests:
        return None
    flips = report.get("flips", {})
    nd = int(flips.get("newly_denied", 0))
    na = int(flips.get("newly_allowed", 0))
    deltas: Dict[str, float] = {
        "replay-deny-rate": round((nd - na) / replayed, 4),
        "replay-flip-rate": round((nd + na) / replayed, 4),
    }
    breached = [g for g in ("replay-deny-rate", "replay-flip-rate")
                if deltas[g] > th.deny_delta]
    suspects = []
    for name, pc in (report.get("per_config") or {}).items():
        if changed_set is not None and name not in changed_set:
            continue
        n = int(pc.get("replayed", 0))
        if n < th.min_config_requests:
            continue
        # per-config criteria: the newly-denied rate, the allow-collapse
        # ratio (both deny-side — the canary guards' semantics), AND the
        # total flip rate — a config-confined mass deny→allow flip is an
        # authorization LOOSENING the deny-side guards are structurally
        # blind to (it lowers deny rates), yet it is exactly the change a
        # preflight must not wave through unexamined
        nd = int(pc.get("newly_denied", 0))
        na = int(pc.get("newly_allowed", 0))
        delta = nd / n
        flip = (nd + na) / n
        old_allows = int(pc.get("old_allows", 0))
        collapsed = (old_allows >= th.min_config_allows
                     and pc.get("new_allows", 0)
                     < th.allow_collapse_ratio * old_allows)
        if delta > th.config_deny_delta or flip > th.config_deny_delta \
                or collapsed:
            suspects.append((name, round(max(delta, flip), 4)))
    if suspects:
        breached.append("replay-config-deny-rate")
        deltas["replay-config-deny-rate"] = max(d for _, d in suspects)
    if not breached:
        return None
    suspects.sort(key=lambda x: -x[1])
    return {
        "guards": breached,
        "deltas": deltas,
        "suspects": [name for name, _ in suspects],
        "suspect_deltas": {name: d for name, d in suspects},
        "replayed": replayed,
        "flips": dict(flips),
        "truncated": int((report.get("skipped") or {}).get("truncated", 0)),
        # the evidence a flight bundle / SnapshotRejected carries: the
        # top-N verdict-diff rows, each already (authconfig, rule)-
        # attributed by the replay's provenance fold
        "top_flips": list(report.get("by_rule", ())[:top_n]),
    }


def preflight(baseline: Any, candidate: Any,
              records: Sequence[Dict[str, Any]], thresholds: Any = None,
              changed: Optional[Iterable[str]] = None,
              time_budget_s: Optional[float] = None
              ) -> Dict[str, Any]:
    """One-call preflight: replay ``records`` old-vs-new and judge the
    diff.  Returns ``{"report": ..., "breach": breach-or-None}`` — the
    engine's ``_replay_pregate`` and the analysis CLI share this seam so
    the offline `--replay` reproduces EXACTLY the verdict the in-process
    gate reached."""
    from .replay import replay_records

    report = replay_records(baseline, candidate, records,
                            time_budget_s=time_budget_s)
    return {"report": report,
            "breach": pregate_check(report, thresholds, changed=changed)}
