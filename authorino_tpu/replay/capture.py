"""Traffic capture (ISSUE 13 layer 1): an opt-in full-fidelity request log.

The PR 9 decision log samples one structured record per batch — enough to
see WHAT the engine decided, useless for re-deciding.  This module extends
that sampling seam with the raw request tuple (authconfig + the full
authorization JSON), so a captured window can be *replayed* offline against
a candidate snapshot (replay/replay.py) or in-process by the reconcile
pregate (replay/pregate.py).

Design constraints (docs/replay.md):

- **zero-cost when off**: the engine's per-batch hook is one attribute
  check (``CAPTURE.enabled``); nothing else runs;
- **never on the batch-cut hot path**: ``offer()`` only appends a raw
  tuple to a bounded queue (drop-and-count on overflow — capture loss is
  an accounted event, never backpressure).  JSON encoding, byte
  accounting, ring eviction and segment persistence all happen on the
  capture log's OWN daemon drain thread;
- **bounded by bytes, not records**: requests vary wildly in size, so the
  in-memory ring evicts oldest-first against ``--capture-log-size-mb`` of
  ENCODED bytes, and the on-disk segment directory is pruned to the same
  budget.  A record cap would let one fat-header tenant blow the memory
  bound;
- **sampled**: ``--capture-sample N`` keeps 1-in-N decisions (per-batch
  stride, same family as the PR 9 head sampler but returning every fire
  point inside the batch, not just the head);
- **readable offline**: segments are pickle-free checksummed containers in
  the PR 8 serialize style (MAGIC + JSON header + JSON-lines payload +
  sha256 trailer).  A version- or schema-skewed segment raises the typed
  :class:`CaptureFormatError` instead of misparsing.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils import metrics as metrics_mod
from ..utils.atomicio import atomic_write_bytes

__all__ = ["CAPTURE", "CaptureLog", "CaptureFormatError", "CAPTURE_SCHEMA",
           "CAPTURE_FORMAT_VERSION", "SEGMENT_SUFFIX", "write_segment",
           "read_segment", "read_capture", "encode_record"]

log = logging.getLogger("authorino_tpu.replay.capture")

# capture record schema: bumped whenever the per-record field set changes,
# so offline readers (analysis --replay, bench --replay-log) can refuse
# version-skewed logs with a typed error instead of misparsing.
# v2 (ISSUE 14): + metadata_doc_digest — the combined digest of the
# prefetch cache's pinned metadata documents the decision evaluated under
# (None for configs with no pinned metadata), making metadata-dependent
# replays reproducible (docs/replay.md)
CAPTURE_SCHEMA = 2
CAPTURE_FORMAT_VERSION = 1
MAGIC = b"ATPUCAP1\x00"
_DIGEST_LEN = 32
SEGMENT_SUFFIX = ".atpucap"

# pinned record shape (tests/test_replay.py): every captured record carries
# exactly these keys
CAPTURE_FIELDS = ("schema", "t", "authconfig", "doc", "verdict",
                  "rule_index", "lane", "generation",
                  "metadata_doc_digest")


class CaptureFormatError(ValueError):
    """The blob is not a valid capture segment (bad magic, truncated,
    checksum mismatch, unsupported container version, or record-schema
    skew).  Read-time only — typed so callers distinguish 'not a capture
    log' from a replay result."""


# ---------------------------------------------------------------------------
# container: MAGIC + u64 header length + JSON header + JSON-lines payload
#            + sha256 trailer (PR 8 serialize style, no pickle anywhere)
# ---------------------------------------------------------------------------


def encode_record(rec: Dict[str, Any]) -> bytes:
    """One record → one canonical JSON line.  sort_keys makes the encoding
    deterministic, so round-trip parity is byte-testable."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":"),
                      default=str).encode("utf-8") + b"\n"


def _build_container(payload: bytes, count: int,
                     meta: Optional[Dict[str, Any]] = None) -> bytes:
    header = {
        "version": CAPTURE_FORMAT_VERSION,
        "schema": CAPTURE_SCHEMA,
        "count": int(count),
        "created_unix": time.time(),
        "meta": meta or {},
    }
    hb = json.dumps(header, sort_keys=True,
                    separators=(",", ":")).encode("utf-8")
    body = MAGIC + struct.pack("<Q", len(hb)) + hb + payload
    return body + hashlib.sha256(body).digest()


def write_segment(path: str, records: Sequence[Dict[str, Any]],
                  meta: Optional[Dict[str, Any]] = None) -> str:
    """Serialize ``records`` into one checksummed segment at ``path``
    (tmp + atomic rename — a torn write is unreachable, like the PR 8
    publisher)."""
    payload = b"".join(encode_record(r) for r in records)
    blob = _build_container(payload, len(records), meta)
    return atomic_write_bytes(path, blob, artifact="capture")


def read_segment(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """One segment file → (header, records).  Verifies magic + sha256 +
    container version + record schema BEFORE parsing any record; every
    failure is a typed :class:`CaptureFormatError` and the caller's state
    is untouched."""
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(MAGIC) + 8 + _DIGEST_LEN:
        raise CaptureFormatError(f"capture segment truncated: {path}")
    if blob[:len(MAGIC)] != MAGIC:
        raise CaptureFormatError(f"bad capture magic: {path}")
    body, digest = blob[:-_DIGEST_LEN], blob[-_DIGEST_LEN:]
    if hashlib.sha256(body).digest() != digest:
        raise CaptureFormatError(
            f"capture checksum mismatch (corrupt or tampered): {path}")
    (hlen,) = struct.unpack_from("<Q", blob, len(MAGIC))
    start = len(MAGIC) + 8
    if start + hlen > len(body):
        raise CaptureFormatError(f"capture header overruns the blob: {path}")
    try:
        header = json.loads(body[start:start + hlen].decode("utf-8"))
    except Exception as e:
        raise CaptureFormatError(f"unparseable capture header ({e}): {path}")
    if header.get("version") != CAPTURE_FORMAT_VERSION:
        raise CaptureFormatError(
            f"unsupported capture container version "
            f"{header.get('version')!r} (reader supports "
            f"{CAPTURE_FORMAT_VERSION}): {path}")
    if header.get("schema") != CAPTURE_SCHEMA:
        raise CaptureFormatError(
            f"capture record schema skew: segment {header.get('schema')!r} "
            f"!= reader {CAPTURE_SCHEMA} — refusing to misparse: {path}")
    records: List[Dict[str, Any]] = []
    for line in body[start + hlen:].splitlines():
        if not line:
            continue
        try:
            records.append(json.loads(line.decode("utf-8")))
        except Exception as e:
            raise CaptureFormatError(f"malformed capture record ({e}): {path}")
    return header, records


def read_capture(source: str) -> List[Dict[str, Any]]:
    """A segment file OR a capture directory → every record, oldest segment
    first (segment names sort chronologically: capture-<ms>-<seq>)."""
    if os.path.isdir(source):
        names = sorted(n for n in os.listdir(source)
                       if n.endswith(SEGMENT_SUFFIX))
        if not names:
            raise CaptureFormatError(
                f"no *{SEGMENT_SUFFIX} segments in {source}")
        out: List[Dict[str, Any]] = []
        for n in names:
            out.extend(read_segment(os.path.join(source, n))[1])
        return out
    return read_segment(source)[1]


# ---------------------------------------------------------------------------
# the live capture log
# ---------------------------------------------------------------------------


class CaptureLog:
    """Byte-bounded sampled request log with an offline persistence tail.

    Hot-path surface (engine `_observe_provenance`, per batch):
    ``sample_indices(n)`` → which of this batch's decisions to keep,
    ``offer(...)`` per kept decision → bounded-queue append.  Everything
    heavier — encode, byte accounting, ring eviction, segment write,
    directory pruning — runs on the drain thread."""

    def __init__(self, enabled: bool = False, size_mb: float = 64.0,
                 sample_n: int = 1, directory: Optional[str] = None,
                 segment_mb: float = 4.0, queue_max: int = 8192):
        self.enabled = bool(enabled)
        self.size_bytes = max(1, int(float(size_mb) * 1024 * 1024))
        self.sample_n = max(1, int(sample_n))
        self.directory = directory
        self.segment_bytes = max(4096, int(float(segment_mb) * 1024 * 1024))
        self.queue_max = max(16, int(queue_max))
        # raw offer queue: appended from any serving thread, drained by the
        # capture thread.  deque appends are atomic; the drop check is a
        # len() read — a racing append can momentarily overshoot by a few
        # records, never unboundedly (each offerer sees the full queue)
        self._queue: deque = deque()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # encoded ring: (nbytes, record) pairs, evicted oldest-first to the
        # byte budget.  Guarded — the drain thread appends while pregate /
        # debug readers snapshot.
        self._ring: deque = deque()
        self._ring_bytes = 0
        self._ring_lock = threading.Lock()
        # drain-side state (drain thread + flush() only, under _proc_lock)
        self._proc_lock = threading.Lock()
        self._seg_lines: List[bytes] = []
        self._seg_nbytes = 0
        self._seg_seq = 0
        # sampler state: same racy-by-design counters as the PR 9 head
        # sampler — a lost race loses a sample, never adds per-request work
        self._seen = 0
        self._next_fire = 1
        # accounting
        self.stored_total = 0
        self.dropped_total = 0
        self.evicted_total = 0
        self.encode_failures = 0
        self.segments_written = 0
        self.segments_pruned = 0

    # -- configuration -----------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  size_mb: Optional[float] = None,
                  sample_n: Optional[int] = None,
                  directory: Optional[str] = None,
                  segment_mb: Optional[float] = None) -> None:
        if size_mb is not None:
            self.size_bytes = max(1, int(float(size_mb) * 1024 * 1024))
        if sample_n is not None:
            self.sample_n = max(1, int(sample_n))
            self._next_fire = self._seen + self.sample_n
        if segment_mb is not None:
            self.segment_bytes = max(4096,
                                     int(float(segment_mb) * 1024 * 1024))
        if directory is not None:
            self.directory = directory or None
            if self.directory:
                os.makedirs(self.directory, exist_ok=True)
        if enabled is not None:
            self.enabled = bool(enabled)
        if self.enabled:
            self._ensure_thread()

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        t = threading.Thread(target=self._drain_loop, name="atpu-capture",
                             daemon=True)
        self._thread = t
        t.start()

    # -- hot path (serving threads) ----------------------------------------

    def sample_indices(self, n_decisions: int) -> Iterable[int]:
        """Which of this batch's ``n_decisions`` decisions the 1-in-N
        sampler keeps (indices into the batch).  sample_n=1 keeps every
        decision; otherwise the stride sampler fires at every multiple —
        O(kept) per batch, not O(batch)."""
        if not self.enabled or n_decisions <= 0:
            return ()
        if self.sample_n <= 1:
            self._seen += n_decisions
            return range(n_decisions)
        start = self._seen
        self._seen = end = start + n_decisions
        out: List[int] = []
        nf = self._next_fire
        while nf <= end:
            out.append(nf - start - 1)
            nf += self.sample_n
        self._next_fire = nf
        return out

    def offer(self, authconfig: str, doc: Any, rule_index: int, lane: str,
              generation: Any, t: Optional[float] = None,
              metadata_doc_digest: Optional[str] = None) -> None:
        """Queue one sampled decision for capture.  Bounded queue,
        drop-and-count on overflow — the serving path never blocks on and
        never pays for capture encoding.  ``metadata_doc_digest`` pins
        which prefetched metadata documents the decision evaluated under
        (ISSUE 14: replays of metadata-dependent configs are reproducible
        and digest-checkable)."""
        if not self.enabled:
            return
        if len(self._queue) >= self.queue_max:
            self.dropped_total += 1
            metrics_mod.capture_records.labels("dropped").inc()
            return
        self._queue.append((t if t is not None else time.time(),
                            authconfig, doc, int(rule_index), lane,
                            generation, metadata_doc_digest))
        self._wake.set()

    # -- drain thread ------------------------------------------------------

    def _drain_loop(self) -> None:
        while True:
            self._wake.wait(timeout=0.05)
            self._wake.clear()
            try:
                self._process_queue()
            except Exception:
                log.exception("capture drain failed (serving unaffected)")

    def _process_queue(self) -> None:
        with self._proc_lock:
            while True:
                try:
                    item = self._queue.popleft()
                except IndexError:
                    break
                self._ingest(item)

    def _ingest(self, item: Tuple) -> None:
        t, authconfig, doc, rule_index, lane, generation, md_digest = item
        rec = {
            "schema": CAPTURE_SCHEMA,
            "t": t,
            "authconfig": authconfig,
            "doc": doc,
            "verdict": "allow" if rule_index < 0 else "deny",
            "rule_index": rule_index,
            "lane": lane,
            "generation": generation,
            "metadata_doc_digest": md_digest,
        }
        try:
            enc = encode_record(rec)
        except Exception:
            self.encode_failures += 1
            self.dropped_total += 1
            metrics_mod.capture_records.labels("dropped").inc()
            return
        n = len(enc)
        with self._ring_lock:
            self._ring.append((n, rec))
            self._ring_bytes += n
            while self._ring_bytes > self.size_bytes and len(self._ring) > 1:
                en, _ = self._ring.popleft()
                self._ring_bytes -= en
                self.evicted_total += 1
        self.stored_total += 1
        metrics_mod.capture_records.labels("stored").inc()
        if self.directory:
            self._seg_lines.append(enc)
            self._seg_nbytes += n
            if self._seg_nbytes >= self.segment_bytes:
                self._write_segment()

    def _write_segment(self) -> None:
        if not self._seg_lines or not self.directory:
            return
        payload = b"".join(self._seg_lines)
        count = len(self._seg_lines)
        self._seg_lines = []
        self._seg_nbytes = 0
        self._seg_seq += 1
        name = "capture-%013d-%06d%s" % (int(time.time() * 1e3),
                                         self._seg_seq, SEGMENT_SUFFIX)
        path = os.path.join(self.directory, name)
        try:
            blob = _build_container(payload, count,
                                    meta={"sample_n": self.sample_n})
            # shared atomic writer (ISSUE 20): the old inline tmp+replace
            # here skipped fsync, so a crash mid-rotation could surface a
            # truncated segment under a fully-renamed name
            atomic_write_bytes(path, blob, artifact="capture")
            self.segments_written += 1
            self._prune_dir()
        except Exception:
            log.exception("capture segment write failed (ring unaffected)")

    def _prune_dir(self) -> None:
        """Byte-bound the segment directory to the SAME budget as the ring:
        oldest segments go first.  Best-effort — pruning must never lose
        the segment just written."""
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.endswith(SEGMENT_SUFFIX))
            sizes = {n: os.path.getsize(os.path.join(self.directory, n))
                     for n in names}
            total = sum(sizes.values())
            for n in names[:-1]:  # never prune the newest
                if total <= self.size_bytes:
                    break
                try:
                    os.unlink(os.path.join(self.directory, n))
                    total -= sizes[n]
                    self.segments_pruned += 1
                except OSError:
                    pass
        except OSError:
            pass

    # -- readers -----------------------------------------------------------

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Drain the queue inline and force the pending segment buffer to
        disk.  Tests, bench artifact finalization and orderly shutdown —
        the serving path never calls this."""
        deadline = time.monotonic() + timeout_s
        while self._queue and time.monotonic() < deadline:
            self._process_queue()
        self._process_queue()
        with self._proc_lock:
            self._write_segment()
        return not self._queue

    def ring_records(self) -> List[Dict[str, Any]]:
        """Snapshot of the in-memory ring, oldest first — the pregate's
        replay corpus."""
        with self._ring_lock:
            return [rec for _, rec in self._ring]

    def clear(self) -> None:
        """Drop ring + queue + sampler state (tests between scenarios)."""
        with self._ring_lock:
            self._ring.clear()
            self._ring_bytes = 0
        self._queue.clear()
        with self._proc_lock:
            self._seg_lines = []
            self._seg_nbytes = 0
        self._seen = 0
        self._next_fire = 1

    def to_json(self) -> Dict[str, Any]:
        with self._ring_lock:
            ring_n, ring_bytes = len(self._ring), self._ring_bytes
        return {
            "enabled": self.enabled,
            "schema": CAPTURE_SCHEMA,
            "size_bytes": self.size_bytes,
            "sample_n": self.sample_n,
            "directory": self.directory,
            "ring_records": ring_n,
            "ring_bytes": ring_bytes,
            "queue_depth": len(self._queue),
            "stored_total": self.stored_total,
            "dropped_total": self.dropped_total,
            "evicted_total": self.evicted_total,
            "encode_failures": self.encode_failures,
            "segments_written": self.segments_written,
            "segments_pruned": self.segments_pruned,
        }


# one capture log per process (both lanes sample into it; the pregate and
# /debug/replay read it) — opt-in: disabled until configure(enabled=True)
CAPTURE = CaptureLog()
