"""Traffic replay & what-if preflight (ISSUE 13, docs/replay.md).

Four layers composing PR 8 (loadable snapshots), PR 9 (decision/attribution
provenance) and PR 10 (canary guards) into "test a policy change against
yesterday's traffic with zero live exposure":

- :mod:`.capture`   — opt-in byte-bounded sampled request log (in-memory
  ring + checksummed on-disk segments), fed off the hot path;
- :mod:`.replay`    — offline verdict-diff: re-decide captured requests
  against two snapshots through the exact host oracle, flips grouped by
  (authconfig, rule) via provenance attribution;
- :mod:`.pregate`   — the reconcile preflight gate: a diff breaching the
  canary guard thresholds rejects the swap BEFORE the canary window;
- :mod:`.bench_load` — captured arrivals as bench.py's open-loop
  timetable (``--replay-log``).

Only the import-light capture surface is re-exported here; the replay /
pregate layers import the host oracle (and with it jax) — pull them in
explicitly: ``from authorino_tpu.replay.replay import replay_records``.
"""

from .capture import (  # noqa: F401
    CAPTURE,
    CAPTURE_SCHEMA,
    CaptureFormatError,
    CaptureLog,
    read_capture,
    read_segment,
    write_segment,
)

__all__ = ["CAPTURE", "CAPTURE_SCHEMA", "CaptureFormatError", "CaptureLog",
           "read_capture", "read_segment", "write_segment"]
