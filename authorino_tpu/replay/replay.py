"""Offline what-if replay (ISSUE 13 layer 2): re-decide captured traffic
against two snapshots and diff the verdicts.

EXTree (PAPERS.md) argues the useful explanation of a policy CHANGE is the
diff — which requests flip, and why — not a pile of individual verdicts;
Cedar frames change analysis as a first-class operation, not a production
experiment.  Here the oracle is the host expression evaluator
(``models.policy_model.host_results``), the same exact reference every
lane's output is certified against (PR 6), so a replay verdict IS the
serving verdict by construction — no kernel, no device, no sampling error.

``replay_records(old, new, records)`` produces the verdict-diff report:
flips split by direction (allow→deny = *newly-denied*, deny→allow =
*newly-allowed*), grouped by (authconfig, rule) through the PR 9
attribution columns on BOTH sides — a newly-denied request is attributed
to the NEW side's firing rule (the rule that now denies it), a
newly-allowed one to the OLD side's (the rule that used to).  Consumed by
``analysis --replay OLD NEW --log DIR`` (offline), the reconcile pregate
(replay/pregate.py) and tests.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["REPLAY_SCHEMA", "SnapshotOracle", "replay_records",
           "replay_platform", "format_replay_report"]

# verdict-diff report schema (stamped into every report/artifact so
# downstream readers can detect skew, matching the capture container)
REPLAY_SCHEMA = 1


def replay_platform() -> str:
    """The platform stamp replay artifacts carry (ISSUE 13 satellite: the
    same honest-labeling rule PR 7 applied to closed-loop rows).  Replay
    decides on the HOST oracle — never a device — so the stamp says so;
    jax backends are deliberately not initialized here (__version__ is a
    plain attribute, jax.devices() would boot a backend)."""
    try:
        import jax

        return f"host-oracle (jax {jax.__version__})"
    except Exception:  # pragma: no cover - jax is baked into the image
        return "host-oracle"


class SnapshotOracle:
    """Uniform exact-decision view over one compiled snapshot: a bare
    ``CompiledPolicy``, a PR 8 ``LoadedSnapshot`` (offline blob), an engine
    ``_Snapshot`` (live pregate), or a mesh-sharded corpus — one ``decide``
    seam for the replay loop, one ``rule_source`` seam for attribution."""

    def __init__(self, policy: Any = None, sharded: Any = None,
                 generation: Any = None):
        self.policy = policy
        self.sharded = sharded
        self.generation = generation
        self._sources_cache: Dict[int, List[List[str]]] = {}

    @classmethod
    def of(cls, obj: Any) -> "SnapshotOracle":
        policy = getattr(obj, "policy", None)
        sharded = getattr(obj, "sharded", None)
        if policy is None and sharded is None:
            policy = obj  # a bare CompiledPolicy
        if policy is None and sharded is None:
            raise ValueError(f"no compiled corpus on {type(obj).__name__}")
        return cls(policy=policy, sharded=sharded,
                   generation=getattr(obj, "generation", None))

    # -- lookups -----------------------------------------------------------

    def has(self, name: str) -> bool:
        if self.sharded is not None:
            return name in self.sharded.locator
        return name in self.policy.config_ids

    def names(self) -> List[str]:
        if self.sharded is not None:
            return list(self.sharded.locator)
        return list(self.policy.config_ids)

    def n_evaluators(self) -> int:
        pol = (self.sharded.shards[0] if self.sharded is not None
               else self.policy)
        return int(pol.eval_rule.shape[1])

    def _locate(self, name: str) -> Tuple[Any, int]:
        if self.sharded is not None:
            s, row = self.sharded.locator[name]
            return self.sharded.shards[s], row
        return self.policy, self.policy.config_ids[name]

    # -- deciding ----------------------------------------------------------

    def decide(self, name: str, doc: Any) -> Tuple[np.ndarray, np.ndarray]:
        """Exact host decision for one captured request: the per-evaluator
        (rule, skipped) columns — the same attribution evidence every
        serving lane folds (PR 9)."""
        from ..models.policy_model import host_results

        pol, row = self._locate(name)
        _, rule_res, skipped = host_results(pol, doc, row)
        return rule_res, skipped

    def rule_source(self, name: str, col: int) -> str:
        pol, row = self._locate(name)
        key = id(pol)
        sources = self._sources_cache.get(key)
        if sources is None:
            sources = pol.rule_sources()
            self._sources_cache[key] = sources
        per_cfg = sources[row] if 0 <= row < len(sources) else []
        return per_cfg[col] if 0 <= col < len(per_cfg) else "<padded>"


def _doc_identity(doc: Any) -> str:
    try:
        req = doc.get("request") or {}
        return "%s %s%s" % (req.get("method", "?"), req.get("host", ""),
                            req.get("path") or req.get("url_path", ""))
    except Exception:
        return "<opaque>"


def replay_records(old: Any, new: Any, records: Sequence[Dict[str, Any]],
                   *, time_budget_s: Optional[float] = None,
                   max_examples: int = 3,
                   metadata_docs: Optional[Dict[str, Dict[str, Any]]] = None,
                   ) -> Dict[str, Any]:
    """Replay every captured record through BOTH snapshots' host oracles
    and diff the verdicts.  ``old``/``new`` accept anything
    :meth:`SnapshotOracle.of` does.

    ``time_budget_s`` bounds the wall-clock (the pregate's reconcile-path
    budget): replay stops at the budget and the report says how many
    records were NOT evaluated (``skipped.truncated`` — no silent caps, a
    truncated preflight must read as partial evidence, not full
    coverage).

    ``metadata_docs`` un-blinds metadata-dependent configs (ISSUE 14):
    {config_id: {metadata_name: document}} — the prefetch cache's pinned
    documents (MetadataPrefetcher.export_docs / --metadata-docs FILE).
    Records of listed configs re-decide with ``auth.metadata`` overridden
    by the pinned documents on BOTH sides (a consistent what-if under
    today's metadata), counted in ``metadata.substituted``; records whose
    captured ``metadata_doc_digest`` disagrees with the pinned set are
    additionally counted in ``metadata.digest_mismatches`` (the capture
    window saw different documents — verdicts may differ from what was
    served, by design of the what-if)."""
    from ..relations.prefetch import doc_digest as _md_digest
    from ..ops.pattern_eval import firing_columns
    from ..runtime.provenance import rule_label

    old_o = old if isinstance(old, SnapshotOracle) else SnapshotOracle.of(old)
    new_o = new if isinstance(new, SnapshotOracle) else SnapshotOracle.of(new)
    t0 = time.monotonic()
    md_substituted = md_mismatch = 0
    pinned_digest: Dict[str, str] = {}
    if metadata_docs:
        for cfg, docs in metadata_docs.items():
            parts = sorted((n, _md_digest(d)) for n, d in docs.items())
            pinned_digest[cfg] = hashlib.sha256(
                repr(parts).encode()).hexdigest()

    kept: List[Dict[str, Any]] = []
    o_rules: List[np.ndarray] = []
    o_skips: List[np.ndarray] = []
    n_rules: List[np.ndarray] = []
    n_skips: List[np.ndarray] = []
    errors = 0
    missing_old: set = set()
    missing_new: set = set()
    missing_n = 0
    truncated = 0
    E_old, E_new = old_o.n_evaluators(), new_o.n_evaluators()

    for i, rec in enumerate(records):
        if time_budget_s is not None and (i & 63) == 0 \
                and time.monotonic() - t0 > time_budget_s:
            truncated = len(records) - i
            break
        name = rec.get("authconfig")
        doc = rec.get("doc")
        if not name or doc is None:
            errors += 1
            continue
        if not old_o.has(name):
            missing_old.add(name)
            missing_n += 1
            continue
        if not new_o.has(name):
            missing_new.add(name)
            missing_n += 1
            continue
        if metadata_docs and name in metadata_docs and isinstance(doc, dict):
            # pinned-document substitution: shallow-copy the doc and its
            # auth subtree so the caller's records stay untouched (a
            # non-dict doc — corrupt/hand-built log — skips substitution
            # and takes its chances with the oracle's own error handling)
            auth = dict(doc.get("auth") or {})
            md = dict(auth.get("metadata") or {})
            md.update(metadata_docs[name])
            auth["metadata"] = md
            doc = dict(doc)
            doc["auth"] = auth
            md_substituted += 1
            cap_digest = rec.get("metadata_doc_digest")
            if cap_digest and cap_digest != pinned_digest.get(name):
                md_mismatch += 1
        try:
            ro, so = old_o.decide(name, doc)
            rn, sn = new_o.decide(name, doc)
        except Exception:
            errors += 1
            continue
        kept.append(rec)
        o_rules.append(np.asarray(ro, dtype=bool))
        o_skips.append(np.asarray(so, dtype=bool))
        n_rules.append(np.asarray(rn, dtype=bool))
        n_skips.append(np.asarray(sn, dtype=bool))

    if kept:
        fire_old = firing_columns(np.stack(o_rules), np.stack(o_skips))
        fire_new = firing_columns(np.stack(n_rules), np.stack(n_skips))
    else:
        fire_old = fire_new = np.zeros(0, dtype=np.int32)

    per_config: Dict[str, Dict[str, int]] = {}
    groups: Dict[Tuple[str, str, int], Dict[str, Any]] = {}
    newly_denied = newly_allowed = 0
    for rec, fo, fn in zip(kept, fire_old, fire_new):
        name = rec["authconfig"]
        pc = per_config.setdefault(name, {
            "replayed": 0, "newly_denied": 0, "newly_allowed": 0,
            "old_allows": 0, "new_allows": 0})
        pc["replayed"] += 1
        old_allow, new_allow = int(fo) < 0, int(fn) < 0
        pc["old_allows"] += int(old_allow)
        pc["new_allows"] += int(new_allow)
        if old_allow == new_allow:
            continue
        if new_allow:
            direction, col, side = "newly-allowed", int(fo), old_o
            newly_allowed += 1
            pc["newly_allowed"] += 1
        else:
            direction, col, side = "newly-denied", int(fn), new_o
            newly_denied += 1
            pc["newly_denied"] += 1
        key = (name, direction, col)
        g = groups.get(key)
        if g is None:
            g = groups[key] = {
                "authconfig": name,
                "direction": direction,
                "rule_index": col,
                "rule": rule_label(col, side.rule_source(name, col)),
                "count": 0,
                "examples": [],
            }
        g["count"] += 1
        if len(g["examples"]) < max_examples:
            g["examples"].append(_doc_identity(rec.get("doc")))

    by_rule = sorted(groups.values(), key=lambda g: -g["count"])
    replayed = len(kept)
    return {
        "schema": REPLAY_SCHEMA,
        "platform": replay_platform(),
        "load_model": "replay",
        "replayed": replayed,
        "flips": {
            "newly_denied": newly_denied,
            "newly_allowed": newly_allowed,
            "total": newly_denied + newly_allowed,
        },
        "flip_rate": round((newly_denied + newly_allowed) / replayed, 6)
        if replayed else 0.0,
        "by_rule": by_rule,
        "per_config": per_config,
        "skipped": {
            "missing_config": missing_n,
            "configs_missing_old": sorted(missing_old)[:32],
            "configs_missing_new": sorted(missing_new)[:32],
            "errors": errors,
            "truncated": truncated,
        },
        "old_generation": old_o.generation,
        "new_generation": new_o.generation,
        "elapsed_ms": round((time.monotonic() - t0) * 1e3, 3),
        "evaluators": {"old": E_old, "new": E_new},
        "metadata": {
            "substituted": md_substituted,
            "digest_mismatches": md_mismatch,
            "configs": sorted(metadata_docs)[:32] if metadata_docs else [],
        },
    }


def format_replay_report(report: Dict[str, Any]) -> str:
    """Human-readable verdict-diff report for the analysis CLI."""
    lines: List[str] = []
    f = report["flips"]
    lines.append(
        f"replay: {report['replayed']} record(s) re-decided "
        f"(old gen {report.get('old_generation')} → "
        f"new gen {report.get('new_generation')}, "
        f"{report['elapsed_ms']:.0f}ms, {report['platform']})")
    sk = report["skipped"]
    if sk["missing_config"] or sk["errors"] or sk["truncated"]:
        lines.append(
            f"  skipped: {sk['missing_config']} missing-config, "
            f"{sk['errors']} error(s), {sk['truncated']} past the time "
            f"budget (partial evidence)")
        for side in ("old", "new"):
            names = sk[f"configs_missing_{side}"]
            if names:
                lines.append(f"    absent in {side}: {', '.join(names)}")
    lines.append(
        f"  flips: {f['total']} ({f['newly_denied']} newly denied, "
        f"{f['newly_allowed']} newly allowed; "
        f"rate {report['flip_rate']:.4f})")
    if not report["by_rule"]:
        lines.append("  verdict-diff EMPTY: the change is behavior-"
                     "preserving over this traffic window")
    for g in report["by_rule"]:
        lines.append(
            f"  {g['direction']:<14} {g['count']:>6}  "
            f"{g['authconfig']}  rule[{g['rule_index']}] {g['rule']}")
        for ex in g["examples"]:
            lines.append(f"      e.g. {ex}")
    return "\n".join(lines)
