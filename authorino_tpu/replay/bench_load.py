"""Replayed-traffic load model for bench.py (ISSUE 13 layer 4).

The PR 7 open-loop generator schedules SYNTHETIC arrival timetables
(steady/burst/diurnal/zipf) — honest about overload, but every artifact
measures a shape someone invented.  ``bench.py --replay-log DIR`` swaps
the synthetic timetable for a CAPTURED one: the recorded inter-arrival
gaps, key skew and per-request documents of a real (or previously
benched) traffic window, replayed open-loop.  BENCH artifacts become
reproducible against recorded traffic, and the block is stamped
``load_model="replay"`` so replay numbers can never masquerade as
synthetic open-loop ones (the ROADMAP bench-reality rule).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

__all__ = ["load_timetable"]


def load_timetable(source: str, *, speed: float = 1.0,
                   limit: Optional[int] = None
                   ) -> Tuple[List[float], List[str], List[Any],
                              Dict[str, Any]]:
    """Capture dir/segment → (offsets, authconfig names, docs, meta).

    Offsets are seconds from the first captured record, divided by
    ``speed`` (2.0 = replay twice as fast — time-compression for long
    capture windows); records sort by capture timestamp so an
    out-of-order multi-segment log still replays causally.  ``limit``
    truncates AFTER sorting (the head of the window, not a random
    subset)."""
    from .capture import CaptureFormatError, read_capture

    records = [r for r in read_capture(source)
               if r.get("doc") is not None and r.get("authconfig")]
    if not records:
        raise CaptureFormatError(
            f"capture log {source!r} holds no replayable records")
    records.sort(key=lambda r: float(r.get("t", 0.0)))
    if limit:
        records = records[:int(limit)]
    speed = max(float(speed), 1e-9)
    t0 = float(records[0].get("t", 0.0))
    offsets = [max(0.0, (float(r.get("t", 0.0)) - t0) / speed)
               for r in records]
    names = [str(r["authconfig"]) for r in records]
    docs = [r["doc"] for r in records]
    span = offsets[-1] if offsets else 0.0
    meta = {
        "source": str(source),
        "records": len(records),
        "span_s": round(span, 3),
        "speed": speed,
        "offered_rps": round(len(records) / span, 1) if span > 0 else None,
        "captured_deny_rate": round(
            sum(1 for r in records if r.get("verdict") == "deny")
            / len(records), 4),
    }
    return offsets, names, docs, meta
