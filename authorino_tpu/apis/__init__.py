"""AuthConfig API shapes: v1beta1 (storage) ↔ v1beta2 (user-facing) conversion."""

from .convert import to_v1beta1, to_v1beta2  # noqa: F401
